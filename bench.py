"""Benchmark harness — runs on the real TPU chip.

Prints one JSON line per row, with the PRIMARY row last (the driver
records the last line; it carries the full row table under "rows").

Rows (BASELINE.json milestone configs scaled to one chip):
  1. gpt2_350m_zero1   — end-to-end train_batch tokens/s (primary; the
     north star is tokens/sec/chip parity with A100+NCCL ≈ 35k)
  2. llama8b_class_zero3 — Llama-3-8B-geometry layers (full hidden 4096 /
     GQA 32:8 / swiglu 14336) under ZeRO-3 specs, depth scaled to fit one
     chip; tokens/s + MFU
  3. peak_params — largest GPT-class model trained (fwd+bwd+adam) on one
     chip; the top ladder entries use ZeRO-Infinity layer streaming +
     host optimizer state; metric = parameter count
  4. v2_decode — inference v2 fused decode loop tokens/s (paged KV), vs
     the reference FastGen's A100 llama-13B ~52 tok/s/seq class figure
  5. serve_load — the async serving layer (deepspeed_tpu/serving) under
     an open-loop arrival process: tokens/s, p50/p95 TTFT, preemption
     rate; vs_baseline = served tokens/s / one-shot batch generate()
  6. serve_load_multi — the multi-replica tier: a Router over 2 replicas
     on disjoint mesh slices, shared-system-prompt workload with and
     without the paged prefix cache; aggregate tokens/s + p95 TTFT +
     prefix_hit_rate + prefill_tokens_saved
  7. gpt2_350m_autosched — overlap-driven step scheduling: the same
     model/data under the static schedule vs the probe→decide→pin
     autotuned one (autotuning/overlap_scheduler.py); mfu_static vs
     mfu_tuned + the ScheduleDecision evidence that picked the schedule
  8. serve_disagg — disaggregated prefill/decode tiers + speculative
     decoding vs the homogeneous router at a fixed chip budget, under
     the mixed scenario load generator (burst / session_heavy /
     shared_system_prompt / long_prompt_short_decode)

Pass --smoke for a tiny-shape CPU plumbing check (no numbers of record).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SMOKE = "--smoke" in sys.argv
if SMOKE:
    # smoke mode is a CPU plumbing check — pin the platform BEFORE any
    # backend touch, or a down TPU tunnel blocks the run forever (env
    # vars alone can't override the axon plugin's jax.config pin)
    import os

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    import jax

    jax.config.update("jax_platforms", "cpu")


def _sync(x) -> float:
    # float() is a hard host sync — block_until_ready returns early under
    # the axon relay, so sync via value fetch.
    return float(np.asarray(x))


def _reset_topology():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


def _time_train(engine, batch, steps, warmup=3):
    for _ in range(warmup):
        loss = engine.train_batch(batch)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    _sync(loss)
    return time.perf_counter() - t0


def _telemetry_jsonl(name: str) -> str:
    """Per-row StepRecord log path (docs/OBSERVABILITY.md): every bench
    row leaves a machine-readable per-step trail next to its one summary
    number."""
    out_dir = os.environ.get("DSTPU_TELEMETRY_DIR", "./telemetry")
    return os.path.join(out_dir, f"{name}.jsonl")


def _trace_json(name: str) -> str:
    """Per-row Chrome trace-event export (Perfetto-viewable span trace;
    docs/OBSERVABILITY.md 'Tracing & flight recorder')."""
    out_dir = os.environ.get("DSTPU_TELEMETRY_DIR", "./telemetry")
    return os.path.join(out_dir, f"{name}.trace.json")


def _fleet_jsonl(name: str) -> str:
    """Per-row TierSnapshot log (docs/OBSERVABILITY.md 'Fleet snapshots
    & SLO ledger'): one frozen-schema JSON line per tier per sampler
    tick."""
    out_dir = os.environ.get("DSTPU_TELEMETRY_DIR", "./telemetry")
    return os.path.join(out_dir, f"{name}.fleet.jsonl")


def _run_id() -> str:
    """The row's ledger run id (telemetry/ledger.py): ONE id stamped
    through StepRecords, trace metadata, TierSnapshots, and the row's
    manifest so the warehouse can stitch them back together.  main()
    mints one per row into ``DSTPU_RUN_ID`` before the row runs (smoke
    re-exec and subprocess rows inherit it through the environment);
    direct ``--row`` invocations mint their own."""
    return os.environ.get("DSTPU_RUN_ID", "")


def _mint_run_id(name: str) -> str:
    # mirrors telemetry/ledger.py new_run_id WITHOUT importing
    # deepspeed_tpu — the non-smoke parent must stay jax-free so row
    # subprocesses grab the chip cleanly
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    return f"{name}-{stamp}-{os.getpid():x}"


def _telemetry_block(name: str) -> dict:
    return {"enabled": True, "jsonl_path": _telemetry_jsonl(name),
            "run_id": _run_id(),
            "tracing": {"enabled": True, "trace_path": _trace_json(name)}}


def _write_row_manifest(name: str, row: dict) -> dict:
    """Stamp the row with its run_id and write the RunManifest next to
    the row's artifacts (telemetry/ledger.py): the ledger's join point
    between the summary row, the per-step JSONL, the span trace, the
    fleet log, and the SLO block.  Best-effort — a manifest failure must
    never cost the row its number."""
    if "manifest" in row:       # smoke re-exec inner already wrote it
        return row
    rid = _run_id() or _mint_run_id(name)
    row.setdefault("run_id", rid)
    try:
        from deepspeed_tpu.telemetry.ledger import write_manifest

        artifacts = {k: row[k] for k in ("telemetry_jsonl", "trace_json",
                                         "fleet_jsonl", "slo", "flight_dir",
                                         "resolved_config") if k in row}
        out_dir = os.environ.get("DSTPU_TELEMETRY_DIR", "./telemetry")
        row["manifest"] = write_manifest(
            os.path.join(out_dir, f"{name}.manifest.json"),
            name, rid, artifacts, smoke=SMOKE, row=row)
    except Exception as e:      # noqa: BLE001 — diagnostics only
        row.setdefault("manifest_error", str(e)[:160])
    return row


def _span_breakdown(tracer, names) -> dict:
    """Per-phase span-time rollup for a row summary: {phase: total_ms}."""
    summary = tracer.summary()
    return {short: summary.get(name, {}).get("total_ms", 0.0)
            for short, name in names.items()}


def _resolved_config(config: dict, serving: dict = None) -> dict:
    """The row's pinned placement decisions as one machine-readable blob
    written next to the metrics (docs/PLANNER.md "Regression gate"):
    mesh, ZeRO stage, comm wire, step_schedule, offload tier — so the
    planner's known-good gate reads what a row ACTUALLY ran, not a
    hand-copied approximation.  The blob is fragment-shaped: it feeds
    ``planner.rank.plan_rank_of`` directly."""
    z = dict(config.get("zero_optimization") or {})
    out = {
        "mesh": dict(config.get("mesh") or {"data": 1}),
        "train_micro_batch_size_per_gpu": int(
            config.get("train_micro_batch_size_per_gpu", 1)),
        "gradient_accumulation_steps": int(
            config.get("gradient_accumulation_steps", 1)),
        "zero_optimization": {"stage": int(z.get("stage", 0))},
    }
    for key in ("offload_param", "offload_optimizer"):
        if z.get(key):
            out["zero_optimization"][key] = {
                k: v for k, v in dict(z[key]).items()
                if k in ("device", "chunk_bytes", "working_set_bytes")}
    for key in ("comm_quantization", "step_schedule"):
        if config.get(key):
            out[key] = json.loads(json.dumps(config[key]))
    if serving:
        out["serving"] = json.loads(json.dumps(serving))
    return out


# the known-good pinned configs at the canonical 8-chip fleet — single
# source for the planner regression gate (tests/test_planner.py asserts
# each ranks top-3 in its row-mirroring query, planner/audit.py) and for
# the 6.7B offload rung the planner must propose sight-unseen.  Shapes
# mirror the rows' real non-smoke configs above/below.
PINNED_ROW_CONFIGS = {
    "gpt2_350m": {
        "mesh": {"data": 8},
        "zero_optimization": {"stage": 1},
    },
    "gpt2_350m_commquant": {
        "mesh": {"data": 8},
        "zero_optimization": {"stage": 1},
        "comm_quantization": {"enabled": True, "grad_reduce": "int8"},
    },
    "gpt2_350m_autosched": {
        "mesh": {"data": 8},
        "zero_optimization": {"stage": 3},
        "step_schedule": {"mode": "pinned", "gather_prefetch_depth": 2,
                          "param_persistence_threshold": 100_000},
    },
    "longseq_ring": {
        "mesh": {"seq": 8},
        "zero_optimization": {"stage": 2},
    },
    # the peak_params ladder's chunked rung (_PEAK_LADDER
    # gpt2-6.7b-chunked): streamed host params + chunked NVMe optimizer
    "gpt2_6_7b_chunked": {
        "mesh": {"data": 1},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "cpu"},
            "offload_optimizer": {"device": "nvme",
                                  "working_set_bytes": 1 << 30,
                                  "chunk_bytes": 64 << 20}},
    },
}


def _fwd_flops_per_tok(model, seq):
    """Model fwd FLOPs/token: qkvo (GQA-aware) + ffn + lm_head + attn.
    Delegates to telemetry/derive.py — the single home of the MFU math,
    shared with the run ledger's rollups so bench numbers and warehouse
    re-derivations can never disagree.  Import stays function-local:
    rows pin their backend before touching deepspeed_tpu."""
    from deepspeed_tpu.telemetry.derive import fwd_flops_per_tok

    return fwd_flops_per_tok(model, seq)


def _mfu(tokens_per_sec, model, seq):
    # ×3 for fwd+bwd, against the v5e bf16 peak of 197 TFLOP/s
    # (derive.V5E_PEAK_FLOPS_PER_SEC).
    from deepspeed_tpu.telemetry.derive import mfu

    return mfu(tokens_per_sec, model, seq)


def row_gpt2_350m():
    """Primary row — unchanged config from rounds 1-2 for comparability."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    if SMOKE:
        model = get_model_config("gpt2-tiny")
        batch_size, gas, seq, steps = 2, 2, 64, 2
    else:
        # Tuned on-chip: repo Pallas flash attention + dots_flash_saveable
        # remat + gas=8. Ladder: 24.5k → 31.1k → 34.5k → 38.1k → ~40.8k.
        model = get_model_config("gpt2-350m", max_seq_len=1024)
        batch_size, gas, seq, steps = 8, 8, 1024, 8
    config = {
        "train_micro_batch_size_per_gpu": batch_size,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
        "activation_checkpointing": {"remat_policy": "dots_flash_saveable"},
        "telemetry": _telemetry_block("gpt2_350m"),
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rows = batch_size * gas
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    dt = _time_train(engine, batch, steps)
    tps = steps * rows * seq / dt
    span_ms = _span_breakdown(engine.telemetry.tracer, {
        "ingest": "train.data_ingest", "dispatch": "train.dispatch",
        "sync": "train.sync"})
    engine.destroy()
    _reset_topology()
    # Baseline: GPT-2 350M-class on one A100, eager torch+DeepSpeed ZeRO-1,
    # ≈35k tokens/s (bf16, seq 1024): A100 312 TFLOPs at ~40% MFU.
    return {
        "metric": "gpt2_350m_zero1_train_tokens_per_sec_per_chip",
        "value": round(tps, 1), "unit": "tokens/s",
        "vs_baseline": round(tps / 35_000.0, 3),
        "mfu": round(_mfu(tps, model, seq), 3),
        "telemetry_jsonl": _telemetry_jsonl("gpt2_350m"),
        "trace_json": _trace_json("gpt2_350m"),
        "span_ms": span_ms,
        "resolved_config": _resolved_config(config),
    }


def _commquant_once(wire: str, steps: int):
    """One comm-quant training run: explicit quantized DP grad reduce with
    ``wire`` on the wire (comm/quantized.py), fixed data, returns
    (tokens/s/chip, per-step losses, grad-reduce wire bytes)."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.comm.quantized import QUANT_COMM_OPS
    from deepspeed_tpu.models import get_model_config

    n = jax.device_count()
    if SMOKE:
        model = get_model_config("gpt2-tiny", num_layers=2)
        batch_size, gas, seq, run_steps = 1, 2, 32, max(3, steps)
    else:
        model = get_model_config("gpt2-350m", max_seq_len=1024)
        batch_size, gas, seq, run_steps = 8, 8, 1024, steps
    name = f"gpt2_350m_commquant_{wire}"
    config = {
        "train_micro_batch_size_per_gpu": batch_size,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": not SMOKE},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "mesh": {"data": n},
        "comm_quantization": {"enabled": True, "grad_reduce": wire},
        "steps_per_print": 10_000,
        "activation_checkpointing": {"remat_policy": "dots_flash_saveable"},
        "telemetry": _telemetry_block(name),
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    assert engine._comm_quant is not None, "explicit reduce path not active"
    rows = batch_size * gas * engine.topology.dp_size
    rng = np.random.default_rng(0)  # IDENTICAL data across wire dtypes
    ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1),
                       dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    losses = [_sync(engine.train_batch(batch)) for _ in range(run_steps)]
    # the loss loop above compiled + warmed the step; warmup=1 re-syncs
    dt = _time_train(engine, batch, run_steps, warmup=1)
    comm = engine._comm_delta()
    grad_bytes = sum(comm.get(op, {}).get("bytes", 0)
                     for op in QUANT_COMM_OPS)
    engine.destroy()
    _reset_topology()
    tps = run_steps * rows * seq / dt / max(1, n)
    return tps, losses, grad_bytes, _resolved_config(config)


def _commquant_body():
    """Comm-quant variant of the gpt2_350m row: the SAME model/step with
    the DP gradient reduction routed through the explicit collective path
    (comm_quantization), int8 wire vs an explicit-fp32-wire control.
    Verification rides the per-collective comm-volume telemetry: the row
    reports the measured grad-reduce byte reduction AND the N-step
    loss-curve delta vs the fp32 reduce (docs/QUANTIZED_COMM.md)."""
    steps = 3 if SMOKE else 8
    tps_q, losses_q, bytes_q, resolved = _commquant_once("int8", steps)
    tps_f, losses_f, bytes_f, _ = _commquant_once("fp32", steps)
    loss_delta = max(abs(a - b) for a, b in zip(losses_q, losses_f))
    return {
        "metric": "gpt2_350m_commquant_int8_train_tokens_per_sec_per_chip",
        "value": round(tps_q, 1), "unit": "tokens/s",
        # quantized wire vs the explicit fp32-wire control (same schedule)
        "vs_baseline": round(tps_q / tps_f, 3) if tps_f else 0.0,
        "grad_reduce_bytes_fp32": int(bytes_f),
        "grad_reduce_bytes_quant": int(bytes_q),
        "bytes_reduction": round(bytes_f / bytes_q, 2) if bytes_q else 0.0,
        "loss_delta": round(loss_delta, 5),
        "loss_final_fp32": round(losses_f[-1], 5),
        "loss_final_int8": round(losses_q[-1], 5),
        "telemetry_jsonl": _telemetry_jsonl("gpt2_350m_commquant_int8"),
        "trace_json": _trace_json("gpt2_350m_commquant_int8"),
        "resolved_config": resolved,
    }


def row_gpt2_350m_commquant():
    """Quantized-collective row.  Explicit DP grad reduce needs dp > 1;
    smoke mode pins the in-process backend to ONE cpu device, so the
    smoke variant re-execs itself on a virtual 8-device CPU mesh (same
    pattern as longseq_ring)."""
    if SMOKE and "--commquant-inner" not in sys.argv:
        import os
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, __file__, "--row", "gpt2_350m_commquant",
               "--smoke", "--commquant-inner"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900, env=env)
        except subprocess.TimeoutExpired:
            return {"metric": "gpt2_350m_commquant", "error": "smoke timed out"}
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"metric": "gpt2_350m_commquant",
                "error": ("no result line; " + " | ".join(tail[-3:]))[:300]}
    return _commquant_body()


def _autosched_run(model, config, batch, steps, seq):
    """One training run for the autosched A/B → (tokens/s/chip, losses)."""
    import jax

    import deepspeed_tpu as ds

    engine, _, _, _ = ds.initialize(model=model, config=config)
    rows = next(iter(batch.values())).shape[0]
    losses = [_sync(engine.train_batch(batch)) for _ in range(steps)]
    dt = _time_train(engine, batch, steps, warmup=1)
    engine.destroy()
    _reset_topology()
    tps = steps * rows * seq / dt / max(1, jax.device_count())
    return tps, losses


def _autosched_fused_ab(model, static_cfg, batch, steps, seq):
    """Fused-vs-scheduled gather A/B → the frozen
    fused_gather_loss_delta / fused_gather_wire_bytes keys.  Both sides
    run IDENTICAL data; the fused engine's all-gather wire bytes come
    from the static census (analysis.collective_census_engine)."""
    import copy

    import deepspeed_tpu as ds
    from deepspeed_tpu.analysis.auditor import collective_census_engine

    def variant(fused):
        cfg = copy.deepcopy(static_cfg)
        cfg["zero_optimization"] = {
            **cfg.get("zero_optimization", {}),
            "param_persistence_threshold": 0}
        cfg["step_schedule"] = {"gather_prefetch_depth": 2,
                                "fused_gather_matmul": fused}
        return cfg

    engine, _, _, _ = ds.initialize(model=model, config=variant(False))
    losses_sched = [_sync(engine.train_batch(batch)) for _ in range(steps)]
    engine.destroy()
    _reset_topology()

    engine, _, _, _ = ds.initialize(model=model, config=variant(True))
    assert engine.model_config.fused_gather_matmul, \
        "fused gather-matmul gate did not engage"
    losses_fused = [_sync(engine.train_batch(batch)) for _ in range(steps)]
    census = collective_census_engine(engine)
    assert census["fused_collective"]["gather_matmul"]["present"]
    gather_bytes = int(census.get("all-gather", {}).get("wire_bytes", 0))
    engine.destroy()
    _reset_topology()
    return {
        "fused_gather_loss_delta": round(
            max(abs(a - b) for a, b in zip(losses_fused, losses_sched)),
            6),
        "fused_gather_wire_bytes": gather_bytes,
    }


def _autosched_body():
    """Overlap-driven step scheduling (autotuning/overlap_scheduler.py;
    docs/AUTOTUNING.md): the SAME model/data trained under the static
    schedule vs the probe→decide→pin autotuned one.  The probe runs k
    steps under a forced telemetry capture, the decision table picks the
    schedule from the overlap report, and the tuned run executes from
    the pinned ``step_schedule`` block — the row reports both MFUs, the
    exposed-comm evidence, and the decision(s) that fired.  On the CPU
    smoke mesh the XPlane report degrades to the software-span estimate
    (the decision loop is what's validated, not chip timings) and the
    overlap threshold is forced to 1.0 so a decision deterministically
    fires."""
    import jax

    from deepspeed_tpu.autotuning.overlap_scheduler import ensure_schedule
    from deepspeed_tpu.models import get_model_config

    n = jax.device_count()
    if SMOKE:
        model = get_model_config("gpt2-tiny", num_layers=2)
        batch_size, gas, seq, steps = 1, 2, 32, 3
        probe_steps, threshold = 2, 1.0
    else:
        model = get_model_config("gpt2-350m", max_seq_len=1024)
        batch_size, gas, seq, steps = 8, 8, 1024, 8
        probe_steps, threshold = 3, 0.5
    name = "gpt2_350m_autosched"
    # ZeRO-3: the issue's success metric is MFU on the ZeRO-3 row — the
    # stage whose param gathers the zero3_prefetch decision reschedules
    base = {
        "train_micro_batch_size_per_gpu": batch_size,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": not SMOKE},
        "zero_optimization": {"stage": 3},
        "gradient_clipping": 1.0,
        "mesh": {"data": n},
        "steps_per_print": 10_000,
        "activation_checkpointing": {"remat_policy": "dots_flash_saveable"},
        "telemetry": _telemetry_block(name),
        "step_schedule": {"mode": "probe", "probe_steps": probe_steps,
                          "overlap_threshold": threshold},
    }
    rows = batch_size * gas * n
    rng = np.random.default_rng(0)  # IDENTICAL data for probe + both runs
    ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1),
                       dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}

    static_cfg = {k: v for k, v in base.items() if k != "step_schedule"}
    tps_static, losses_s = _autosched_run(model, static_cfg, batch, steps,
                                          seq)

    tuned_cfg, decisions = ensure_schedule(model, base, batch)
    assert tuned_cfg["step_schedule"]["mode"] == "pinned"
    tps_tuned, losses_t = _autosched_run(model, tuned_cfg, batch, steps, seq)

    # fused-vs-scheduled gather A/B (the fused_gather_matmul decision
    # arm's two sides on identical data; docs/AUTOTUNING.md): scheduled
    # = prefetch-depth-2 unroll, fused = the gather-matmul MLP region
    # (ops/pallas/gather_matmul.py).  Persistence is forced off so the
    # MLP weights actually shard at smoke geometry (the 350m row's MLP
    # crosses the default threshold on its own).
    fused_ab = _autosched_fused_ab(model, static_cfg, batch, steps, seq)

    fired = sorted({d.decision for d in decisions} - {"noop"})
    ev = decisions[0].evidence
    return {
        "metric": "gpt2_350m_autosched_train_tokens_per_sec_per_chip",
        "value": round(tps_tuned, 1), "unit": "tokens/s",
        # tuned schedule vs the static control (same data, same silicon)
        "vs_baseline": round(tps_tuned / tps_static, 3) if tps_static
        else 0.0,
        "mfu_static": round(_mfu(tps_static, model, seq), 6),
        "mfu_tuned": round(_mfu(tps_tuned, model, seq), 6),
        "exposed_comm_ms": ev["exposed_comm_ms"],
        "schedule_decision": "+".join(fired) if fired else "noop",
        "overlap_fraction": ev["overlap_fraction"],
        "overlap_source": ev["overlap_source"],
        "decisions": [d.to_dict() for d in decisions],
        "loss_final_static": round(losses_s[-1], 5),
        "loss_final_tuned": round(losses_t[-1], 5),
        **fused_ab,
        "telemetry_jsonl": _telemetry_jsonl(name),
        "trace_json": _trace_json(name),
        "resolved_config": _resolved_config(tuned_cfg),
    }


def row_gpt2_350m_autosched():
    """Overlap-scheduler row.  The decision paths need dp > 1; smoke mode
    pins the in-process backend to ONE cpu device, so the smoke variant
    re-execs itself on a virtual 8-device CPU mesh (same pattern as
    gpt2_350m_commquant)."""
    if SMOKE and "--autosched-inner" not in sys.argv:
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, __file__, "--row", "gpt2_350m_autosched",
               "--smoke", "--autosched-inner"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900, env=env)
        except subprocess.TimeoutExpired:
            return {"metric": "gpt2_350m_autosched",
                    "error": "smoke timed out"}
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"metric": "gpt2_350m_autosched",
                "error": ("no result line; " + " | ".join(tail[-3:]))[:300]}
    return _autosched_body()


def row_llama8b_class_zero3():
    """Llama-3-8B geometry (hidden 4096, GQA 32:8, swiglu 14336) with depth
    and vocab scaled to one chip, ZeRO-3 sharding specs active
    (single-device: specs are trivial but the code path — fsdp param style
    + streamed update — is the 8B-on-v5e-8 configuration of BASELINE.json).

    Sizing: AdamW keeps fp32 master+m+v = 12 B/param persistent, and the
    measured program peak is ~21 B/param; one 15.75-GB v5e chip therefore
    caps this row near 750M params.  Full 128256 vocab alone is 1.05G
    params (embed+head), so the vocab is cut to 32256 and depth to 2 —
    the per-layer geometry (the thing MFU depends on) is untouched.
    Measured r04: 35,968 tok/s = 63.2% MFU."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    if SMOKE:
        # loss_tiles mirrors the real row so the ZeRO-3 + tiled-loss
        # combination smoke-compiles before the driver's on-chip run
        model = get_model_config("llama-tiny", loss_tiles=4)
        batch_size, gas, seq, steps, layers = 2, 1, 64, 2, 2
    else:
        layers = 2
        batch_size, gas, seq, steps = 8, 8, 1024, 4
        model = get_model_config("llama3-8b", num_layers=layers,
                                 vocab_size=32256, max_seq_len=seq,
                                 loss_tiles=8)
    config = {
        "train_micro_batch_size_per_gpu": batch_size,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
        "activation_checkpointing": {"remat_policy": "dots_flash_saveable"},
        "telemetry": _telemetry_block("llama8b_class_zero3"),
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rows = batch_size * gas
    rng = np.random.default_rng(1)
    ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    seq_eff = min(seq, model.max_seq_len)
    dt = _time_train(engine, batch, steps)
    tps = steps * rows * seq_eff / dt
    engine.destroy()
    _reset_topology()
    # A100 80G, Llama-class layers, ZeRO-3 bf16: ~55% MFU published for
    # well-tuned stacks ⇒ per-chip token rate for THIS depth:
    a100_tps = 0.55 * 312e12 / (3 * _fwd_flops_per_tok(model, seq_eff))
    return {
        "metric": f"llama3_8b_class_{layers}L_zero3_tokens_per_sec_per_chip",
        "value": round(tps, 1), "unit": "tokens/s",
        "vs_baseline": round(tps / a100_tps, 3),
        "mfu": round(_mfu(tps, model, seq_eff), 3),
        "telemetry_jsonl": _telemetry_jsonl("llama8b_class_zero3"),
        "trace_json": _trace_json("llama8b_class_zero3"),
        "resolved_config": _resolved_config(config),
    }


def _longseq_row(model, seed: int, label: str, steps: int = 3):
    """Shared long-context training body: one chip, seq 32k through the
    KV-blocked Pallas flash path with sequence-tiled logits+loss (ALST)
    so [B,S,V] never materialises.  flash_saveable, not
    dots_flash_saveable: at seq 32k the saved matmul outputs alone are
    ~15GB (measured r04: 21.8G > 15.75G); saving only the flash
    residuals fits with room to spare.  vs_baseline = MFU / 0.55
    (blogs/ulysses-offload long-context claim)."""
    import deepspeed_tpu as ds

    batch_size, gas = 1, 2
    seq = model.max_seq_len
    config = {
        "train_micro_batch_size_per_gpu": batch_size,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
        "activation_checkpointing": {"remat_policy": "flash_saveable"},
        "telemetry": _telemetry_block(f"longseq_{label}"),
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rows = batch_size * gas
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1),
                       dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    dt = _time_train(engine, batch, steps, warmup=2)
    tps = steps * rows * seq / dt
    engine.destroy()
    _reset_topology()
    mfu = _mfu(tps, model, seq)
    return {
        "metric": f"longseq_{seq}_{label}_train_tokens_per_sec_per_chip",
        "value": round(tps, 1), "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.55, 3),
        "mfu": round(mfu, 3),
        "telemetry_jsonl": _telemetry_jsonl(f"longseq_{label}"),
        "trace_json": _trace_json(f"longseq_{label}"),
        "resolved_config": _resolved_config(config),
    }


def row_longseq_flash():
    """Long-context row, d=64 MHA class (gpt2-350m at seq 32k): the
    config held since r03 for cross-round comparability.  d=64 heads cap
    the MXU contraction at half utilization — see row_longseq_llama for
    the like-for-like comparison against the reference claim."""
    from deepspeed_tpu.models import get_model_config

    if SMOKE:
        model = get_model_config("gpt2-tiny", max_seq_len=256, loss_tiles=4)
        return _longseq_row(model, 2, "flash", steps=2)
    model = get_model_config("gpt2-350m", max_seq_len=32768,
                             loss_tiles=32, attn_impl="pallas_flash")
    return _longseq_row(model, 2, "flash")


def row_longseq_llama():
    """Long-context row at the reference claim's model class: d=128 GQA
    llama geometry (h=2048, 16:8 heads, swiglu 8192, 6L) at seq 32k.
    The reference's 55%-MFU FPDT claim is on GPT/Llama-class models with
    128-wide heads (blogs/ulysses-offload/README.md:47-48), where the
    flash kernel runs 113.4 TF/s fwd+bwd vs 57.8 at d=64 (r04 sweep)."""
    from deepspeed_tpu.models import get_model_config

    if SMOKE:
        model = get_model_config("llama-tiny", max_seq_len=256, loss_tiles=4)
        return _longseq_row(model, 4, "llama_d128", steps=2)
    model = get_model_config(
        "llama3-8b", hidden_size=2048, num_heads=16, num_kv_heads=8,
        intermediate_size=8192, num_layers=6, vocab_size=32256,
        max_seq_len=32768, loss_tiles=32, attn_impl="pallas_flash")
    return _longseq_row(model, 4, "llama_d128")


def _ring_wire_ab():
    """Per-hop fused-vs-scheduled wire A/B (comm_quantization.
    ring_rotation; docs/RING_ATTENTION.md): int8 quantized rotation vs
    the fp32 wire.  Wire bytes are CENSUS-verified via
    analysis.collective_census_engine on twin engines (the static HLO
    parse of every collective-permute — the ratio is geometry-
    independent, so the census twins stay small), and loss parity runs
    on IDENTICAL data at a long-sequence smoke (per-position V-wire
    noise enters the loss ~1/S, so the longseq regime is where the row
    lives anyway) with fp32 compute so the delta is pure wire error."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.analysis.auditor import collective_census_engine
    from deepspeed_tpu.models import get_model_config

    def build(wire, seq):
        model = get_model_config("llama-tiny", max_seq_len=seq,
                                 seq_impl="ring",
                                 ring_placement="striped",
                                 attn_impl="xla")
        cfg = {
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 2},
            "gradient_clipping": 1.0,
            "mesh": {"seq": 4},
            "steps_per_print": 10_000,
        }
        if wire != "fp32":
            cfg["comm_quantization"] = {"enabled": True,
                                        "ring_rotation": wire}
        engine, _, _, _ = ds.initialize(model=model, config=cfg)
        return engine, model

    wire_bytes = {}
    for wire in ("fp32", "int8"):
        engine, _ = build(wire, 256)
        census = collective_census_engine(engine)
        wire_bytes[wire] = int(census.get("collective-permute",
                                          {}).get("wire_bytes", 0))
        if wire == "int8":
            fused = census["fused_collective"]["ring_rotation"]
            assert fused["present"] and fused["wire"] == "int8", fused
        engine.destroy()
        _reset_topology()

    seq, steps = 2048, 2
    losses = {}
    for wire in ("fp32", "int8"):
        engine, model = build(wire, seq)
        rows = engine.topology.dp_size
        rng = np.random.default_rng(6)  # IDENTICAL data across wires
        ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1),
                           dtype=np.int32)
        batch = {"input_ids": ids[:, :-1],
                 "labels": ids[:, 1:].astype(np.int32)}
        losses[wire] = [_sync(engine.train_batch(batch))
                        for _ in range(steps)]
        engine.destroy()
        _reset_topology()

    loss_delta = max(abs(a - b) for a, b in zip(losses["int8"],
                                                losses["fp32"]))
    return {
        "ring_wire_bytes_fp32": wire_bytes["fp32"],
        "ring_wire_bytes_quant": wire_bytes["int8"],
        "ring_wire_reduction": round(
            wire_bytes["fp32"] / wire_bytes["int8"], 2)
        if wire_bytes["int8"] else 0.0,
        "ring_loss_delta": round(loss_delta, 6),
    }


def _longseq_ring_body():
    """Ring context parallelism measured for real: llama-class geometry
    with the sequence sharded over a "seq" mesh ring — striped block
    placement (causal load balance), the Pallas flash inner block on TPU,
    ZeRO-2 composed on top (the exact composition the remat fix in
    sequence/ring.py + runtime/engine.py targets).  Reports
    tokens/s/chip; vs_baseline = MFU / 0.55 like the other longseq rows."""
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    n = jax.device_count()
    if SMOKE:
        sp = min(4, n)
        model = get_model_config("llama-tiny", max_seq_len=256,
                                 seq_impl="ring", ring_placement="striped",
                                 attn_impl="xla")
        batch_size, gas, steps, warmup = 2, 1, 2, 1
        mesh = {"seq": sp}
        # route the ring inner block through the interpreted Pallas
        # kernels so the smoke run exercises the FUSED fwd+bwd ring path
        # (on TPU _kernel_enabled() selects it natively)
        import importlib

        importlib.import_module(
            "deepspeed_tpu.ops.pallas.flash_mha").INTERPRET = True
    else:
        # d=128 GQA llama geometry (the longseq_llama row's model) with the
        # 32k sequence sharded over every chip in one ring
        sp = n
        model = get_model_config(
            "llama3-8b", hidden_size=2048, num_heads=16, num_kv_heads=8,
            intermediate_size=8192, num_layers=6, vocab_size=32256,
            max_seq_len=32768, loss_tiles=32, seq_impl="ring",
            ring_placement="striped", attn_impl="pallas_flash")
        batch_size, gas, steps, warmup = 1, 2, 3, 2
        mesh = {"seq": sp}
    config = {
        "train_micro_batch_size_per_gpu": batch_size,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "gradient_clipping": 1.0,
        "mesh": mesh,
        "steps_per_print": 10_000,
        "telemetry": _telemetry_block("longseq_ring"),
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    seq = model.max_seq_len
    dp = engine.topology.dp_size
    rows = batch_size * dp * gas
    rng = np.random.default_rng(6)
    ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1),
                       dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}
    dt = _time_train(engine, batch, steps, warmup=warmup)
    tps_chip = steps * rows * seq / dt / max(1, n)
    engine.destroy()
    _reset_topology()
    mfu = _mfu(tps_chip, model, seq)
    from deepspeed_tpu.sequence.ring import _kernel_enabled

    ring_bwd = "fused" if _kernel_enabled() else "xla"
    # quantize-into-ppermute A/B (after the main engine is torn down —
    # the A/B builds its own twins); the XLA wire codec is gate-
    # independent, so drop the smoke's interpreter flag first: the
    # interpreted Pallas kernels at the A/B's 2048-seq loss run would
    # crawl, and the wire bytes/parity they'd measure are identical
    if SMOKE:
        import importlib

        importlib.import_module(
            "deepspeed_tpu.ops.pallas.flash_mha").INTERPRET = False
    wire_ab = _ring_wire_ab()
    return {
        "metric": f"longseq_{seq}_ring_sp{sp}_train_tokens_per_sec_per_chip",
        "value": round(tps_chip, 1), "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.55, 3),
        "mfu": round(mfu, 3),
        "placement": "striped",
        "ring_backward": ring_bwd,
        **wire_ab,
        "telemetry_jsonl": _telemetry_jsonl("longseq_ring"),
        "trace_json": _trace_json("longseq_ring"),
        "resolved_config": _resolved_config(config),
    }


def row_longseq_ring():
    """Ring-attention long-context row.  The ring needs sp > 1; smoke mode
    pins the in-process backend to ONE cpu device, so the smoke variant
    re-execs itself on a virtual 8-device CPU mesh (same pattern as the
    driver's row isolation)."""
    if SMOKE and "--ring-inner" not in sys.argv:
        import os
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, __file__, "--row", "longseq_ring",
               "--smoke", "--ring-inner"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900, env=env)
        except subprocess.TimeoutExpired:
            return {"metric": "longseq_ring", "error": "smoke timed out"}
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"metric": "longseq_ring",
                "error": ("no result line; " + " | ".join(tail[-3:]))[:300]}
    return _longseq_ring_body()


# Peak-params ladder: (name, base preset, model overrides, zero_config).
# Big entries lean on the framework's own scale machinery — ZeRO-Infinity
# layer streaming (offload_param cpu: layer weights live host-side,
# streamed through the compiled scan) + host optimizer state — because
# plain AdamW is 12 B/param of persistent HBM (so one bare 15.75-GB v5e
# chip caps near 750M params).  This is a fits-and-trains metric (one
# finite step), not throughput, so host-transfer latency is acceptable.
# entries: (name, base config, overrides, zero config, subprocess timeout).
# NVMe rungs put the fp32 masters+moments (and the streamed param
# partition) on DISK via NVMeOptimizerSwapper + pipelined reads — the
# repo's ZeRO-Infinity tier (ref swap_tensor/partitioned_optimizer_
# swapper.py:27) — so host RAM stops being the wall that killed the
# 4B/6.7B cpu rungs in r04 (RESOURCE_EXHAUSTED on ~80GB hosts).
_PEAK_LADDER = [
    ("gpt2-8b-nvme", "gpt2-1.3b",
     dict(hidden_size=4096, intermediate_size=16384, num_layers=40,
          num_heads=32, max_seq_len=512),
     {"stage": 3, "offload_param": {"device": "nvme"},
      "offload_optimizer": {"device": "nvme"}}, 1500.0),
    # the 6.7B chunked rung: streamed host params (offload_param cpu) +
    # the chunked host Adam with its masters+moments on DISK
    # (offload_optimizer nvme + working_set_bytes) — host RAM holds only
    # the streamed param partition and O(chunk) optimizer working set,
    # so the ~80GB host that killed the r04 cpu rung suffices
    ("gpt2-6.7b-chunked", "gpt2-1.3b",
     dict(hidden_size=4096, intermediate_size=16384, num_layers=32,
          num_heads=32, max_seq_len=512),
     {"stage": 3, "offload_param": {"device": "cpu"},
      "offload_optimizer": {"device": "nvme",
                            "working_set_bytes": 1 << 30,
                            "chunk_bytes": 64 << 20}}, 1500.0),
    ("gpt2-6.7b-nvme", "gpt2-1.3b",
     dict(hidden_size=4096, intermediate_size=16384, num_layers=32,
          num_heads=32, max_seq_len=512),
     {"stage": 3, "offload_param": {"device": "nvme"},
      "offload_optimizer": {"device": "nvme"}}, 1200.0),
    ("gpt2-4b-nvme", "gpt2-1.3b",
     dict(hidden_size=3072, intermediate_size=12288, num_layers=36,
          num_heads=24, max_seq_len=512),
     {"stage": 3, "offload_param": {"device": "nvme"},
      "offload_optimizer": {"device": "nvme"}}, 900.0),
    # cpu (host-RAM) rungs: 6.7B needs ~120GB of remote-host RAM for the
    # fp32 masters+moments (observed r04: compiles and streams, dies
    # RESOURCE_EXHAUSTED at runtime) — the 4B rung fits a ~80GB host
    # cpu-chunked: masters stay host-RESIDENT but the step runs over
    # 64MB chunks with double-buffered d2h/h2d, so transfer working set
    # is O(chunk) and the host Adam overlaps the streams
    ("gpt2-4b-stream", "gpt2-1.3b",
     dict(hidden_size=3072, intermediate_size=12288, num_layers=36,
          num_heads=24, max_seq_len=512),
     {"stage": 3, "offload_param": {"device": "cpu"},
      "offload_optimizer": {"device": "cpu",
                            "working_set_bytes": 8 << 30,
                            "chunk_bytes": 64 << 20}}, 700.0),
    ("gpt2-2.7b-stream", "gpt2-1.3b",
     dict(hidden_size=2560, intermediate_size=10240, num_layers=32,
          num_heads=32, max_seq_len=512),
     {"stage": 3, "offload_param": {"device": "cpu"},
      "offload_optimizer": {"device": "cpu"}}, 600.0),
    ("gpt2-1.3b-offload", "gpt2-1.3b", dict(max_seq_len=512),
     {"stage": 2, "offload_optimizer": {"device": "cpu"}}, 600.0),
    ("gpt2-774m", "gpt2-350m",
     dict(hidden_size=1600, num_layers=24, num_heads=20, max_seq_len=512),
     {"stage": 0}, 600.0),
]


def _host_ram_bytes() -> int:
    """Host RAM — the budget cpu-offloaded classes must fit (the
    offload rungs die in HOST RESOURCE_EXHAUSTED — r04).  Priced against
    MemAvailable (what the kernel can actually hand out) minus a 10%
    safety margin, NOT MemTotal: on a busy host the page cache and other
    tenants hold a big slice of MemTotal, and a rung admitted against
    the total dies RESOURCE_EXHAUSTED mid-ladder anyway.  Falls back to
    MemTotal, then 16 GiB."""
    total = avail = 0
    try:
        with open("/proc/meminfo", "r", encoding="utf-8") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                elif line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
    except OSError:
        pass
    if avail:
        return int(avail * 0.9)
    return total or (16 << 30)


def _host_peak_bytes() -> int:
    """Measured host high-water mark (VmHWM) of THIS process — the
    measured counterpart the ladder records next to the predictor's
    `predicted_peak_bytes` (read via the CPU accelerator's /proc
    watermark so bench and telemetry agree on the source)."""
    try:
        from deepspeed_tpu.accelerator.cpu_accelerator import \
            CPU_Accelerator

        return int(CPU_Accelerator().memory_stats(0).get(
            "peak_bytes_in_use", 0))
    except Exception:
        return 0


def _memory_budget_bytes() -> int:
    """The budget the ladder's DEVICE-resident state must fit: device
    HBM when the accelerator reports a limit, else host RAM (the CPU
    backend's "device" memory IS host RAM)."""
    try:
        from deepspeed_tpu.accelerator import get_accelerator

        limit = int(get_accelerator().memory_stats(0).get(
            "bytes_limit", 0))
        if limit > (1 << 30):
            return limit
    except Exception:
        pass
    return _host_ram_bytes()


def _peak_rungs():
    """(name, base, overrides, zero, seq) per ladder rung.  The smoke
    ladder runs three tiny rungs so the plumbing check actually
    EXECUTES every optimizer tier — fused on-device, cpu-chunked host
    Adam, and the nvme chunk store — not just the base path."""
    if SMOKE:
        nvme_dir = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "dstpu_bench_nvme_smoke")
        return [
            ("gpt2-tiny", "gpt2-tiny", {}, {"stage": 0}, 64),
            ("gpt2-tiny-cpu-chunk", "gpt2-tiny", {},
             {"stage": 2,
              "offload_optimizer": {"device": "cpu",
                                    "working_set_bytes": 1,
                                    "chunk_bytes": 1 << 16}}, 64),
            ("gpt2-tiny-nvme-chunk", "gpt2-tiny", {},
             {"stage": 2,
              "offload_optimizer": {"device": "nvme",
                                    "nvme_path": nvme_dir,
                                    "working_set_bytes": 1,
                                    "chunk_bytes": 1 << 16}}, 64),
        ]
    return [(name, base, over, zero, 512)
            for name, base, over, zero, _ in _PEAK_LADDER]


def _ladder_predictions() -> list:
    """OOM-before-you-run gate (docs/STATIC_ANALYSIS.md): the calibrated
    analytic predictor prices every rung BEFORE anything runs, so a
    too-big rung reports why it cannot fit (dominant class + shortfall)
    instead of dying in RESOURCE_EXHAUSTED mid-ladder."""
    import jax

    from deepspeed_tpu.autotuning import (ModelInfo,
                                          load_memory_calibration,
                                          predict_fit)
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.profiling import get_model_profile

    budget = _memory_budget_bytes()
    cal = load_memory_calibration(backend=jax.default_backend())
    preds = []
    for name, base, over, zero, seq in _peak_rungs():
        model = get_model_config(base, **over)
        prof = get_model_profile(model, 1, seq)
        # offloaded classes must not be priced against the device
        # budget (they are the POINT of the offload rungs) — cpu-homed
        # state is priced against host RAM instead, nvme is unbounded
        off_p = (zero.get("offload_param") or {}).get("device")
        off_o_cfg = zero.get("offload_optimizer") or {}
        off_o = off_o_cfg.get("device")
        # chunked rungs price the O(chunk) pinned working set instead of
        # the whole fp32 state (the nvme tier's host need IS the chunk)
        chunk = (off_o_cfg.get("chunk_bytes")
                 if off_o_cfg.get("working_set_bytes") else None)
        pred = predict_fit(
            ModelInfo(num_params=prof["params"],
                      hidden_size=model.hidden_size,
                      num_layers=model.num_layers,
                      vocab_size=model.vocab_size),
            int(zero.get("stage", 0)), dp_size=1, micro_batch=1,
            seq_len=seq, hbm_bytes=budget, calibration=cal,
            offload_param=off_p, offload_optimizer=off_o,
            chunk_bytes=chunk,
            host_bytes=_host_ram_bytes()
            if ("cpu" in (off_p, off_o) or chunk) else None)
        preds.append({
            "rung": name,
            "predicted_peak_bytes": pred["predicted_peak_bytes"],
            "predicted_fit": pred["predicted_fit"],
            "dominant_class": pred["dominant_class"],
            "shortfall_bytes": pred["shortfall_bytes"],
        })
    return preds


def _peak_entry(idx: int) -> dict:
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    if SMOKE:
        name, base, over, zero, seq = _peak_rungs()[idx]
    else:
        name, base, over, zero, _ = _PEAK_LADDER[idx]
        seq = 512
    model = get_model_config(base, **over)
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": zero,
        "steps_per_print": 10_000,
        "activation_checkpointing": {"remat_policy": "nothing_saveable"},
        "telemetry": _telemetry_block("peak_params"),
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, model.vocab_size, size=(1, seq + 1),
                       dtype=np.int32)
    batch = {"input_ids": ids[:, :-1],
             "labels": ids[:, 1:].astype(np.int32)}
    loss = engine.train_batch(batch)
    if not np.isfinite(_sync(loss)):
        raise RuntimeError("non-finite loss")
    import jax

    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(engine.params))
    entry = {"name": name, "params_m": round(n_params / 1e6, 1),
             # measured VmHWM next to the predictor's number — the
             # ladder's predicted-vs-measured host story per rung
             "host_peak_bytes": _host_peak_bytes(),
             "offload_overlap_fraction":
                 getattr(engine, "_last_offload_overlap", None)}
    if SMOKE:
        # smoke runs every rung in ONE process — tear down between rungs
        # or the next engine inherits this one's mesh and swap pools
        engine.destroy()
        _reset_topology()
    return entry


def row_peak_params():
    """Largest model trained end-to-end (fwd+bwd+adam step) on one chip —
    the 'train bigger than you think' metric.  The ladder consults the
    static memory predictor FIRST (per-rung `predicted_peak_bytes` /
    `predicted_fit` — a rung predicted not to fit is skipped with its
    dominant class + shortfall recorded instead of dying in
    RESOURCE_EXHAUSTED; DSTPU_PEAK_RUN_ALL=1 overrides).  Each attempted
    entry runs in its own subprocess (an OOM-killed entry must not leak
    HBM into the next); largest that completes a finite step wins."""
    preds = _ladder_predictions()
    run_all = os.environ.get("DSTPU_PEAK_RUN_ALL") == "1"
    best = None
    best_idx = None
    if SMOKE:
        # run EVERY smoke rung (base, cpu-chunked, nvme-chunked) so the
        # plumbing check exercises all three optimizer tiers; the base
        # rung stays the reported metric for comparability
        for i in range(len(preds)):
            entry = _peak_entry(i)
            preds[i]["ran"] = True
            preds[i]["fit"] = True
            preds[i]["host_peak_bytes"] = entry["host_peak_bytes"]
            preds[i]["offload_overlap_fraction"] = \
                entry["offload_overlap_fraction"]
            if best is None:
                best = entry
                best_idx = i
    else:
        import subprocess

        for i in range(len(_PEAK_LADDER)):
            preds[i]["ran"] = False
            preds[i]["fit"] = None
            if not preds[i]["predicted_fit"] and not run_all:
                continue   # the predictor already explains why
            preds[i]["ran"] = True
            try:
                proc = subprocess.run(
                    [sys.executable, __file__, "--peak-entry", str(i)],
                    capture_output=True, text=True,
                    timeout=_PEAK_LADDER[i][4])
            except subprocess.TimeoutExpired:
                preds[i]["fit"] = False
                continue
            for line in reversed(proc.stdout.strip().splitlines()):
                if line.startswith("{") and "params_m" in line:
                    best = json.loads(line)
                    break
            preds[i]["fit"] = best is not None
            if best:
                preds[i]["host_peak_bytes"] = best.get("host_peak_bytes")
                preds[i]["offload_overlap_fraction"] = \
                    best.get("offload_overlap_fraction")
                best_idx = i
                break
    if best is None:
        raise RuntimeError("no ladder entry fit")
    # A100-80G fits ~1.3B params trained in fp32-master Adam without
    # offload (16 bytes/param ≈ 21GB + activations); the reference's
    # ZeRO-Offload headline is 13B on one V100-32G — scale by HBM
    # (v5e 16GB → 6.5B-class) for the offload-assisted bar.
    return {
        "metric": "peak_params_trained_one_chip",
        "value": best["params_m"], "unit": "Mparams",
        "vs_baseline": round(best["params_m"] / 6500.0, 3),
        "model": best["name"],
        "predicted_peak_bytes": preds[best_idx]["predicted_peak_bytes"],
        "predicted_fit": preds[best_idx]["predicted_fit"],
        "host_peak_bytes": best.get("host_peak_bytes"),
        "offload_overlap_fraction": best.get("offload_overlap_fraction"),
        "ladder": preds,
        "telemetry_jsonl": _telemetry_jsonl("peak_params"),
        "trace_json": _trace_json("peak_params"),
        "resolved_config": _resolved_config({
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1,
            "zero_optimization": _peak_rungs()[best_idx][3]}),
    }


def _v2_decode_once(model, eng_cfg, n_seqs, gen_tokens, prompt_len=32):
    from deepspeed_tpu.inference.v2 import InferenceEngineV2

    eng = InferenceEngineV2(model, eng_cfg)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, model.vocab_size, size=(prompt_len,)).tolist()
               for _ in range(n_seqs)]
    # warmup with the full token budget: compiles every decode-chunk
    # bucket the timed run will use (a chunk size first seen inside the
    # timing window would bill its remote compile as decode time)
    eng.generate(prompts, max_new_tokens=gen_tokens)
    eng.generate(prompts, max_new_tokens=1)
    # prefill throughput: admit + first token for all prompts (SplitFuse
    # mixed steps with on-device sampling)
    t0 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=1)
    prefill_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=gen_tokens)
    dt = time.perf_counter() - t0
    # steady-state decode: the 1-token run above paid the same prefill, so
    # the difference times only the remaining gen_tokens-1 decode steps
    decode_dt = max(dt - prefill_dt, 1e-9)
    _reset_topology()
    return (n_seqs * (gen_tokens - 1) / decode_dt,
            n_seqs * prompt_len / prefill_dt)


def row_v2_decode():
    """Inference v2 fused decode loop (paged KV cache): steady-state decode
    tokens/s on one chip, bf16 cache and int8 (quantized-KV) cache."""
    from deepspeed_tpu.models import get_model_config

    if SMOKE:
        model = get_model_config("llama-tiny")
        n_seqs, gen_tokens = 2, 8
        eng_cfg = {}
    else:
        model = get_model_config("llama3-8b", num_layers=4, max_seq_len=2048)
        # 32 seqs ride the 64-slot decode batch, and 128-step fused chunks
        # amortize the per-dispatch host round-trip (measured r04: 64
        # active seqs raised tok/s only 21% — the step is compute-bound —
        # while doubling the bar, so 32 is the better operating point)
        n_seqs, gen_tokens = 32, 128
        eng_cfg = {"max_decode_chunk": 128,
                   "memory_config": {"num_blocks": 1024}}
    tps, prefill_tps = _v2_decode_once(model, eng_cfg, n_seqs, gen_tokens)
    int8_cfg = {**eng_cfg,
                "memory_config": {**eng_cfg.get("memory_config", {}),
                                  "kv_dtype": "int8"}}
    tps_int8, _ = _v2_decode_once(model, int8_cfg, n_seqs, gen_tokens)
    best = max(tps, tps_int8)
    # FastGen blog: Llama-13B-class full-depth decode on A100 ≈ 50
    # tok/s/seq; scale the bar by PARAM count, not layer count — decode
    # cost tracks weight bytes/FLOPs, and the 525M-param lm_head (full
    # 128256 vocab) does not shrink when depth is truncated.
    layer_p = 218.1e6  # one llama3-8b layer (GQA attn 41.9M + swiglu 176.2M)
    embed_p = 2 * 128256 * 4096
    n_p = embed_p + model.num_layers * layer_p
    full_p = embed_p + 32 * layer_p
    bar_per_seq = 50.0 * (full_p / n_p)
    # Decode is HBM-bandwidth-bound (weights + KV re-read per token), so
    # the cross-hardware bar must be normalized by the bandwidth ratio:
    # v5e ≈ 0.82 TB/s vs A100-80G ≈ 2.0 TB/s → 0.41.  vs_baseline is the
    # raw param-scaled FastGen bar; vs_roofline divides out the hardware
    # ratio (1.0 = "as good as the reference, per byte/s of HBM").
    hw_bw_ratio = 0.82 / 2.0
    vs_raw = best / (bar_per_seq * n_seqs)
    # the decode engine has no serve loop to stream records from; emit
    # one summary StepRecord so this row leaves a JSONL trail too
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    tel = Telemetry(TelemetryConfig(
        enabled=True, jsonl_path=_telemetry_jsonl("v2_decode"),
        run_id=_run_id()))
    tel.record_serving_step(0, {
        "tokens_out": n_seqs * gen_tokens, "tokens_per_sec": best,
        "bf16_tokens_per_sec": tps, "int8_kv_tokens_per_sec": tps_int8,
        "prefill_tokens_per_sec": prefill_tps})
    tel.close()
    return {
        "metric": "v2_decode_tokens_per_sec",
        "telemetry_jsonl": _telemetry_jsonl("v2_decode"),
        "value": round(best, 1), "unit": "tokens/s",
        "vs_baseline": round(vs_raw, 3),
        "vs_roofline": round(vs_raw / hw_bw_ratio, 3),
        "bf16_tokens_per_sec": round(tps, 1),
        "int8_kv_tokens_per_sec": round(tps_int8, 1),
        "prefill_tokens_per_sec": round(prefill_tps, 1),
        "resolved_config": _resolved_config(
            {}, serving={"n_replicas": 1, "engine": eng_cfg}),
    }


def row_serve_load():
    """Serving layer (deepspeed_tpu/serving) under a synthetic open-loop
    arrival process: requests arrive on an exponential clock regardless of
    service progress (the closed-loop alternative hides queueing delay),
    stream through the async serve loop, and the row reports delivered
    tokens/s, p50/p95 TTFT, and the preemption rate.  vs_baseline is the
    serving path's throughput against the same engine's one-shot batch
    generate() on the identical workload — the async layer's overhead
    (queue, admission, per-step host fan-out) expressed as a fraction."""
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.serving import InferenceServer, SamplingParams

    if SMOKE:
        model = get_model_config("llama-tiny")
        n_req, new, prompt_len, rate = 8, 8, 16, 100.0
        # 31 usable blocks vs 8 requests × 6 final blocks: admission
        # overcommits and the smoke run exercises real preemption
        eng_cfg = {"dtype": "float32",
                   "memory_config": {"num_blocks": 32, "block_size": 4},
                   "max_context": 64}
    else:
        model = get_model_config("llama3-8b", num_layers=4, max_seq_len=2048)
        n_req, new, prompt_len, rate = 64, 64, 32, 32.0
        eng_cfg = {"memory_config": {"num_blocks": 1024}}
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    tel = Telemetry(TelemetryConfig(
        enabled=True, jsonl_path=_telemetry_jsonl("serve_load"),
        run_id=_run_id(),
        tracing={"enabled": True, "trace_path": _trace_json("serve_load")}))
    eng = InferenceEngineV2(model, eng_cfg)
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, model.vocab_size, size=(prompt_len,)).tolist()
               for _ in range(n_req)]
    # baseline + warmup in one: batch one-shot generate compiles every
    # bucket the served run will hit, and times the non-serving path
    eng.generate(prompts, max_new_tokens=new)
    t0 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=new)
    batch_dt = time.perf_counter() - t0
    batch_tps = n_req * new / batch_dt

    srv = InferenceServer(eng, {"metrics_interval_steps": 32},
                          telemetry=tel).start()
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    t0 = time.perf_counter()
    streams = []
    for i in range(n_req):
        lag = arrivals[i] - (time.perf_counter() - t0)
        if lag > 0:
            time.sleep(lag)
        streams.append(srv.submit(prompts[i],
                                  SamplingParams(max_new_tokens=new)))
    for s in streams:
        s.result()
    dt = time.perf_counter() - t0
    srv.stop()
    snap = srv.metrics.snapshot()
    span_ms = _span_breakdown(tel.tracer, {
        "queue": "serve.queue_wait", "prefill": "serve.prefill",
        "decode": "serve.decode"})
    tel.close()
    _reset_topology()
    tps = n_req * new / dt
    return {
        "metric": "serve_load_tokens_per_sec",
        "telemetry_jsonl": _telemetry_jsonl("serve_load"),
        "trace_json": _trace_json("serve_load"),
        "span_ms": span_ms,
        "value": round(tps, 1), "unit": "tokens/s",
        "vs_baseline": round(tps / batch_tps, 3),
        "ttft_p50_ms": round(snap["ttft"]["p50"] * 1e3, 1),
        "ttft_p95_ms": round(snap["ttft"]["p95"] * 1e3, 1),
        "tpot_p50_ms": round(snap["tpot"]["p50"] * 1e3, 2),
        "preemption_rate": round(snap["preemptions"] / n_req, 3),
        "completed": snap["completed"],
        "resolved_config": _resolved_config(
            {}, serving={"n_replicas": 1, "engine": eng_cfg}),
    }


def _serve_load_multi_body():
    """Multi-replica serving tier (serving/replica.py + router.py +
    prefix_cache.py): a mixed scenario schedule (shared_system_prompt +
    session_heavy traffic mixes from the scenario load generator)
    against a Router over 2 replicas on DISJOINT virtual mesh slices.
    Two sub-runs on identical workloads — prefix reuse ON vs OFF — report
    aggregate delivered tokens/s and p95 TTFT (measured router-side:
    submit → first token on the routed stream), plus the cache's
    hit-rate and prefill-tokens-saved counters.  Frozen keys linted by
    tools/telemetry_check.py against docs/SERVING.md."""
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.serving import ReplicaSet, Router
    from deepspeed_tpu.telemetry import Telemetry

    n_rep = 2
    if SMOKE:
        model = get_model_config("llama-tiny")
        n_per_mix, rate = 6, 100.0
        eng_cfg = {"dtype": "float32",
                   "memory_config": {"num_blocks": 64, "block_size": 4},
                   "max_context": 64}
    else:
        model = get_model_config("llama3-8b", num_layers=4,
                                 max_seq_len=2048)
        n_per_mix, rate = 64, 64.0
        eng_cfg = {"memory_config": {"num_blocks": 1024}}
    rng = np.random.default_rng(11)
    # the cache-relevant half of the scenario vocabulary: one shared
    # system prompt across everyone + session-sticky per-session prefixes
    schedule = _scenario_schedule(("shared_system_prompt",
                                   "session_heavy"), rng, model,
                                  n_per_mix, rate, SMOKE)
    warm_prompts = [r["prompt"] for r in schedule[:n_rep]]

    def run_once(prefix_enabled, telemetry=None):
        srv_cfg = {"prefix_cache": {"enabled": prefix_enabled}}
        rs = ReplicaSet.build(model, n_rep, eng_cfg, srv_cfg, seed=0)
        router = Router(rs, telemetry=telemetry).start()
        # warmup: compile every replica's buckets off the clock
        router.generate(warm_prompts, max_new_tokens=8)
        # baseline the cache counters so the reported hit rate / tokens
        # saved cover only the measured window (warmup hits the cache too)
        warm = rs.snapshot()
        res = _drive_schedule(router, schedule)
        snap = router.snapshot()
        for key in ("prefix_hits", "prefix_misses", "prefill_tokens_saved"):
            snap["aggregate"][key] -= warm[key]
        router.stop()
        _reset_topology()
        return res, snap

    tel = Telemetry(TelemetryConfig(
        enabled=True, jsonl_path=_telemetry_jsonl("serve_load_multi"),
        run_id=_run_id(),
        tracing={"enabled": True,
                 "trace_path": _trace_json("serve_load_multi")}))
    # reuse run FIRST: the second run inherits this process's warm XLA
    # compile cache, so running the no-reuse control second biases the
    # comparison AGAINST the cache — the reported win is conservative
    res_on, snap = run_once(True, telemetry=tel)
    res_off, _ = run_once(False)
    tel.close()
    tps_on, p95_on = res_on["tokens_per_sec"], res_on["ttft_p95_ms"]
    tps_off, p95_off = res_off["tokens_per_sec"], res_off["ttft_p95_ms"]
    agg = snap["aggregate"]
    hits, misses = agg["prefix_hits"], agg["prefix_misses"]
    return {
        "metric": "serve_load_multi_tokens_per_sec",
        "telemetry_jsonl": _telemetry_jsonl("serve_load_multi"),
        "trace_json": _trace_json("serve_load_multi"),
        "value": round(tps_on, 1), "unit": "tokens/s",
        "agg_tokens_per_sec": round(tps_on, 1),
        "agg_tokens_per_sec_noreuse": round(tps_off, 1),
        # reuse vs no-reuse on the identical workload
        "vs_baseline": round(tps_on / tps_off, 3) if tps_off else 0.0,
        "ttft_p95_ms": round(p95_on, 1),
        "ttft_p95_ms_noreuse": round(p95_off, 1),
        # frozen-key SLO ledger block (telemetry/slo.py SLO_BLOCK_KEYS):
        # attainment over the reuse run's per-request measurements, with
        # per-scenario-phase attainment under by_scenario
        "slo": _slo_spec().evaluate(res_on["requests"]),
        "prefix_hit_rate": round(hits / max(1, hits + misses), 3),
        "prefill_tokens_saved": int(agg["prefill_tokens_saved"]),
        "n_replicas": n_rep,
        "routed": snap["routed"],
        "failovers": snap["failovers"],
        "resolved_config": _resolved_config(
            {}, serving={"n_replicas": n_rep,
                         "prefix_cache": {"enabled": True}}),
    }


def row_serve_load_multi():
    """Multi-replica serving row.  Disjoint replica slices need > 1
    device; smoke mode pins the in-process backend to ONE cpu device,
    so the smoke variant re-execs itself on a virtual 8-device CPU mesh
    (same pattern as longseq_ring)."""
    if SMOKE and "--multi-inner" not in sys.argv:
        import os
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, __file__, "--row", "serve_load_multi",
               "--smoke", "--multi-inner"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900, env=env)
        except subprocess.TimeoutExpired:
            return {"metric": "serve_load_multi",
                    "error": "smoke timed out"}
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"metric": "serve_load_multi",
                "error": ("no result line; " + " | ".join(tail[-3:]))[:300]}
    return _serve_load_multi_body()


# ---------------------------------------------------------------------------
# Scenario load generator (docs/SERVING.md "Scenario load generator"):
# named traffic mixes composed into one open-loop schedule.  The mix
# names are a frozen vocabulary linted by tools/telemetry_check.py.
# ---------------------------------------------------------------------------

SCENARIO_MIXES = ("burst", "session_heavy", "shared_system_prompt",
                  "long_prompt_short_decode")


def _scenario_requests(mix: str, rng, model, n_req: int, rate: float,
                       smoke: bool) -> list:
    """One named traffic mix → request dicts {at, prompt, max_new,
    session, mix}.  Shapes scale with --smoke; arrival processes are the
    point: `burst` clusters arrivals (queue-depth stress),
    `session_heavy` pins few sessions with per-session shared prefixes
    (sticky-routing + cache stress), `shared_system_prompt` shares one
    long system prefix across everyone (the dominant production shape),
    and `long_prompt_short_decode` is prefill-dominated (the mix that
    separates the tiers)."""
    if mix not in SCENARIO_MIXES:
        raise ValueError(f"unknown scenario mix {mix!r} "
                         f"(known: {SCENARIO_MIXES})")
    vocab = model.vocab_size
    toks = lambda n: rng.integers(1, vocab, size=n).tolist()
    out = []
    if mix == "burst":
        group, uniq, new = (4, 10, 6) if smoke else (16, 64, 32)
        for i in range(n_req):           # exactly n_req, last burst may
            g = i // group               # be partial
            at0 = g * (group / rate) * 4.0   # bursts with idle gaps
            out.append({"at": at0 + rng.uniform(0, 0.002),
                        "prompt": toks(uniq), "max_new": new,
                        "session": None, "mix": mix})
    elif mix == "session_heavy":
        n_sessions = max(2, n_req // 3)
        uniq, new = (4, 6) if smoke else (24, 48)
        prefix_len = 8 if smoke else 256
        prefixes = [toks(prefix_len) for _ in range(n_sessions)]
        at = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
        for i in range(n_req):
            s = int(rng.integers(0, n_sessions))
            out.append({"at": float(at[i]),
                        "prompt": prefixes[s] + toks(uniq),
                        "max_new": new, "session": f"sess-{s}",
                        "mix": mix})
    elif mix == "shared_system_prompt":
        sys_len, uniq, new = (16, 6, 6) if smoke else (512, 32, 48)
        system = toks(sys_len)
        at = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
        for i in range(n_req):
            out.append({"at": float(at[i]), "prompt": system + toks(uniq),
                        "max_new": new, "session": None, "mix": mix})
    else:  # long_prompt_short_decode
        new = 4 if smoke else 8
        at = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
        for i in range(n_req):
            plen = int(rng.integers(24, 33)) if smoke \
                else int(rng.integers(1024, 1537))
            out.append({"at": float(at[i]), "prompt": toks(plen),
                        "max_new": new, "session": None, "mix": mix})
    return out


def _scenario_schedule(mixes, rng, model, n_per_mix: int, rate: float,
                       smoke: bool) -> list:
    """Compose named mixes into ONE merged arrival schedule (sorted by
    arrival time — the mixes interleave, they don't run back-to-back)."""
    sched = []
    for mix in mixes:
        sched.extend(_scenario_requests(mix, rng, model, n_per_mix,
                                        rate, smoke))
    sched.sort(key=lambda r: r["at"])
    return sched


def _drive_schedule(router, schedule, speculative: bool = False,
                    timeout: float = 600.0) -> dict:
    """Open-loop drive of one schedule against a router front door.
    Measures router-side per-request TTFT and TPOT (first/last token
    wall times observed by a consumer thread per stream) and aggregate
    delivered tokens/s."""
    import threading

    from deepspeed_tpu.serving import SamplingParams

    n = len(schedule)
    first_at = [0.0] * n
    last_at = [0.0] * n
    counts = [0] * n
    threads, streams = [], []

    def consume(i, stream):
        for _tok in stream:
            now = time.perf_counter()
            if first_at[i] == 0.0:
                first_at[i] = now
            last_at[i] = now
            counts[i] += 1

    t0 = time.perf_counter()
    for i, req in enumerate(schedule):
        lag = req["at"] - (time.perf_counter() - t0)
        if lag > 0:
            time.sleep(lag)
        s = router.submit(req["prompt"],
                          SamplingParams(max_new_tokens=req["max_new"],
                                         speculative=speculative),
                          session=req["session"])
        streams.append(s)
        th = threading.Thread(target=consume, args=(i, s))
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout)
    dt = time.perf_counter() - t0
    submit_at = [t0 + r["at"] for r in schedule]
    ttft_ms = sorted((f - s) * 1e3 for f, s in zip(first_at, submit_at)
                     if f > 0)
    tpot_ms = sorted((l - f) / (c - 1) * 1e3
                     for f, l, c in zip(first_at, last_at, counts)
                     if c > 1 and f > 0)

    # shared percentile derivation (telemetry/derive.py) — same index
    # formula the run ledger uses when it re-rolls these artifacts
    from deepspeed_tpu.telemetry.derive import p95

    handoff_ms = sorted(s.handoff_ms for s in streams
                        if getattr(s, "handoff_ms", None) is not None)
    handoff_bytes = [s.handoff_bytes for s in streams
                     if getattr(s, "handoff_bytes", None) is not None]
    # per-request measurements keyed by scenario mix — the SLO
    # evaluator's input (telemetry/slo.py SLOSpec.evaluate)
    requests = [{
        "scenario": r["mix"],
        "ttft_ms": ((first_at[i] - submit_at[i]) * 1e3
                    if first_at[i] > 0 else None),
        "tpot_ms": ((last_at[i] - first_at[i]) / (counts[i] - 1) * 1e3
                    if counts[i] > 1 and first_at[i] > 0 else None),
    } for i, r in enumerate(schedule)]
    return {
        "tokens_per_sec": sum(counts) / dt,
        "ttft_p95_ms": p95(ttft_ms), "tpot_p95_ms": p95(tpot_ms),
        "delivered": sum(counts), "completed": sum(1 for s in streams
                                                   if s.error is None),
        "handoff_ms_p95": p95(handoff_ms),
        "handoff_bytes_per_req": (sum(handoff_bytes)
                                  / max(1, len(handoff_bytes))),
        "requests": requests,
    }


def _slo_spec():
    """The bench rows' SLO targets (serving.slo shape): generous enough
    that a healthy CPU-smoke run attains them, tight enough that a
    regression (a stuck tier, a starved queue) shows as burn.  The
    prefill-dominated mix gets a looser TTFT target — exactly what
    scenario_overrides exists for."""
    from deepspeed_tpu.telemetry.slo import SLOSpec

    t = ({"ttft_p95_ms": 20_000.0, "tpot_p95_ms": 10_000.0,
          "queue_wait_p95_ms": 20_000.0} if SMOKE
         else {"ttft_p95_ms": 2_000.0, "tpot_p95_ms": 250.0,
               "queue_wait_p95_ms": 1_000.0})
    return SLOSpec({"enabled": True, "objective": 0.99, **t,
                    "scenario_overrides": {
                        "long_prompt_short_decode":
                            {"ttft_p95_ms": 2 * t["ttft_p95_ms"]}}})


def _serve_disagg_body():
    """Disaggregated tiers vs the homogeneous router at a FIXED chip
    budget (serving/disagg.py; docs/SERVING.md "Disaggregated tiers &
    speculative decoding"): the same mixed scenario schedule — every
    named mix, dominated by long_prompt_short_decode + chat-heavy
    session traffic — drives (a) a DisaggRouter over 2 prefill + 2
    decode replicas with KV-block handoff and speculative decoding on
    the decode tier, and (b) a plain Router over 4 unified replicas on
    the identical 4×2-device slices.  Frozen keys linted by
    tools/telemetry_check.py against docs/SERVING.md."""
    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.serving import DisaggRouter, ReplicaSet, Router
    from deepspeed_tpu.telemetry import Telemetry

    if SMOKE:
        model = get_model_config("llama-tiny", num_layers=2)
        n_per_mix, rate = 5, 50.0
        eng_cfg = {"dtype": "float32",
                   "memory_config": {"num_blocks": 96, "block_size": 4},
                   "max_context": 64}
    else:
        model = get_model_config("llama3-8b", num_layers=4,
                                 max_seq_len=2048)
        n_per_mix, rate = 32, 48.0
        eng_cfg = {"memory_config": {"num_blocks": 1024}}
    # identical-architecture draft (same seed ⇒ same argmax): the row
    # measures the serving-stack term of speculation — accepted tokens
    # per dispatch at its ceiling — because the draft-quality term needs
    # a trained/distilled draft checkpoint the bench does not have
    # (random-weight heterogeneous drafts agree at ~1/vocab chance)
    draft = model
    rng = np.random.default_rng(15)
    schedule = _scenario_schedule(SCENARIO_MIXES, rng, model, n_per_mix,
                                  rate, SMOKE)
    mix_counts = {m: sum(1 for r in schedule if r["mix"] == m)
                  for m in SCENARIO_MIXES}
    srv_cfg = {"prefix_cache": {"enabled": True},
               "metrics_window_s": 60.0}
    # warm set spans the shape buckets: a couple of typical prompts plus
    # one long-prompt entry (its block-table bucket compiles separately)
    warm = [r["prompt"] for r in schedule[:2]]
    warm.append(next(r["prompt"] for r in schedule
                     if r["mix"] == "long_prompt_short_decode"))

    tel = Telemetry(TelemetryConfig(
        enabled=True, jsonl_path=_telemetry_jsonl("serve_disagg"),
        run_id=_run_id(),
        tracing={"enabled": True,
                 "trace_path": _trace_json("serve_disagg")}))

    # (a) disaggregated: 2 prefill + 2 decode tiers + spec decoding
    disagg = {"enabled": True, "prefill_replicas": 2,
              "decode_replicas": 2,
              "speculative": {"enabled": True, "draft_model": draft,
                              "spec_k": 3}}
    rs = ReplicaSet.build(model, 4, eng_cfg, srv_cfg, seed=0,
                          disagg=disagg)
    router = DisaggRouter(rs, telemetry=tel).start()
    # compile off the clock: speculative submits so the draft + verify-k
    # buckets (not just prefill/decode) are warm before the window opens
    from deepspeed_tpu.serving import FleetSampler
    from deepspeed_tpu.serving import SamplingParams as _SP
    for s in [router.submit(p, _SP(max_new_tokens=6, speculative=True))
              for p in warm]:
        s.result(timeout=600)
    sampler = FleetSampler(rs, router=router, slo=_slo_spec(),
                           cadence_s=0.25,
                           jsonl_path=_fleet_jsonl("serve_disagg"),
                           telemetry=tel).start()
    dis = _drive_schedule(router, schedule, speculative=True)
    snap = router.snapshot()
    sampler.stop()                 # quiesce the cadence thread first so
    sampler.sample_once()          # the tail tick is the true last row
    fleet = sampler.latest()
    router.stop()
    _reset_topology()
    tel.close()

    # (b) homogeneous control: the same 8 chips as 4 unified replicas
    rs_h = ReplicaSet.build(model, 4, eng_cfg, srv_cfg, seed=0)
    router_h = Router(rs_h).start()
    router_h.generate(warm, max_new_tokens=6)
    hom = _drive_schedule(router_h, schedule, speculative=False)
    router_h.stop()
    _reset_topology()

    return {
        "metric": "serve_disagg_tokens_per_sec",
        "telemetry_jsonl": _telemetry_jsonl("serve_disagg"),
        "trace_json": _trace_json("serve_disagg"),
        "value": round(dis["tokens_per_sec"], 1), "unit": "tokens/s",
        "agg_tokens_per_sec_disagg": round(dis["tokens_per_sec"], 1),
        "agg_tokens_per_sec_homog": round(hom["tokens_per_sec"], 1),
        "vs_baseline": (round(dis["tokens_per_sec"]
                              / hom["tokens_per_sec"], 3)
                        if hom["tokens_per_sec"] else 0.0),
        "ttft_p95_ms_disagg": round(dis["ttft_p95_ms"], 1),
        "ttft_p95_ms_homog": round(hom["ttft_p95_ms"], 1),
        "tpot_p95_ms_disagg": round(dis["tpot_p95_ms"], 2),
        "tpot_p95_ms_homog": round(hom["tpot_p95_ms"], 2),
        "handoff_ms_p95": round(dis["handoff_ms_p95"], 2),
        "handoff_bytes_per_req": round(dis["handoff_bytes_per_req"], 1),
        "handoffs": snap["handoffs"],
        "spec_accept_rate": round(
            snap["aggregate"]["spec_accept_rate"], 3),
        # frozen-key SLO ledger block (telemetry/slo.py SLO_BLOCK_KEYS)
        # with per-scenario-phase attainment under by_scenario
        "slo": _slo_spec().evaluate(dis["requests"]),
        "fleet_jsonl": _fleet_jsonl("serve_disagg"),
        "fleet_tiers": sorted(fleet),
        "scenario_mix": mix_counts,
        "completed_disagg": dis["completed"],
        "completed_homog": hom["completed"],
        "resolved_config": _resolved_config(
            {}, serving={"n_replicas": 4,
                         "disagg": {"enabled": True,
                                    "prefill_replicas": 2,
                                    "decode_replicas": 2,
                                    "speculative": True, "spec_k": 3}}),
    }


def row_serve_disagg():
    """Disaggregated-serving row.  Tier slices need 8 devices; smoke
    mode pins ONE cpu device, so the smoke variant re-execs itself on a
    virtual 8-device CPU mesh (same pattern as serve_load_multi)."""
    if SMOKE and "--disagg-inner" not in sys.argv:
        import os
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, __file__, "--row", "serve_disagg",
               "--smoke", "--disagg-inner"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900, env=env)
        except subprocess.TimeoutExpired:
            return {"metric": "serve_disagg", "error": "smoke timed out"}
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"metric": "serve_disagg",
                "error": ("no result line; " + " | ".join(tail[-3:]))[:300]}
    return _serve_disagg_body()


def _chaos_train_half(base: str, tel) -> dict:
    """Train-side chaos (resilience/supervisor.py): an unkilled reference
    run, then the same workload with a worker killed mid-train AND its
    host removed from the survivors census — the supervisor must dump a
    flight bundle, stop the group (SIGTERM→SIGKILL budget), re-plan a
    SMALLER mesh, restart, and resume from the latest committed
    universal checkpoint with the loss curve landing back on the
    reference."""
    from deepspeed_tpu.resilience.supervisor import (RecoverySupervisor,
                                                     loss_curve)

    if SMOKE:
        total_steps, die_at, deadline_s = 6, 3, 240.0
        n_hosts, dev_per_host = 2, 2
        worker_env = {"DSTPU_SEQ": "16", "DSTPU_BATCH": "8"}
    else:
        total_steps, die_at, deadline_s = 20, 10, 600.0
        n_hosts, dev_per_host = 2, 4
        worker_env = {"DSTPU_SEQ": "128", "DSTPU_BATCH": "8"}

    ref_dir = os.path.join(base, "ref")
    sup_ref = RecoverySupervisor(
        ref_dir, hosts_fn=lambda: [f"h{i}" for i in range(n_hosts)],
        devices_per_host=dev_per_host, total_steps=total_steps,
        deadline_s=60.0, poll_s=0.2, worker_env=dict(worker_env),
        force_cpu=SMOKE)
    ref = sup_ref.run()
    ref_losses = loss_curve(ref.progress_path)

    chaos_dir = os.path.join(base, "chaos")
    os.makedirs(chaos_dir, exist_ok=True)
    sentinel = os.path.join(chaos_dir, ".chaos_fired")

    def hosts():
        # the dying worker arms the chaos sentinel just before exiting —
        # from then on host h1 is gone and the re-plan must shrink
        alive = n_hosts - (1 if os.path.exists(sentinel) else 0)
        return [f"h{i}" for i in range(alive)]

    sup = RecoverySupervisor(
        chaos_dir, hosts_fn=hosts, devices_per_host=dev_per_host,
        total_steps=total_steps, deadline_s=60.0, poll_s=0.2,
        stop_timeout_s=15.0, resume_deadline_s=deadline_s, telemetry=tel,
        worker_env={**worker_env,
                    "DSTPU_CHAOS": json.dumps({"die_at": die_at})},
        force_cpu=SMOKE)
    res = sup.run()
    curve = loss_curve(res.progress_path)

    gap = max(abs(curve[s] - ref_losses[s])
              for s in ref_losses if s >= die_at)
    recovery_s = res.outages[0]["outage_s"] if res.outages else -1.0
    assert res.returncode == 0 and res.recoveries >= 1, \
        (res.returncode, res.recoveries)
    assert res.outages and res.outages[0]["resized"], \
        "host loss did not shrink the planned mesh"
    assert recovery_s < deadline_s, (recovery_s, deadline_s)
    # one outage = one skipped record next to total_steps applied ones
    goodput_after = total_steps / (total_steps + len(res.outages))
    return {"recovery_s": round(recovery_s, 1),
            "loss_gap": round(gap, 6),
            "goodput_after": round(goodput_after, 4),
            "recovered_mesh": res.outages[0]["mesh"],
            "flight_bundle": bool(res.outages[0]["bundle"])}


def _chaos_serve_half() -> dict:
    """Serving-side chaos: open-loop load against a 2-replica Router;
    replica r0 is hard-killed mid-load (fail-over must keep p99 TTFT
    bounded and every request completing) and then respawned LIVE on its
    own slice (ReplicaSet.respawn) — the re-grown replica must serve
    again."""
    import threading

    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.serving import ReplicaSet, Router, SamplingParams

    model = get_model_config("llama-tiny")
    if SMOKE:
        n_req, new, rate = 12, 8, 50.0
        eng_cfg = {"dtype": "float32",
                   "memory_config": {"num_blocks": 64, "block_size": 4},
                   "max_context": 64}
    else:
        n_req, new, rate = 64, 32, 32.0
        eng_cfg = {"memory_config": {"num_blocks": 512}}
    rng = np.random.default_rng(23)
    prompts = [rng.integers(1, model.vocab_size, size=12).tolist()
               for _ in range(n_req)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    kill_at, respawn_at = n_req // 3, 2 * n_req // 3

    rs = ReplicaSet.build(model, 2, eng_cfg, {}, seed=0)
    router = Router(rs).start()
    router.generate(prompts[:2], max_new_tokens=new)  # compile both
    first_at = [0.0] * n_req
    threads = []

    def consume(i, stream):
        for _tok in stream:
            if first_at[i] == 0.0:
                first_at[i] = time.perf_counter()

    t0 = time.perf_counter()
    for i in range(n_req):
        lag = arrivals[i] - (time.perf_counter() - t0)
        if lag > 0:
            time.sleep(lag)
        if i == kill_at:
            rs[0].kill()          # hard stop: in-flight requests fail over
        if i == respawn_at:
            rs.respawn(0)         # live re-grow on the freed slice
        s = router.submit(prompts[i], SamplingParams(max_new_tokens=new))
        th = threading.Thread(target=consume, args=(i, s))
        th.start()
        threads.append(th)
    submit_at = [t0 + a for a in arrivals]
    for th in threads:
        th.join(timeout=600)
    ttft_ms = sorted((f - s) * 1e3
                     for f, s in zip(first_at, submit_at) if f > 0)
    p99 = (ttft_ms[min(len(ttft_ms) - 1, int(0.99 * (len(ttft_ms) - 1)))]
           if ttft_ms else -1.0)
    snap = router.snapshot()
    # the respawned replica must actually serve: a direct request to it
    out = rs[0].server.generate([prompts[0]], max_new_tokens=4)
    regrown = int(rs[0].alive and len(out[0]) == 4)
    router.stop()
    _reset_topology()
    assert len(ttft_ms) == n_req, (len(ttft_ms), n_req)
    assert snap["failovers"] >= 1, snap
    assert regrown == 1
    return {"serve_ttft_p99_ms": round(p99, 1),
            "failovers": int(snap["failovers"]),
            "regrown": regrown}


def _chaos_recovery_body():
    """Chaos row (docs/ELASTICITY.md): kill a worker mid-train → assert
    recovery within the deadline + loss continuity on a SHRUNK mesh;
    kill a serving replica under open-loop load → assert p99 TTFT stays
    bounded through fail-over and the ReplicaSet re-grows live.  Frozen
    keys linted by tools/telemetry_check.py against docs/ELASTICITY.md."""
    import tempfile

    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.telemetry import Telemetry

    base = tempfile.mkdtemp(prefix="dstpu_chaos_")
    tel = Telemetry(TelemetryConfig(
        enabled=True, jsonl_path=_telemetry_jsonl("chaos_recovery"),
        run_id=_run_id(),
        tracing={"enabled": True,
                 "trace_path": _trace_json("chaos_recovery")},
        flight={"enabled": True,
                "output_dir": os.path.join(base, "flight")}))
    train = _chaos_train_half(base, tel)
    serve = _chaos_serve_half()
    tel.close()
    return {
        "metric": "chaos_recovery_s",
        "telemetry_jsonl": _telemetry_jsonl("chaos_recovery"),
        "trace_json": _trace_json("chaos_recovery"),
        "flight_dir": os.path.join(base, "flight"),
        "value": train["recovery_s"], "unit": "s",
        **train, **serve,
        "resolved_config": _resolved_config(
            {"zero_optimization": {"stage": 1}},
            serving={"n_replicas": 2}),
    }


def row_chaos_recovery():
    """Self-healing chaos row.  The recovery supervisor spawns worker
    subprocesses that force their own virtual CPU meshes, but the
    serving half needs >1 device in-process; smoke mode pins the outer
    process to ONE cpu device, so the smoke variant re-execs itself on a
    virtual 8-device CPU mesh (same pattern as serve_load_multi)."""
    if SMOKE and "--chaos-inner" not in sys.argv:
        import os
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, __file__, "--row", "chaos_recovery",
               "--smoke", "--chaos-inner"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900, env=env)
        except subprocess.TimeoutExpired:
            return {"metric": "chaos_recovery",
                    "error": "smoke timed out"}
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"metric": "chaos_recovery",
                "error": ("no result line; " + " | ".join(tail[-3:]))[:300]}
    return _chaos_recovery_body()


def _chaos_serve_body():
    """Serving chaos drill (docs/SERVING.md "Fault injection &
    self-healing"): a scripted, seeded FaultPlan — all six fault kinds —
    against a supervised 2 prefill + 2 decode disagg fleet.  The control
    phase records fault-free greedy outputs on the same fleet; the chaos
    phase then demands every request terminate typed (zero hangs), every
    dead/stuck replica quarantined + respawned within the heal deadline,
    the decode tier collapse into degraded homogeneous routing and
    restore after healing, and every chaos-phase completion bit-identical
    to its control twin.  Frozen keys linted by tools/telemetry_check.py
    against docs/SERVING.md."""
    import tempfile

    from deepspeed_tpu.models import get_model_config
    from deepspeed_tpu.resilience.chaos import FaultPlan, attach_chaos
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.serving import (DisaggRouter, FleetSampler,
                                       FleetSupervisor, ReplicaSet,
                                       RequestCancelled, RequestShed,
                                       SamplingParams, ServingError)
    from deepspeed_tpu.telemetry import Telemetry

    model = get_model_config("llama-tiny", num_layers=2)
    if SMOKE:
        n_req, new, rate, wait_s = 24, 8, 40.0, 240.0
        eng_cfg = {"dtype": "float32",
                   "memory_config": {"num_blocks": 96, "block_size": 4},
                   "max_context": 64}
    else:
        n_req, new, rate, wait_s = 64, 16, 48.0, 600.0
        eng_cfg = {"memory_config": {"num_blocks": 512}}
    rng = np.random.default_rng(31)
    prompts = [rng.integers(1, model.vocab_size, size=12).tolist()
               for _ in range(n_req)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    # every 6th request is below-floor priority: the shed_low_priority
    # rung (if pressure climbs that far) must take exactly this class
    prios = [-1 if i % 6 == 5 else 0 for i in range(n_req)]

    base = tempfile.mkdtemp(prefix="dstpu_chaos_serve_")
    flight_dir = os.path.join(base, "flight")
    tel = Telemetry(TelemetryConfig(
        enabled=True, jsonl_path=_telemetry_jsonl("chaos_serve"),
        run_id=_run_id(),
        tracing={"enabled": True,
                 "trace_path": _trace_json("chaos_serve")}))

    rs = ReplicaSet.build(model, 4, eng_cfg,
                          {"admission": {"max_queue_size": 32}}, seed=0,
                          disagg={"enabled": True, "prefill_replicas": 2,
                                  "decode_replicas": 2})
    router = DisaggRouter(rs, telemetry=tel).start()

    # control phase: fault-free greedy outputs through the SAME disagg
    # path (this also pays every compile before the chaos clock starts);
    # respawned replicas rebuild from the same seed, so chaos-phase
    # completions must reproduce these bit-for-bit
    control = router.generate(prompts, max_new_tokens=new)
    assert all(len(o) == new for o in control), "control run incomplete"

    sampler = FleetSampler(rs, router=router, slo=_slo_spec(),
                           cadence_s=0.25,
                           jsonl_path=_fleet_jsonl("chaos_serve"),
                           telemetry=tel).start()
    sup = FleetSupervisor(
        rs, router=router, sampler=sampler, telemetry=tel,
        flight_dir=flight_dir,
        config={"cadence_s": 0.2, "suspect_ticks": 2,
                "stuck_after_s": 1.0, "straggler_factor": 8.0,
                "heal_deadline_s": 60.0 if SMOKE else 30.0,
                "max_heals": 6,
                "brownout": {"enter": 0.5, "exit": 0.2, "dwell_s": 0.3,
                             "priority_floor": 0}}).start()

    # the scripted fault plan — all six kinds, offsets from arm time.
    # Both decode replicas (r2, r3) crash ~together so the decode pool
    # empties while healing is still in flight: the supervisor must
    # collapse the tiers, heal, then restore them.
    plan = FaultPlan([
        {"kind": "slow_replica", "target": "r0", "at": 0.1,
         "duration_s": 3.0, "params": {"delay_ms": 30.0}},
        {"kind": "handoff_fail", "target": "r2", "at": 0.2},
        {"kind": "admission_storm", "target": "r0", "at": 0.4,
         "params": {"burst": 4, "priority": -100, "max_new_tokens": 4}},
        {"kind": "cancel_storm", "target": "r2", "at": 0.5,
         "params": {"count": 2}},
        {"kind": "replica_hang", "target": "r1", "at": 0.8},
        {"kind": "replica_crash", "target": "r2", "at": 0.9},
        {"kind": "replica_crash", "target": "r3", "at": 0.95},
    ], seed=7)
    injectors = attach_chaos(rs, plan, router=router)

    streams, shed_at_submit = {}, 0
    t0 = time.perf_counter()
    for i in range(n_req):
        lag = arrivals[i] - (time.perf_counter() - t0)
        if lag > 0:
            time.sleep(lag)
        try:
            streams[i] = router.submit(
                prompts[i], SamplingParams(max_new_tokens=new),
                priority=prios[i])
        except RequestShed:
            shed_at_submit += 1

    completed, shed, cancelled, failed, hung = 0, shed_at_submit, 0, 0, 0
    outs = {}
    for i, s in streams.items():
        try:
            outs[i] = s.result(timeout=wait_s)
            completed += 1
        except RequestShed:
            shed += 1
        except RequestCancelled:
            cancelled += 1
        except TimeoutError:
            hung += 1
        except ServingError:
            failed += 1

    # settle: every casualty healed, tiers restored, before reading out
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        snap = sup.snapshot()
        if (not snap["failed"] and not router.collapsed
                and all(st in ("healthy", "respawned")
                        for st in snap["states"].values())):
            break
        time.sleep(0.25)
    sup.stop()
    sup.check()                    # heal budget must NOT have blown
    snap = sup.snapshot()
    heals = [e for e in sup.events if e.get("state") == "respawned"]
    brownouts = [e for e in sup.events if e.get("state") == "brownout"]
    sampler.stop()
    sampler.sample_once()
    hist = sampler.history()
    router.stop()
    _reset_topology()
    tel.close()

    kinds = set()
    for inj in injectors.values():
        kinds |= inj.fired_kinds
    faults_injected = sum(inj.injected for inj in injectors.values())
    mismatch = [i for i in outs if outs[i] != control[i]]
    curve = {}
    for row in hist:
        curve[row["tick"]] = (curve.get(row["tick"], 0)
                              + int(row["slo_violation"]))
    heal_s = [e["heal_s"] for e in heals]
    from deepspeed_tpu.serving.admission import brownout_index

    # the acceptance gates — each failure names the evidence
    assert hung == 0, f"{hung} requests never terminated"
    assert len(kinds) >= 4, f"only {sorted(kinds)} fired"
    assert snap["heals"] >= 3 and len(heals) >= 3, (snap, len(heals))
    assert all(st in ("healthy", "respawned")
               for st in snap["states"].values()), snap["states"]
    assert snap["collapses"] >= 1 and snap["restores"] >= 1, snap
    assert not mismatch, f"chaos outputs diverged on requests {mismatch}"
    assert completed >= n_req // 2, (completed, n_req)
    return {
        "metric": "chaos_serve_completed",
        "telemetry_jsonl": _telemetry_jsonl("chaos_serve"),
        "trace_json": _trace_json("chaos_serve"),
        "fleet_jsonl": _fleet_jsonl("chaos_serve"),
        "flight_dir": flight_dir,
        "value": completed, "unit": "requests",
        "vs_baseline": round(completed / n_req, 3),
        "faults_injected": faults_injected,
        "fault_kinds": sorted(kinds),
        "completed_chaos": completed,
        "shed_chaos": shed,
        "cancelled_chaos": cancelled,
        "failed_chaos": failed,
        "heals": snap["heals"],
        "time_to_heal_s": round(max(heal_s), 3) if heal_s else -1.0,
        "collapses": snap["collapses"],
        "restores": snap["restores"],
        "bit_identical": int(not mismatch),
        "brownout_peak": max([brownout_index(e["level"])
                              for e in brownouts] or [0]),
        "slo_violations_curve": [curve[t] for t in sorted(curve)],
        "resolved_config": _resolved_config(
            {}, serving={"n_replicas": 4,
                         "disagg": {"enabled": True,
                                    "prefill_replicas": 2,
                                    "decode_replicas": 2},
                         "supervisor": {"max_heals": 6,
                                        "brownout": True}}),
    }


def row_chaos_serve():
    """Serving chaos-drill row.  The disagg fleet needs 8 devices; smoke
    mode pins ONE cpu device, so the smoke variant re-execs itself on a
    virtual 8-device CPU mesh (same pattern as serve_disagg)."""
    if SMOKE and "--chaos-serve-inner" not in sys.argv:
        import os
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, __file__, "--row", "chaos_serve",
               "--smoke", "--chaos-serve-inner"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=900, env=env)
        except subprocess.TimeoutExpired:
            return {"metric": "chaos_serve", "error": "smoke timed out"}
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"metric": "chaos_serve",
                "error": ("no result line; " + " | ".join(tail[-3:]))[:300]}
    return _chaos_serve_body()


def row_plan_validate():
    """Planner regression row (docs/PLANNER.md "Regression gate"): the
    plan compiler re-derives every pinned known-good bench config from
    first principles — for each audit row, compile the query mirroring
    the row's experiment space and report the 1-based rank of the row's
    pinned config; then propose the 6.7B offload ladder rung
    sight-unseen on a 1-chip host+NVMe fleet.  Pure analytic CPU work:
    identical in smoke and on-chip runs.  Keys frozen in
    tools/telemetry_check.py."""
    from deepspeed_tpu.planner import (FleetSpec, ModelSpec, compile_plan,
                                       plan_rank_of)
    from deepspeed_tpu.planner.audit import PLAN_AUDIT_ROWS, plan_for_row

    ranks = {}
    for name in PLAN_AUDIT_ROWS:
        plan = plan_for_row(name)
        ranks[name] = plan_rank_of(plan, PINNED_ROW_CONFIGS[name])
    # sight-unseen: the chunked 6.7B rung on a fleet the planner has
    # never benched — 1 chip, 64 GiB host, NVMe (the r16 ladder box)
    model = ModelSpec.from_name("gpt2-6.7b", seq_len=512)
    fleet = FleetSpec(chips=1, hbm_bytes=16 << 30, host_bytes=64 << 30,
                      nvme=True)
    plan67 = compile_plan(model, fleet, max_micro_batch=4)
    ranks["gpt2_6_7b_chunked"] = plan_rank_of(
        plan67, PINNED_ROW_CONFIGS["gpt2_6_7b_chunked"])
    hits = sum(1 for r in ranks.values() if r is not None and r <= 3)
    return {
        "metric": "plan_validate_known_good_top3",
        "value": hits, "unit": "rows",
        "vs_baseline": round(hits / len(ranks), 3),
        "known_good_ranks": ranks,
        "proposed_6_7b": (plan67.ranked[0].candidate
                          if plan67.ranked else None),
        "pruned_6_7b": len(plan67.pruned),
        "evidence_keys_ok": _plan_evidence_ok(plan67),
    }


def _plan_evidence_ok(plan) -> bool:
    from deepspeed_tpu.planner import PLAN_EVIDENCE_KEYS

    want = tuple(sorted(PLAN_EVIDENCE_KEYS))
    return bool(plan.ranked) and all(
        tuple(sorted(e.evidence)) == want for e in plan.ranked)


def _device_probe_error(timeout_s: float = 120.0):
    """A hung bench run records nothing at all (worse than an error row) —
    probe the backend with a deadline before touching it."""
    from deepspeed_tpu.utils.device_probe import probe_default_backend

    return probe_default_backend(1, timeout_s)


_ROWS = {
    "gpt2_350m_autosched": row_gpt2_350m_autosched,
    "gpt2_350m_commquant": row_gpt2_350m_commquant,
    "llama8b_class_zero3": row_llama8b_class_zero3,
    "longseq_flash": row_longseq_flash,
    "longseq_llama": row_longseq_llama,
    "longseq_ring": row_longseq_ring,
    "peak_params": row_peak_params,
    "v2_decode": row_v2_decode,
    "serve_load": row_serve_load,
    "serve_load_multi": row_serve_load_multi,
    "serve_disagg": row_serve_disagg,
    "chaos_recovery": row_chaos_recovery,
    "chaos_serve": row_chaos_serve,
    "plan_validate": row_plan_validate,
    "gpt2_350m": row_gpt2_350m,
}


def _run_row_subprocess(name: str, timeout_s: float = 900.0) -> dict:
    """Run one row in a fresh interpreter.

    Isolation is load-bearing, not hygiene: rows materialize multi-GB
    engines, and a row that dies mid-compile (or mid-step) can leave its
    HBM buffers live in this process, cascading RESOURCE_EXHAUSTED into
    every later row (observed r04: one failing row zeroed the whole
    report). A subprocess exit frees the chip unconditionally."""
    import subprocess

    cmd = [sys.executable, __file__, "--row", name]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"metric": name, "error": f"row timed out after {timeout_s}s",
                "run_id": _run_id()}
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {"metric": name, "run_id": _run_id(),
            "error": ("no result line; " + " | ".join(tail[-3:]))[:300]}


# peak_params walks the ladder serially; the NVMe rungs alone can spend
# 1500+1200+900 s before the cpu rungs run, so the row budget must cover
# a failing-descent worst case
_ROW_TIMEOUTS = {"peak_params": 5400.0}


def main() -> None:
    if "--peak-entry" in sys.argv:
        idx = int(sys.argv[sys.argv.index("--peak-entry") + 1])
        print(json.dumps(_peak_entry(idx)), flush=True)
        return
    if "--row" in sys.argv:
        name = sys.argv[sys.argv.index("--row") + 1]
        # inherit the parent's run id (env) or mint one for direct
        # invocations — smoke re-exec inners must share the outer's id
        os.environ.setdefault("DSTPU_RUN_ID", _mint_run_id(name))
        try:
            r = _write_row_manifest(name, _ROWS[name]())
        except Exception as e:
            r = {"metric": name, "error": str(e)[:250],
                 "run_id": _run_id()}
        print(json.dumps(r), flush=True)
        return
    probe_err = None if SMOKE else _device_probe_error()
    if probe_err is not None:
        # one retry after a pause: the axon tunnel drops transiently, and
        # a single failed probe would otherwise record a numberless round
        time.sleep(90)
        probe_err = _device_probe_error()
    if probe_err is not None:
        print(json.dumps({
            "metric": "gpt2_350m_zero1_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "error": f"TPU backend unreachable ({probe_err})",
            "rows": []}), flush=True)
        return
    rows = []
    for name in ("llama8b_class_zero3", "longseq_flash", "longseq_llama",
                 "longseq_ring", "gpt2_350m_commquant",
                 "gpt2_350m_autosched", "peak_params",
                 "v2_decode", "serve_load", "serve_load_multi",
                 "serve_disagg", "chaos_recovery", "chaos_serve",
                 "plan_validate"):
        # one run id per row, minted HERE so subprocess rows inherit it
        # through the environment and every artifact carries the same id
        os.environ["DSTPU_RUN_ID"] = _mint_run_id(name)
        if SMOKE:
            try:
                r = _write_row_manifest(name, _ROWS[name]())
            except Exception as e:
                r = {"metric": name, "error": str(e)[:250],
                     "run_id": _run_id()}
        else:
            r = _run_row_subprocess(name, _ROW_TIMEOUTS.get(name, 900.0))
        rows.append(r)
        print(json.dumps(r), flush=True)
    os.environ["DSTPU_RUN_ID"] = _mint_run_id("gpt2_350m")
    if SMOKE:
        try:
            primary = _write_row_manifest("gpt2_350m", row_gpt2_350m())
        except Exception as e:
            primary = {"metric":
                       "gpt2_350m_zero1_train_tokens_per_sec_per_chip",
                       "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                       "error": str(e)[:250]}
    else:
        primary = _run_row_subprocess("gpt2_350m")
        if "error" in primary:
            # the LAST line is what the driver records — it must be the
            # primary metric (or its explicit failure), never a stray
            # secondary row
            primary = {"metric":
                       "gpt2_350m_zero1_train_tokens_per_sec_per_chip",
                       "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                       "error": primary["error"]}
    primary["rows"] = rows
    print(json.dumps(primary), flush=True)


if __name__ == "__main__":
    main()
