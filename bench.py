"""Benchmark harness — runs on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Benchmarks the ZeRO training engine end-to-end (train_batch: fwd+bwd+update
in one compiled step) on a GPT-2-class model sized for a single v5e chip and
reports model FLOPs throughput (MFU-style tokens/sec).  ``vs_baseline``
compares against an A100 eager-torch reference rate for the same model class
(the north star in BASELINE.md is tokens/sec/chip parity with A100+NCCL).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    # GPT-2 350M-class, bf16, ZeRO-1, seq 1024 — fits one v5e chip.
    # Tuned on-chip: repo-owned Pallas flash attention (ops/pallas/flash_mha,
    # default) + dots_flash_saveable remat (save matmul outputs AND the
    # flash kernel's o/lse residuals so the backward never re-runs the
    # attention forward) + gas=8 to amortise the optimizer step.
    # Measured ladder: 24.5k (xla attn, full remat) → 31.1k (library flash)
    # → 34.5k (dots_saveable+gas8) → 38.1k (repo kernel) → ~39.9k
    # (dots_flash_saveable).
    model = get_model_config("gpt2-350m", max_seq_len=1024)
    batch_size = 8
    gas = 8
    seq = 1024
    config = {
        "train_micro_batch_size_per_gpu": batch_size,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
        "activation_checkpointing": {"remat_policy": "dots_flash_saveable"},
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)

    rows = batch_size * gas
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, size=(rows, seq + 1), dtype=np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:].astype(np.int32)}

    # warmup (compile); float() is a hard host sync — block_until_ready
    # returns early under the axon relay, so sync via value fetch.
    for _ in range(3):
        loss = engine.train_batch(batch)
    float(np.asarray(loss))

    steps = 8
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    float(np.asarray(loss))
    dt = time.perf_counter() - t0

    tokens_per_sec = steps * rows * seq / dt
    # Baseline: GPT-2 350M-class training on one A100 with eager
    # torch+DeepSpeed ZeRO-1 sustains roughly 35k tokens/s (bf16, seq 1024)
    # — derived from A100 312 TFLOPs peak at ~40% MFU over 6*N*T flops/token.
    baseline_tokens_per_sec = 35_000.0
    # Model FLOPs per token (fwd [2·params-matmuls + lm_head + causal attn]
    # ×3 for fwd+bwd), against the v5e bf16 peak of 197 TFLOP/s.
    h, L, V = model.hidden_size, model.num_layers, model.vocab_size
    fwd_flops_per_tok = 2 * (12 * h * h * L) + 2 * h * V + 2 * seq * h * L
    mfu = tokens_per_sec * 3 * fwd_flops_per_tok / 197e12
    print(json.dumps({
        "metric": "gpt2_350m_zero1_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / baseline_tokens_per_sec, 3),
        "mfu": round(mfu, 3),
    }))


if __name__ == "__main__":
    main()
