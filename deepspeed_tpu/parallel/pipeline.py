"""Pipeline parallelism via SPMD collective-permute.

TPU-native re-design of ``runtime/pipe/`` (PipelineModule module.py:86,
PipelineEngine engine.py:337, TrainSchedule schedule.py:189, P2P p2p.py):
instead of an instruction-schedule interpreter issuing eager P2P sends
between stage processes, the whole pipeline is ONE ``shard_map`` over the
"pipe" mesh axis:

* layer params are stacked ``[L, ...]`` and sharded over "pipe", so each
  stage holds ``L/pp`` layers — the analog of ``PipelineModule``'s layer
  partitioning ("uniform" method, ref module.py:393);
* microbatches circulate between stages with ``lax.ppermute`` (ICI
  neighbour exchange), the analog of SendActivation/RecvActivation
  (ref engine.py:1016/:1108);
* :func:`spmd_pipeline` is the forward schedule (GPipe fill-drain as a
  differentiable ``lax.scan``); finished microbatches **ring-drain**
  through a single-slot transit buffer to a home stage (``o % pp``), so
  each stage stores ``ceil(n_micro/pp)`` microbatches, drain traffic is
  one microbatch per tick, and a single all-gather at the end replaces
  the old full-buffer psum broadcast.

Other mesh axes (data/tensor/seq/expert) stay in GSPMD "auto" mode inside
the shard_map (jax 0.9 ``axis_names``), so pipeline composes with ZeRO/DP/TP
sharding unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.parallel.topology import PIPE_AXIS, MeshTopology


def _drain_schedule(n_micro: int, pp: int):
    """Static capture schedule for the transit-slot ring drain.

    Finished microbatch ``o`` (emitted by the last stage at tick
    ``o + pp - 1``) travels the ring one hop per tick in a single-slot
    transit buffer until it reaches its home stage ``o % pp``, which
    captures it into row ``o // pp`` of its local (never-permuted) store.
    Emissions are one per tick and every trip is < pp hops, so at most one
    item occupies any stage's transit slot at a time — inter-stage drain
    traffic is one microbatch per tick (the old full-buffer rotation moved
    ceil(n_micro/pp) of them every tick).

    Returns ``(cap_do [T, pp], cap_row [T, pp], T)`` where tick ``t``'s
    entries say whether stage ``s`` captures its incoming transit item
    this tick and into which row; ``T`` includes the post-compute drain
    ticks that flush the last items home.
    """
    compute_ticks = n_micro + pp - 1
    T = compute_ticks + pp - 1
    cap_do = np.zeros((T, pp), np.bool_)
    cap_row = np.zeros((T, pp), np.int32)
    for o in range(n_micro):
        home = o % pp
        hops = (home - (pp - 1)) % pp
        if hops == 0:
            continue  # captured directly at emission on the last stage
        t_arrive = (o + pp - 1) + hops
        cap_do[t_arrive, home] = True
        cap_row[t_arrive, home] = o // pp
    return cap_do, cap_row, T


def spmd_pipeline(layer_fn: Callable,
                  stage_params,
                  x: jnp.ndarray,
                  *,
                  topo: MeshTopology,
                  n_micro: int,
                  extras=None):
    """Run stacked layers over the "pipe" axis in pipelined fashion.

    ``layer_fn(stage_local_params, h, extras_mb) -> (h, aux)`` must apply
    this stage's layers to a microbatch of activations ``[mb, S, H]``
    (typically a scan over the local ``L/pp`` stacked layers) and return an
    auxiliary scalar (e.g. the MoE load-balancing loss; 0 for dense).
    ``stage_params`` leaves have a leading layer axis sharded over "pipe".
    ``x``: ``[B, S, H]`` activations after the (replicated) embedding;
    ``B % n_micro == 0``.  ``extras`` is an optional pytree of per-example
    side inputs (leading dim B, e.g. RoPE positions); each stage receives
    the slice belonging to the microbatch it is currently processing.

    Returns ``([B, S, H], aux)`` with activations after all L layers,
    replicated over the pipe axis, and the auxiliary scalar averaged over
    microbatches and summed over stages.
    """
    pp = topo.pp_size
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} not divisible by microbatches {n_micro}"
    mb = b // n_micro
    extras = extras if extras is not None else ()
    if pp == 1:
        return layer_fn(stage_params, x, extras)

    rows = -(-n_micro // pp)
    cap_do_np, cap_row_np, total_ticks = _drain_schedule(n_micro, pp)
    compute_ticks = n_micro + pp - 1

    dtype = x.dtype

    def per_stage(stage_local_params, x_local, extras_local):
        idx = lax.axis_index(PIPE_AXIS)
        x_local = x_local.astype(dtype)
        micro = x_local.reshape((n_micro, mb) + x_local.shape[1:])
        micro_extras = jax.tree.map(
            lambda e: e.reshape((n_micro, mb) + e.shape[1:]), extras_local)
        state = jnp.zeros_like(micro[0])
        # local store of finished microbatches (never permuted) + the
        # single-slot transit buffer carrying one finished microbatch per
        # tick toward its home stage o % pp
        store = jnp.zeros((rows,) + micro.shape[1:], micro.dtype)
        transit = jnp.zeros_like(micro[0])
        cap_do = jnp.asarray(cap_do_np)
        cap_row = jnp.asarray(cap_row_np)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def drain_step(store, transit, out, t):
            """Move the transit slot one hop, capture at home stages, and
            emit this tick's finished microbatch (``out`` on the last
            stage; it goes straight to the store when home == pp-1)."""
            transit = lax.ppermute(transit, PIPE_AXIS, perm)
            o = t - (pp - 1)
            emit = (idx == pp - 1) & (o >= 0) & (o < n_micro)
            direct = emit & (o % pp == pp - 1)
            do_cap = cap_do[t, idx] | direct
            row = jnp.clip(jnp.where(direct, o // pp, cap_row[t, idx]),
                           0, rows - 1)
            val = jnp.where(direct, out.astype(store.dtype), transit)
            cur = lax.dynamic_index_in_dim(store, row, axis=0, keepdims=False)
            store = lax.dynamic_update_index_in_dim(
                store, jnp.where(do_cap, val, cur), row, axis=0)
            # non-home emissions enter the transit slot
            transit = jnp.where(emit & ~direct, out.astype(transit.dtype),
                                transit)
            return store, transit

        def tick(carry, t):
            state, store, transit, aux_acc = carry
            # Stage 0 ingests microbatch t (while t < n_micro); other stages
            # use what arrived from the previous stage.
            inp = micro[jnp.minimum(t, n_micro - 1)]
            feed = jnp.where((idx == 0) & (t < n_micro), 1.0, 0.0).astype(state.dtype)
            h = feed * inp + (1 - feed) * state
            # This stage is processing microbatch t - idx right now.
            cur_mb = jnp.clip(t - idx, 0, n_micro - 1)
            extras_mb = jax.tree.map(lambda e: e[cur_mb], micro_extras)
            out, aux = layer_fn(stage_local_params, h, extras_mb)
            # fill/drain ticks recycle garbage state: only count aux from
            # ticks where this stage held a real microbatch
            useful = (t >= idx) & (t - idx < n_micro)
            aux_acc = aux_acc + jnp.where(useful, aux, 0.0)
            store, transit = drain_step(store, transit, out, t)
            state = lax.ppermute(out, PIPE_AXIS, perm)
            return (state, store, transit, aux_acc), None

        def flush_tick(carry, t):
            store, transit = carry
            store, transit = drain_step(store, transit,
                                        jnp.zeros_like(transit), t)
            return (store, transit), None

        (state, store, transit, aux_acc), _ = lax.scan(
            tick, (state, store, transit, jnp.zeros((), jnp.float32)),
            jnp.arange(compute_ticks))
        # post-compute ticks flush the last in-flight items home
        (store, transit), _ = lax.scan(
            flush_tick, (store, transit),
            jnp.arange(compute_ticks, total_ticks))
        # gather every stage's store and restore batch order: microbatch o
        # lives at (stage o % pp, row o // pp). fp32 across the collective —
        # its VJP is a reduce-scatter, and a bf16 one aborts XLA CPU's
        # AllReducePromotion pass.
        gathered = lax.all_gather(store.astype(jnp.float32), PIPE_AXIS,
                                  axis=0)                    # [pp, rows, ...]
        o = np.arange(n_micro)
        outputs = gathered[o % pp, o // pp].astype(store.dtype)
        aux = lax.psum(aux_acc, PIPE_AXIS) / n_micro
        return outputs.reshape(x_local.shape), aux

    from jax.sharding import PartitionSpec as P

    param_specs = jax.tree.map(lambda _: P(PIPE_AXIS), stage_params)
    extras_specs = jax.tree.map(lambda _: P(), extras)
    out, aux = jax.shard_map(
        per_stage,
        mesh=topo.mesh,
        in_specs=(param_specs, P(), extras_specs),
        out_specs=(P(), P()),
        axis_names={PIPE_AXIS},
        check_vma=False,
        # the replicated activation boundary crosses in fp32: the VJP of a
        # replicated bf16 input is a bf16 psum, which XLA CPU's
        # AllReducePromotion pass aborts on (and fp32 boundary grads are
        # what the embedding wants anyway)
    )(stage_params, x.astype(jnp.float32), extras)
    return out.astype(dtype), aux
