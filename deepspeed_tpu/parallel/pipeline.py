"""Pipeline parallelism via SPMD collective-permute.

TPU-native re-design of ``runtime/pipe/`` (PipelineModule module.py:86,
PipelineEngine engine.py:337, TrainSchedule schedule.py:189, P2P p2p.py):
instead of an instruction-schedule interpreter issuing eager P2P sends
between stage processes, the whole pipeline is ONE ``shard_map`` over the
"pipe" mesh axis:

* layer params are stacked ``[L, ...]`` and sharded over "pipe", so each
  stage holds ``L/pp`` layers — the analog of ``PipelineModule``'s layer
  partitioning ("uniform" method, ref module.py:393);
* microbatches circulate between stages with ``lax.ppermute`` (ICI
  neighbour exchange), the analog of SendActivation/RecvActivation
  (ref engine.py:1016/:1108);
* the schedule is the classic GPipe fill-drain: ``n_micro + pp - 1`` ticks,
  expressed as a differentiable ``lax.scan`` — backward reuses the same
  rotation in reverse (the transpose of ppermute), replacing
  SendGrad/RecvGrad (ref engine.py:1052/:1151).

Other mesh axes (data/tensor/seq/expert) stay in GSPMD "auto" mode inside
the shard_map (jax 0.9 ``axis_names``), so pipeline composes with ZeRO/DP/TP
sharding unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.parallel.topology import PIPE_AXIS, MeshTopology


def spmd_pipeline(layer_fn: Callable,
                  stage_params,
                  x: jnp.ndarray,
                  *,
                  topo: MeshTopology,
                  n_micro: int,
                  extras=None):
    """Run stacked layers over the "pipe" axis in pipelined fashion.

    ``layer_fn(stage_local_params, h, extras_mb) -> h`` must apply this
    stage's layers to a microbatch of activations ``[mb, S, H]`` (typically
    a scan over the local ``L/pp`` stacked layers).  ``stage_params`` leaves
    have a leading layer axis sharded over "pipe".  ``x``: ``[B, S, H]``
    activations after the (replicated) embedding; ``B % n_micro == 0``.
    ``extras`` is an optional pytree of per-example side inputs (leading dim
    B, e.g. RoPE positions); each stage receives the slice belonging to the
    microbatch it is currently processing (microbatch ``t - stage_idx``).

    Returns ``[B, S, H]`` activations after all L layers, replicated over
    the pipe axis.

    NOTE: every stage carries the full outputs accumulator through the scan
    (only the last stage writes it) and the final psum broadcasts it across
    the pipe axis — simple and correct; a ring-drain collection would save
    (pp-1)/pp of that buffer and is a planned optimisation.
    """
    pp = topo.pp_size
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} not divisible by microbatches {n_micro}"
    mb = b // n_micro
    extras = extras if extras is not None else ()
    if pp == 1:
        return layer_fn(stage_params, x, extras)

    def per_stage(stage_local_params, x_local, extras_local):
        idx = lax.axis_index(PIPE_AXIS)
        micro = x_local.reshape((n_micro, mb) + x_local.shape[1:])
        micro_extras = jax.tree.map(
            lambda e: e.reshape((n_micro, mb) + e.shape[1:]), extras_local)
        state = jnp.zeros_like(micro[0])
        outputs = jnp.zeros_like(micro)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 ingests microbatch t (while t < n_micro); other stages
            # use what arrived from the previous stage.
            inp = micro[jnp.minimum(t, n_micro - 1)]
            feed = jnp.where((idx == 0) & (t < n_micro), 1.0, 0.0).astype(state.dtype)
            h = feed * inp + (1 - feed) * state
            # This stage is processing microbatch t - idx right now.
            cur_mb = jnp.clip(t - idx, 0, n_micro - 1)
            extras_mb = jax.tree.map(lambda e: e[cur_mb], micro_extras)
            out = layer_fn(stage_local_params, h, extras_mb)
            # Last stage emits microbatch t-(pp-1): masked dynamic update so
            # non-emitting ticks/stages leave the slot untouched.
            out_t = t - (pp - 1)
            emit = (idx == pp - 1) & (out_t >= 0)
            safe_t = jnp.maximum(out_t, 0)
            cur = lax.dynamic_index_in_dim(outputs, safe_t, axis=0, keepdims=False)
            upd = jnp.where(emit, out.astype(outputs.dtype), cur)
            outputs = lax.dynamic_update_index_in_dim(outputs, upd, safe_t, axis=0)
            state = lax.ppermute(out, PIPE_AXIS, perm)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(tick, (state, outputs),
                                       jnp.arange(n_micro + pp - 1))
        # outputs are valid only on the last stage → broadcast via psum.
        mask = (idx == pp - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, PIPE_AXIS)
        return outputs.reshape(x_local.shape)

    from jax.sharding import PartitionSpec as P

    param_specs = jax.tree.map(lambda _: P(PIPE_AXIS), stage_params)
    extras_specs = jax.tree.map(lambda _: P(), extras)
    return jax.shard_map(
        per_stage,
        mesh=topo.mesh,
        in_specs=(param_specs, P(), extras_specs),
        out_specs=P(),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )(stage_params, x, extras)
