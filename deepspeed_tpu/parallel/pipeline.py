"""Pipeline parallelism via SPMD collective-permute.

TPU-native re-design of ``runtime/pipe/`` (PipelineModule module.py:86,
PipelineEngine engine.py:337, TrainSchedule schedule.py:189, P2P p2p.py):
instead of an instruction-schedule interpreter issuing eager P2P sends
between stage processes, the whole pipeline is ONE ``shard_map`` over the
"pipe" mesh axis:

* layer params are stacked ``[L, ...]`` and sharded over "pipe", so each
  stage holds ``L/pp`` layers — the analog of ``PipelineModule``'s layer
  partitioning ("uniform" method, ref module.py:393);
* microbatches circulate between stages with ``lax.ppermute`` (ICI
  neighbour exchange), the analog of SendActivation/RecvActivation
  (ref engine.py:1016/:1108);
* :func:`spmd_pipeline` is the forward schedule (GPipe fill-drain as a
  differentiable ``lax.scan``); finished microbatches **ring-drain**
  through a single-slot transit buffer to a home stage (``o % pp``), so
  each stage stores ``ceil(n_micro/pp)`` microbatches, drain traffic is
  one microbatch per tick, and a single all-gather at the end replaces
  the old full-buffer psum broadcast.
* :func:`make_pipeline_train_loss` is the **1F1B** training schedule
  (ref TrainSchedule, schedule.py:189): a custom-VJP loss whose forward
  runs a host-precomputed interleaved F/B tick table and produces the
  gradients itself (each backward tick re-linearizes its stage with
  ``jax.vjp`` from an O(pp) input stash), so live activations are
  bounded by pp microbatches per stage instead of n_micro — the defining
  property of 1F1B — and the outer ``jax.grad`` merely rescales the
  stashed grads.

Other mesh axes (data/tensor/seq/expert) stay in GSPMD "auto" mode inside
the shard_map (jax 0.9 ``axis_names``), so pipeline composes with ZeRO/DP/TP
sharding unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.parallel.topology import PIPE_AXIS, MeshTopology
from deepspeed_tpu.utils.jax_compat import shard_map


def _drain_schedule(n_micro: int, pp: int):
    """Static capture schedule for the transit-slot ring drain.

    Finished microbatch ``o`` (emitted by the last stage at tick
    ``o + pp - 1``) travels the ring one hop per tick in a single-slot
    transit buffer until it reaches its home stage ``o % pp``, which
    captures it into row ``o // pp`` of its local (never-permuted) store.
    Emissions are one per tick and every trip is < pp hops, so at most one
    item occupies any stage's transit slot at a time — inter-stage drain
    traffic is one microbatch per tick (the old full-buffer rotation moved
    ceil(n_micro/pp) of them every tick).

    Returns ``(cap_do [T, pp], cap_row [T, pp], T)`` where tick ``t``'s
    entries say whether stage ``s`` captures its incoming transit item
    this tick and into which row; ``T`` includes the post-compute drain
    ticks that flush the last items home.
    """
    compute_ticks = n_micro + pp - 1
    T = compute_ticks + pp - 1
    cap_do = np.zeros((T, pp), np.bool_)
    cap_row = np.zeros((T, pp), np.int32)
    for o in range(n_micro):
        home = o % pp
        hops = (home - (pp - 1)) % pp
        if hops == 0:
            continue  # captured directly at emission on the last stage
        t_arrive = (o + pp - 1) + hops
        cap_do[t_arrive, home] = True
        cap_row[t_arrive, home] = o // pp
    return cap_do, cap_row, T


def spmd_pipeline(layer_fn: Callable,
                  stage_params,
                  x: jnp.ndarray,
                  *,
                  topo: MeshTopology,
                  n_micro: int,
                  extras=None):
    """Run stacked layers over the "pipe" axis in pipelined fashion.

    ``layer_fn(stage_local_params, h, extras_mb) -> (h, aux)`` must apply
    this stage's layers to a microbatch of activations ``[mb, S, H]``
    (typically a scan over the local ``L/pp`` stacked layers) and return an
    auxiliary scalar (e.g. the MoE load-balancing loss; 0 for dense).
    ``stage_params`` leaves have a leading layer axis sharded over "pipe".
    ``x``: ``[B, S, H]`` activations after the (replicated) embedding;
    ``B % n_micro == 0``.  ``extras`` is an optional pytree of per-example
    side inputs (leading dim B, e.g. RoPE positions); each stage receives
    the slice belonging to the microbatch it is currently processing.

    Returns ``([B, S, H], aux)`` with activations after all L layers,
    replicated over the pipe axis, and the auxiliary scalar averaged over
    microbatches and summed over stages.
    """
    pp = topo.pp_size
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} not divisible by microbatches {n_micro}"
    mb = b // n_micro
    extras = extras if extras is not None else ()
    if pp == 1:
        return layer_fn(stage_params, x, extras)

    rows = -(-n_micro // pp)
    cap_do_np, cap_row_np, total_ticks = _drain_schedule(n_micro, pp)
    compute_ticks = n_micro + pp - 1

    dtype = x.dtype

    def per_stage(stage_local_params, x_local, extras_local):
        idx = lax.axis_index(PIPE_AXIS)
        x_local = x_local.astype(dtype)
        micro = x_local.reshape((n_micro, mb) + x_local.shape[1:])
        micro_extras = jax.tree.map(
            lambda e: e.reshape((n_micro, mb) + e.shape[1:]), extras_local)
        state = jnp.zeros_like(micro[0])
        # local store of finished microbatches (never permuted) + the
        # single-slot transit buffer carrying one finished microbatch per
        # tick toward its home stage o % pp
        store = jnp.zeros((rows,) + micro.shape[1:], micro.dtype)
        transit = jnp.zeros_like(micro[0])
        cap_do = jnp.asarray(cap_do_np)
        cap_row = jnp.asarray(cap_row_np)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def drain_step(store, transit, out, t):
            """Move the transit slot one hop, capture at home stages, and
            emit this tick's finished microbatch (``out`` on the last
            stage; it goes straight to the store when home == pp-1)."""
            transit = lax.ppermute(transit, PIPE_AXIS, perm)
            o = t - (pp - 1)
            emit = (idx == pp - 1) & (o >= 0) & (o < n_micro)
            direct = emit & (o % pp == pp - 1)
            do_cap = cap_do[t, idx] | direct
            row = jnp.clip(jnp.where(direct, o // pp, cap_row[t, idx]),
                           0, rows - 1)
            val = jnp.where(direct, out.astype(store.dtype), transit)
            cur = lax.dynamic_index_in_dim(store, row, axis=0, keepdims=False)
            store = lax.dynamic_update_index_in_dim(
                store, jnp.where(do_cap, val, cur), row, axis=0)
            # non-home emissions enter the transit slot
            transit = jnp.where(emit & ~direct, out.astype(transit.dtype),
                                transit)
            return store, transit

        def tick(carry, t):
            state, store, transit, aux_acc = carry
            # Stage 0 ingests microbatch t (while t < n_micro); other stages
            # use what arrived from the previous stage.
            inp = micro[jnp.minimum(t, n_micro - 1)]
            feed = jnp.where((idx == 0) & (t < n_micro), 1.0, 0.0).astype(state.dtype)
            h = feed * inp + (1 - feed) * state
            # This stage is processing microbatch t - idx right now.
            cur_mb = jnp.clip(t - idx, 0, n_micro - 1)
            extras_mb = jax.tree.map(lambda e: e[cur_mb], micro_extras)
            out, aux = layer_fn(stage_local_params, h, extras_mb)
            # fill/drain ticks recycle garbage state: only count aux from
            # ticks where this stage held a real microbatch
            useful = (t >= idx) & (t - idx < n_micro)
            aux_acc = aux_acc + jnp.where(useful, aux, 0.0)
            store, transit = drain_step(store, transit, out, t)
            state = lax.ppermute(out, PIPE_AXIS, perm)
            return (state, store, transit, aux_acc), None

        def flush_tick(carry, t):
            store, transit = carry
            store, transit = drain_step(store, transit,
                                        jnp.zeros_like(transit), t)
            return (store, transit), None

        (state, store, transit, aux_acc), _ = lax.scan(
            tick, (state, store, transit, jnp.zeros((), jnp.float32)),
            jnp.arange(compute_ticks))
        # post-compute ticks flush the last in-flight items home
        (store, transit), _ = lax.scan(
            flush_tick, (store, transit),
            jnp.arange(compute_ticks, total_ticks))
        # gather every stage's store and restore batch order: microbatch o
        # lives at (stage o % pp, row o // pp). fp32 across the collective —
        # its VJP is a reduce-scatter, and a bf16 one aborts XLA CPU's
        # AllReducePromotion pass.
        gathered = lax.all_gather(store.astype(jnp.float32), PIPE_AXIS,
                                  axis=0)                    # [pp, rows, ...]
        o = np.arange(n_micro)
        outputs = gathered[o % pp, o // pp].astype(store.dtype)
        aux = lax.psum(aux_acc, PIPE_AXIS) / n_micro
        return outputs.reshape(x_local.shape), aux

    from jax.sharding import PartitionSpec as P

    param_specs = jax.tree.map(lambda _: P(PIPE_AXIS), stage_params)
    extras_specs = jax.tree.map(lambda _: P(), extras)
    out, aux = shard_map(
        per_stage,
        mesh=topo.mesh,
        in_specs=(param_specs, P(), extras_specs),
        out_specs=(P(), P()),
        axis_names={PIPE_AXIS},
        check_vma=False,
        # the replicated activation boundary crosses in fp32: the VJP of a
        # replicated bf16 input is a bf16 psum, which XLA CPU's
        # AllReducePromotion pass aborts on (and fp32 boundary grads are
        # what the embedding wants anyway)
    )(stage_params, x.astype(jnp.float32), extras)
    return out.astype(dtype), aux


# ----------------------------------------------------------------------
# 1F1B training schedule
# ----------------------------------------------------------------------
def _make_1f1b_schedule(pp: int, m: int):
    """Greedy B-priority 1F1B tick table (ref TrainSchedule,
    runtime/pipe/schedule.py:189).

    Each tick every stage does one unit of work: a Forward for its next
    microbatch (if its predecessor's activation has arrived and fewer than
    pp microbatches are in flight — the 1F1B stash bound) or, preferably, a
    Backward (if the successor's cotangent has arrived; the last stage
    needs only its own forward).  Returns ``(wt, wm)`` int32 ``[T, pp]``:
    work type (0 idle / 1 fwd / 2 bwd) and microbatch index.
    """
    next_f = [0] * pp
    next_b = [0] * pp
    f_tick = [[-1] * m for _ in range(pp)]
    b_tick = [[-1] * m for _ in range(pp)]
    wt_rows, wm_rows = [], []
    t = 0
    while min(next_b) < m:
        wt, wm = [0] * pp, [0] * pp
        for s in range(pp):
            ob, of = next_b[s], next_f[s]
            can_b = ob < m and (
                (s == pp - 1 and 0 <= f_tick[s][ob] < t)
                or (s < pp - 1 and 0 <= b_tick[s + 1][ob] < t))
            can_f = of < m and (of - next_b[s]) < pp and (
                s == 0 or 0 <= f_tick[s - 1][of] < t)
            if can_b:
                wt[s], wm[s] = 2, ob
                b_tick[s][ob] = t
                next_b[s] += 1
            elif can_f:
                wt[s], wm[s] = 1, of
                f_tick[s][of] = t
                next_f[s] += 1
        wt_rows.append(wt)
        wm_rows.append(wm)
        t += 1
        if t > 4 * (m + pp) + 8:
            raise RuntimeError("1F1B schedule did not converge")
    return np.asarray(wt_rows, np.int32), np.asarray(wm_rows, np.int32)


def make_pipeline_train_loss(stage_fn: Callable, tail_fn: Callable,
                             topo: MeshTopology, n_micro: int,
                             aux_coef: float = 0.0,
                             embed_fn: Optional[Callable] = None):
    """Build the 1F1B pipelined training loss.

    ``stage_fn(stage_params, h, extras_mb) -> (h, aux)`` applies one
    stage's layers; ``tail_fn(tail_params, h, labels_mb) -> nll_sum``
    computes the summed token NLL of one microbatch on the last stage's
    output.  The returned callable

        ``loss = f(stage_params, tail_params, x, labels, extras, denom)``
        (or, with ``embed_fn``:
        ``f(stage_params, tail_params, embed_params, ids, labels, extras,
        denom)``)

    computes ``sum(nll)/denom + aux_coef * mean_micro(sum_stage(aux))``
    with a custom VJP: its *forward* runs the interleaved 1F1B tick table
    (so each stage keeps at most pp stashed microbatch inputs — O(pp)
    live activations, vs the GPipe scan's O(n_micro) residuals) and
    already produces the parameter/input gradients; the backward pass
    just scales them by the incoming cotangent.  ``denom`` is the global
    valid-token count (computable from labels before any compute).

    ``embed_fn(embed_params, ids_mb, extras_mb) -> h_mb``, when given,
    moves the embedding prologue *inside* the pipelined region: stage 0
    embeds each microbatch on its forward tick and, on the backward tick,
    converts the microbatch input-cotangent straight into embed-parameter
    gradients (a scatter-add into an O(vocab·H) accumulator).  Without it
    the input cotangent must be returned whole, which costs an
    O(n_micro)·activation ``dx`` stash on every stage — the exact
    anti-pattern 1F1B exists to avoid (ref TrainSchedule intent,
    runtime/pipe/schedule.py:189).
    """
    pp = topo.pp_size
    wt_np, wm_np = _make_1f1b_schedule(pp, n_micro)
    ticks = wt_np.shape[0]
    from jax.sharding import PartitionSpec as P

    def _run(stage_params, tail_params, embed_params, x, labels, extras,
             denom):
        b = x.shape[0]
        assert b % n_micro == 0
        mb = b // n_micro
        if embed_fn is None:
            hstruct = jax.eval_shape(lambda a: a[:mb], x)
        else:
            mb_ids = jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype)
            mb_ex = jax.tree.map(
                lambda e: jax.ShapeDtypeStruct((mb,) + e.shape[1:], e.dtype),
                extras)
            hstruct = jax.eval_shape(embed_fn, embed_params, mb_ids, mb_ex)
        dtype = hstruct.dtype

        def per_stage(sp, tp, ep, x_local, labels_local, extras_local):
            idx = lax.axis_index(PIPE_AXIS)
            micro = x_local.reshape((n_micro, mb) + x_local.shape[1:])
            lab_micro = labels_local.reshape((n_micro, mb)
                                             + labels_local.shape[1:])
            ex_micro = jax.tree.map(
                lambda e: e.reshape((n_micro, mb) + e.shape[1:]),
                extras_local)
            wt = jnp.asarray(wt_np)
            wm = jnp.asarray(wm_np)
            hshape = hstruct.shape
            fperm = [(i, (i + 1) % pp) for i in range(pp)]
            bperm = [(i, (i - 1) % pp) for i in range(pp)]

            # "acc" is the input-gradient accumulator: with embed_fn the
            # per-microbatch input cotangent is folded into O(vocab·H)
            # embed grads immediately; without it the full-batch dx must
            # be stashed (in the activation dtype — it is cast to x.dtype
            # by f_fwd anyway, so fp32 storage would be pure waste)
            if embed_fn is None:
                acc0 = jnp.zeros((n_micro,) + hshape, dtype)
            else:
                acc0 = jax.tree.map(jnp.zeros_like, ep)
            carry = dict(
                arr_f=jnp.zeros((pp,) + hshape, dtype),   # arrived activations
                arr_b=jnp.zeros((pp,) + hshape, dtype),   # arrived cotangents
                a_in=jnp.zeros((pp,) + hshape, dtype),    # 1F1B input stash
                state_f=jnp.zeros(hshape, dtype),
                state_b=jnp.zeros(hshape, dtype),
                g_sp=jax.tree.map(jnp.zeros_like, sp),
                g_tp=jax.tree.map(jnp.zeros_like, tp),
                acc=acc0,
                nll=jnp.zeros((), jnp.float32),
                aux=jnp.zeros((), jnp.float32),
            )

            def tick(c, t):
                # deliver last tick's ring arrivals per the schedule
                left = jnp.clip(idx - 1, 0, pp - 1)
                right = jnp.clip(idx + 1, 0, pp - 1)
                tm1 = jnp.maximum(t - 1, 0)
                got_f = (t > 0) & (idx > 0) & (wt[tm1, left] == 1)
                got_b = (t > 0) & (idx < pp - 1) & (wt[tm1, right] == 2)
                sf = wm[tm1, left] % pp
                sb = wm[tm1, right] % pp
                arr_f = c["arr_f"].at[sf].set(
                    jnp.where(got_f, c["state_f"], c["arr_f"][sf]))
                arr_b = c["arr_b"].at[sb].set(
                    jnp.where(got_b, c["state_b"], c["arr_b"][sb]))

                my_wt = wt[t, idx]
                my_m = wm[t, idx]
                slot = my_m % pp
                x_mb = micro[my_m]
                lab_mb = lab_micro[my_m]
                ex_mb = jax.tree.map(lambda e: e[my_m], ex_micro)

                def stage0_input():
                    return x_mb if embed_fn is None else embed_fn(ep, x_mb,
                                                                  ex_mb)

                def idle(op):
                    a_in, g_sp, g_tp, acc, nll, aux = op
                    return (jnp.zeros(hshape, dtype), jnp.zeros(hshape, dtype),
                            a_in, g_sp, g_tp, acc, nll, aux)

                def fwd_work(op):
                    a_in, g_sp, g_tp, acc, nll, aux = op
                    h_f_in = jnp.where(idx == 0,
                                       stage0_input().astype(dtype),
                                       arr_f[slot])
                    a_in = a_in.at[slot].set(h_f_in)
                    h_out, _ = stage_fn(sp, h_f_in, ex_mb)
                    return (h_out.astype(dtype), jnp.zeros(hshape, dtype),
                            a_in, g_sp, g_tp, acc, nll, aux)

                def bwd_work(op):
                    a_in, g_sp, g_tp, acc, nll, aux = op
                    h_in = a_in[slot]
                    last_stage = idx == pp - 1

                    def stage_plus(sp_, tp_, h_):
                        h_out, aux_ = stage_fn(sp_, h_, ex_mb)
                        # the [mb,S,V] head projection + NLL only exists on
                        # the last stage; other stages skip it entirely
                        # (no collectives inside, so cond is safe here)
                        nll_ = lax.cond(
                            last_stage,
                            lambda h: tail_fn(tp_, h, lab_mb),
                            lambda h: jnp.zeros((), jnp.float32),
                            h_out)
                        return h_out, aux_, nll_

                    (h_out, aux_v, nll_v), pull = jax.vjp(
                        stage_plus, sp, tp, h_in)
                    last = idx == pp - 1
                    d_h = jnp.where(last, jnp.zeros_like(h_out),
                                    arr_b[slot].astype(h_out.dtype))
                    d_aux = jnp.asarray(aux_coef / n_micro, aux_v.dtype)
                    d_nll = jnp.where(last, 1.0 / denom,
                                      0.0).astype(nll_v.dtype)
                    d_sp, d_tp, d_hin = pull((d_h, d_aux, d_nll))
                    g_sp = jax.tree.map(jnp.add, g_sp, d_sp)
                    g_tp = jax.tree.map(jnp.add, g_tp, d_tp)
                    if embed_fn is None:
                        acc = acc.at[my_m].set(
                            jnp.where(idx == 0, d_hin.astype(dtype),
                                      acc[my_m]))
                    else:
                        # stage 0 folds the input cotangent straight into
                        # embed grads; other stages contribute zeros (the
                        # cotangent is masked, not the — collective-free —
                        # vjp computation, so lax.switch stays safe)
                        d_emb = jnp.where(idx == 0, d_hin,
                                          jnp.zeros_like(d_hin))
                        _, pull_e = jax.vjp(
                            lambda ep_: embed_fn(ep_, x_mb, ex_mb)
                            .astype(d_hin.dtype), ep)
                        (d_ep,) = pull_e(d_emb)
                        acc = jax.tree.map(jnp.add, acc, d_ep)
                    nll = nll + jnp.where(last, nll_v.astype(jnp.float32), 0.0)
                    aux = aux + aux_v.astype(jnp.float32)
                    return (jnp.zeros(hshape, dtype), d_hin.astype(dtype),
                            a_in, g_sp, g_tp, acc, nll, aux)

                op = (c["a_in"], c["g_sp"], c["g_tp"], c["acc"], c["nll"],
                      c["aux"])
                send_f, send_b, a_in, g_sp, g_tp, acc, nll, aux = lax.switch(
                    my_wt, [idle, fwd_work, bwd_work], op)
                return dict(
                    arr_f=arr_f, arr_b=arr_b, a_in=a_in,
                    state_f=lax.ppermute(send_f, PIPE_AXIS, fperm),
                    state_b=lax.ppermute(send_b, PIPE_AXIS, bperm),
                    g_sp=g_sp, g_tp=g_tp, acc=acc, nll=nll, aux=aux), None

            c, _ = lax.scan(tick, carry, jnp.arange(ticks))
            nll = lax.psum(c["nll"], PIPE_AXIS)          # last stage only
            aux = lax.psum(c["aux"], PIPE_AXIS) / n_micro
            loss = nll / denom + aux_coef * aux
            g_tp = jax.tree.map(lambda a: lax.psum(a, PIPE_AXIS), c["g_tp"])
            # stage 0 only contributes; fp32 across the collective (a bf16
            # psum aborts XLA CPU's AllReducePromotion pass)
            acc = jax.tree.map(
                lambda a: lax.psum(a.astype(jnp.float32), PIPE_AXIS)
                .astype(a.dtype), c["acc"])
            if embed_fn is None:
                acc = acc.reshape(x_local.shape)
            return loss, c["g_sp"], g_tp, acc

        sp_specs = jax.tree.map(lambda _: P(PIPE_AXIS), stage_params)
        tp_specs = jax.tree.map(lambda _: P(), tail_params)
        ep_specs = jax.tree.map(lambda _: P(), embed_params)
        ex_specs = jax.tree.map(lambda _: P(), extras)
        acc_specs = (P() if embed_fn is None
                     else jax.tree.map(lambda _: P(), embed_params))
        return shard_map(
            per_stage,
            mesh=topo.mesh,
            in_specs=(sp_specs, tp_specs, ep_specs, P(), P(), ex_specs),
            out_specs=(P(), sp_specs, tp_specs, acc_specs),
            axis_names={PIPE_AXIS},
            check_vma=False,
        )(stage_params, tail_params, embed_params, x, labels, extras)

    def _primal(stage_params, tail_params, embed_params, x, labels, extras,
                denom):
        # loss-only (non-differentiated) calls — e.g. eval_batch — take the
        # plain GPipe forward instead of paying the full fwd+bwd tick table;
        # mathematically identical: tail NLL is per-token additive, and
        # spmd_pipeline's aux is the same psum/n_micro statistic
        def wrap(sp, h, ex):
            return stage_fn(sp, h, ex)

        if embed_fn is not None:
            # embed per microbatch (vmapped), exactly as _run's stage-0
            # ticks do — so extras that carry per-microbatch state (e.g.
            # dropout key rows, whose row 0 per slice is that microbatch's
            # key) draw the same masks on this loss-only path as on the
            # differentiated 1F1B path
            b = x.shape[0]
            mb = b // n_micro
            resh = lambda a: a.reshape((n_micro, mb) + a.shape[1:])
            x_mb = jax.vmap(embed_fn, in_axes=(None, 0, 0))(
                embed_params, resh(x), jax.tree.map(resh, extras))
            x = x_mb.reshape((b,) + x_mb.shape[2:])
        h, aux = spmd_pipeline(wrap, stage_params, x, topo=topo,
                               n_micro=n_micro, extras=extras)
        return tail_fn(tail_params, h, labels) / denom + aux_coef * aux

    if embed_fn is None:

        @jax.custom_vjp
        def f(stage_params, tail_params, x, labels, extras, denom):
            return _primal(stage_params, tail_params, (), x, labels, extras,
                           denom)

        def f_fwd(stage_params, tail_params, x, labels, extras, denom):
            loss, g_sp, g_tp, dx = _run(stage_params, tail_params, (), x,
                                        labels, extras, denom)
            return loss, (g_sp, g_tp, dx.astype(x.dtype))

        def f_bwd(res, g):
            g_sp, g_tp, dx = res

            def scale(tree):
                return jax.tree.map(lambda a: (a * g).astype(a.dtype), tree)

            return (scale(g_sp), scale(g_tp), scale(dx), None, None, None)

        f.defvjp(f_fwd, f_bwd)
        return f

    @jax.custom_vjp
    def f(stage_params, tail_params, embed_params, ids, labels, extras,
          denom):
        return _primal(stage_params, tail_params, embed_params, ids, labels,
                       extras, denom)

    def f_fwd(stage_params, tail_params, embed_params, ids, labels, extras,
              denom):
        loss, g_sp, g_tp, g_ep = _run(stage_params, tail_params,
                                      embed_params, ids, labels, extras,
                                      denom)
        return loss, (g_sp, g_tp, g_ep)

    def f_bwd(res, g):
        g_sp, g_tp, g_ep = res

        def scale(tree):
            return jax.tree.map(lambda a: (a * g).astype(a.dtype), tree)

        return (scale(g_sp), scale(g_tp), scale(g_ep), None, None, None,
                None)

    f.defvjp(f_fwd, f_bwd)
    return f
