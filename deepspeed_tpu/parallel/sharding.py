"""Parameter sharding rules — compatibility shim.

The implementation moved to :mod:`deepspeed_tpu.resilience.oracle`: the
name-based spec derivation is now the :class:`PartitionOracle`, the ONE
source of partition specs shared by engine init, checkpoint save/load
and the serving replicas (docs/ELASTICITY.md).  ``ShardingRules`` is the
same class under its historical name; importing from here keeps every
existing call site working without a second derivation existing
anywhere.
"""

from __future__ import annotations

from deepspeed_tpu.resilience.oracle import (DEFAULT_RULES,  # noqa: F401
                                             PartitionOracle, ShardingRules,
                                             path_str)

__all__ = ["ShardingRules", "PartitionOracle", "DEFAULT_RULES", "path_str"]
