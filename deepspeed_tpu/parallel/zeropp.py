"""ZeRO++ — quantized & hierarchical ZeRO communication.

TPU-native realisation of the three ZeRO++ techniques (ref
``runtime/zero/config.py:300-313``, ``csrc/quantization/swizzled_quantize.cu``,
``runtime/comm/coalesced_collectives.py:31``):

* **qwZ** (``zero_quantized_weights``): the stage-3 parameter all-gather
  moves int8 blocks + scales instead of bf16.  Here the param shard is
  block-quantized while still sharded, the *int8* arrays are resharded to
  the gathered layout (XLA lowers that constraint to an all-gather of the
  int8 payload — the qwZ bandwidth win), then dequantized locally.
  Gradients flow straight-through to the original params.
* **hpZ** (``zero_hpz_partition_size``): params shard only over the inner
  ("subdata") factor of the DP world and replicate across the outer factor,
  so fwd/bwd gathers ride ICI within a node — realised purely as shardings
  (see ShardingRules.secondary_mode="hpz", parallel/sharding.py).
* **qgZ** (``zero_quantized_gradients``): int8 two-level all-to-all gradient
  reduction — ``comm/coalesced_collectives.all_to_all_quant_reduce``; the
  engine's compressed-DP mode wires it into the train step.

MiCS (ref runtime/zero/mics.py) reuses the same factored mesh with
secondary_mode="mics": params AND optimizer state shard within the
sub-group only.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.sharding import ShardingRules


def gathered_rules(rules: ShardingRules) -> ShardingRules:
    """Sharding rules for the *gathered* (compute-time) layout: tensor/
    pipe/expert sharding kept, ZeRO fsdp sharding removed."""
    return ShardingRules(rules.topo, zero_stage=0,
                         rules=[(p.pattern, d) for p, d in rules.rules],
                         shard_norms=rules.shard_norms)


def qwz_weight_gather(params: Any, rules: ShardingRules,
                      num_bits: int = 8, group_size: int = 256,
                      wire_dtype: str = "int8") -> Any:
    """Quantized stage-3 weight gather with straight-through gradients.

    Apply inside the jitted train step to the (fsdp-sharded) params before
    the loss: the resharding constraint sits between quantize and
    dequantize, so the all-gather XLA inserts moves the quantized payload
    + scales — the same wire format as qwZ's quantized_gather (ref
    partition_parameters.py:823 CUDAQuantizer + all_gather_coalesced).

    ``wire_dtype``: "int8" (qwZ classic) or "fp8" (float8_e4m3fn blocks,
    bitcast to uint8 around the resharding constraint so the gather moves
    plain bytes on every backend) — selected by the ``comm_quantization``
    config block's ``zero3_gather`` entry.
    """
    from deepspeed_tpu.comm.quantized import (_wire_decode, _wire_encode,
                                              validate_wire_dtype)

    validate_wire_dtype(wire_dtype)
    if wire_dtype == "fp32":
        return params
    g_rules = gathered_rules(rules)
    mesh = rules.topo.mesh

    def one(path, p):
        if p.ndim == 0 or p.size < group_size:
            return p
        from deepspeed_tpu.parallel.sharding import path_str

        spec = g_rules.spec_for(path_str(path), p.shape, param_style=True)
        gs = group_size if p.shape[-1] % group_size == 0 else p.shape[-1]
        # backend="jnp" is load-bearing: this runs in-jit on SHARDED
        # params — GSPMD partitions the jnp ops and fuses them into the
        # quantized all-gather, while a pallas_call here would not
        # partition automatically (it would force a gather of the bf16
        # payload, exactly what qwZ exists to avoid)
        q, s = _wire_encode(p.astype(jnp.float32), wire_dtype, gs,
                            backend="jnp", num_bits=num_bits)
        q = lax.with_sharding_constraint(q, NamedSharding(mesh, spec))
        s_spec = P(*(list(spec)[:-1] + [None])) if len(spec) else P()
        s = lax.with_sharding_constraint(s, NamedSharding(mesh, s_spec))
        w = _wire_decode(q, s, wire_dtype, backend="jnp").astype(p.dtype)
        # straight-through: forward sees quantized-gathered weights, grads
        # flow to the master param untouched
        return p + lax.stop_gradient(w - p)

    return jax.tree_util.tree_map_with_path(one, params)
