"""`dstpu_report` — environment/compatibility report.

Analog of the reference's ``ds_report`` (``deepspeed/env_report.py``):
prints framework version, JAX/backend versions, visible devices, memory,
and which optional native/host ops are usable (AIO library, host-offload
support), mirroring the reference's op-compatibility table.
"""

from __future__ import annotations

import importlib
import os
import shutil
import sys

GREEN_OK = "[OKAY]"
RED_NO = "[NO]"


def _try_version(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "unknown")
    except Exception:
        return "not installed"


def op_compat_report() -> "list[tuple[str, bool, str]]":
    """(op name, usable, detail) rows — analog of ds_report's op table."""
    rows = []
    # AIO: our csrc/aio host library
    try:
        from deepspeed_tpu.ops.aio import aio_available
        ok = aio_available()
        rows.append(("async_io (csrc/aio)", ok, "" if ok else "build csrc/aio"))
    except Exception as e:  # pragma: no cover
        rows.append(("async_io (csrc/aio)", False, str(e)))
    # Pallas flash attention
    try:
        importlib.import_module("jax.experimental.pallas.ops.tpu.flash_attention")
        rows.append(("pallas_flash_attention", True, ""))
    except Exception as e:
        rows.append(("pallas_flash_attention", False, str(e)))
    # Host offload (memory kinds)
    try:
        import jax
        kinds = sorted({m.kind for m in jax.devices()[0].addressable_memories()}) \
            if jax.devices() else []
        ok = "pinned_host" in kinds or "unpinned_host" in kinds
        rows.append(("host_offload (memory kinds)", ok, ",".join(kinds)))
    except Exception as e:  # pragma: no cover
        rows.append(("host_offload (memory kinds)", False, str(e)))
    # Native toolchain for building host ops
    for tool in ("g++", "cmake", "ninja"):
        rows.append((f"toolchain:{tool}", shutil.which(tool) is not None, ""))
    return rows


def report_lines() -> "list[str]":
    import deepspeed_tpu

    lines = []
    lines.append("-" * 66)
    lines.append("deepspeed_tpu environment report")
    lines.append("-" * 66)
    lines.append(f"deepspeed_tpu ......... {deepspeed_tpu.__version__}")
    lines.append(f"python ................ {sys.version.split()[0]}")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        lines.append(f"{mod:<22} {_try_version(mod)}")
    try:
        import jax
        devs = jax.devices()
        lines.append(f"backend ............... {devs[0].platform if devs else 'none'}")
        lines.append(f"devices ............... {len(devs)}"
                     + (f" × {devs[0].device_kind}" if devs else ""))
        lines.append(f"process ............... {jax.process_index()}/{jax.process_count()}")
    except Exception as e:  # pragma: no cover
        lines.append(f"backend ............... error: {e}")
    lines.append("-" * 66)
    lines.append("op compatibility")
    for name, ok, detail in op_compat_report():
        status = GREEN_OK if ok else RED_NO
        lines.append(f"{name:<34} {status:<7} {detail}")
    lines.append("-" * 66)
    env_keys = [k for k in os.environ if k.startswith(("DSTPU_", "JAX_", "XLA_", "TPU_"))]
    for k in sorted(env_keys):
        lines.append(f"env {k}={os.environ[k]}")
    return lines


def main() -> int:
    # honor JAX_PLATFORMS even when a platform plugin pinned the config
    # (e.g. forced-CPU reporting on a machine whose TPU is held elsewhere)
    try:
        from deepspeed_tpu.utils.platform import honor_jax_platforms_env

        honor_jax_platforms_env()
    except Exception:
        pass
    print("\n".join(report_lines()))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
