"""Accelerator abstraction — runtime device plug-in interface.

TPU-native analog of the reference's ``DeepSpeedAccelerator``
(accelerator/abstract_accelerator.py:10).  The reference exposes ~80 abstract
methods shaped around CUDA semantics (streams, events, caching allocator).
On JAX/XLA those map to:

* streams/events  → XLA's async dispatch queue; ``synchronize`` is
  ``jax.block_until_ready`` / ``device.synchronize_all_activity``.
* memory stats    → PJRT ``device.memory_stats()``.
* RNG             → functional ``jax.random`` keys (a mutable wrapper is
  provided for API parity).
* graph capture   → ``jax.jit`` (everything is a captured graph); the
  reference's ``create_graph/capture_to_graph/replay_graph`` map to jitted
  callables.
* op builder      → ``ops.op_builder`` (C++ host ops via ctypes) and the
  Pallas kernel registry.

Backends: ``tpu`` (also drives any PJRT device incl. GPU) and ``cpu``
(the test/fake backend, mirroring the reference's cpu_accelerator role).
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional, Tuple


class DeepSpeedAccelerator(abc.ABC):
    """Abstract accelerator interface (ref abstract_accelerator.py:10)."""

    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None
        self._compile_backend: Optional[str] = None

    # ------------------------------------------------------------------
    # Identification
    # ------------------------------------------------------------------
    @abc.abstractmethod
    def is_synchronized_device(self) -> bool:
        ...

    def use_host_timers(self) -> bool:
        return self.is_synchronized_device()

    def resolves_data_dependency(self) -> bool:
        # XLA resolves data dependencies inside the compiled program.
        return True

    def handles_memory_backpressure(self) -> bool:
        return False

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name or "unknown"
        return f"{self._name}:{device_index}"

    # ------------------------------------------------------------------
    # Device APIs
    # ------------------------------------------------------------------
    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None):
        ...

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    @abc.abstractmethod
    def set_device(self, device_index: int) -> None:
        ...

    @abc.abstractmethod
    def current_device(self) -> int:
        ...

    def current_device_name(self) -> str:
        return self.device_name(self.current_device())

    @abc.abstractmethod
    def is_available(self) -> bool:
        ...

    # ------------------------------------------------------------------
    # RNG APIs (functional on JAX; these mirror the torch-style surface)
    # ------------------------------------------------------------------
    @abc.abstractmethod
    def random(self):
        ...

    @abc.abstractmethod
    def set_rng_state(self, new_state, device_index: Optional[int] = None) -> None:
        ...

    @abc.abstractmethod
    def get_rng_state(self, device_index: Optional[int] = None):
        ...

    @abc.abstractmethod
    def manual_seed(self, seed: int) -> None:
        ...

    def manual_seed_all(self, seed: int) -> None:
        self.manual_seed(seed)

    def initial_seed(self) -> int:
        raise NotImplementedError

    def default_generator(self, device_index: int):
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Streams/Events — XLA async dispatch analogs
    # ------------------------------------------------------------------
    def Stream(self, *args, **kwargs):
        return NullStream()

    def StreamContext(self, stream):
        return NullContext()

    def stream(self, stream):
        return NullContext()

    def current_stream(self, device_index: Optional[int] = None):
        return NullStream()

    def default_stream(self, device_index: Optional[int] = None):
        return NullStream()

    def Event(self, enable_timing: bool = False, **kwargs):
        return NullEvent(enable_timing=enable_timing)

    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None:
        ...

    # ------------------------------------------------------------------
    # Memory management
    # ------------------------------------------------------------------
    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, int]:
        ...

    def empty_cache(self) -> None:
        pass

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return self.memory_stats(device_index).get("peak_bytes_in_use", 0)

    def reset_max_memory_allocated(self, device_index: Optional[int] = None) -> None:
        pass

    def memory_cached(self, device_index: Optional[int] = None) -> int:
        return self.memory_allocated(device_index)

    def max_memory_cached(self, device_index: Optional[int] = None) -> int:
        return self.max_memory_allocated(device_index)

    def reset_max_memory_cached(self, device_index: Optional[int] = None) -> None:
        pass

    def memory_reserved(self, device_index: Optional[int] = None) -> int:
        return self.memory_stats(device_index).get("bytes_reserved", 0) or \
            self.memory_allocated(device_index)

    def max_memory_reserved(self, device_index: Optional[int] = None) -> int:
        return self.max_memory_allocated(device_index)

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        pass

    @abc.abstractmethod
    def total_memory(self, device_index: Optional[int] = None) -> int:
        ...

    def available_memory(self, device_index: Optional[int] = None) -> int:
        return self.total_memory(device_index) - self.memory_allocated(device_index)

    # ------------------------------------------------------------------
    # Dtype support
    # ------------------------------------------------------------------
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool:
        ...

    def supported_dtypes(self) -> List[Any]:
        import jax.numpy as jnp

        dtypes = [jnp.float32]
        if self.is_fp16_supported():
            dtypes.append(jnp.float16)
        if self.is_bf16_supported():
            dtypes.append(jnp.bfloat16)
        return dtypes

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.is_bf16_supported() else jnp.float32

    def is_triton_supported(self) -> bool:
        return False  # TPU kernels come from Pallas, not Triton

    # ------------------------------------------------------------------
    # Misc
    # ------------------------------------------------------------------
    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        ...

    def communication_backend_version(self) -> str:
        import jax

        return jax.__version__

    def range_push(self, msg: str) -> None:
        """Profiler range start (ref abstract_accelerator.py:190, nvtx)."""
        try:
            import jax.profiler

            tc = jax.profiler.TraceAnnotation(msg)
            tc.__enter__()
            self._ranges().append(tc)
        except Exception:
            pass

    def range_pop(self) -> None:
        stack = self._ranges()
        if not stack:
            # unbalanced pop: warn, don't crash — instrumented code paths
            # with early returns hit this, and dying inside a profiling
            # annotation would turn a bookkeeping slip into an outage.
            # Warn once per process: a balanced hot loop whose pushes
            # silently failed (range_push swallows errors) would
            # otherwise flood the log every iteration
            if not getattr(self, "_unbalanced_pop_warned", False):
                self._unbalanced_pop_warned = True
                from deepspeed_tpu.utils.logging import logger

                logger.warning("range_pop: unbalanced pop — accelerator "
                               "range stack is empty (warning once)")
            return
        try:
            stack.pop().__exit__(None, None, None)
        except Exception:
            pass

    def _ranges(self):
        if not hasattr(self, "_range_stack"):
            self._range_stack = []
        return self._range_stack

    def lazy_call(self, callback) -> None:
        callback()

    def communication_backend(self):
        from deepspeed_tpu import comm

        return comm

    # ------------------------------------------------------------------
    # Graph capture (ref abstract_accelerator.py graph ops) → jax.jit
    # ------------------------------------------------------------------
    def is_graph_capture_supported(self) -> bool:
        return True

    def create_graph(self):
        return _JitGraph()

    def capture_to_graph(self, graph, **kwargs):
        return graph

    def replay_graph(self, graph, *args):
        return graph.replay(*args)

    # ------------------------------------------------------------------
    # Tensor constructors / pinning
    # ------------------------------------------------------------------
    def pin_memory(self, tensor, align_bytes: int = 1):
        import numpy as np

        return np.ascontiguousarray(tensor)

    def is_pinned(self, tensor) -> bool:
        import numpy as np

        return isinstance(tensor, np.ndarray) and tensor.flags["C_CONTIGUOUS"]

    def on_accelerator(self, tensor) -> bool:
        import jax

        return isinstance(tensor, jax.Array)

    # ------------------------------------------------------------------
    # Op builder resolution (ref abstract_accelerator.py op-builder-dir)
    # ------------------------------------------------------------------
    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops"

    def create_op_builder(self, class_name: str):
        from deepspeed_tpu.ops import op_builder

        return getattr(op_builder, class_name, None)

    def get_op_builder(self, class_name: str):
        return self.create_op_builder(class_name)

    def build_extension(self):
        from deepspeed_tpu.ops import op_builder

        return op_builder

    def export_envs(self) -> List[str]:
        return ["JAX_", "XLA_", "LIBTPU", "TPU_"]


class NullStream:
    """CUDA-stream stand-in: XLA owns scheduling; stream ops are no-ops."""

    def synchronize(self) -> None:
        import jax

        jax.effects_barrier()

    def wait_event(self, event) -> None:
        pass

    def wait_stream(self, stream) -> None:
        pass

    def record_event(self, event=None):
        return event or NullEvent()

    def query(self) -> bool:
        return True


class NullContext:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class NullEvent:
    """CUDA-event stand-in; timing events use host wall clock after a
    device barrier (XLA has no device-side timers)."""

    def __init__(self, enable_timing: bool = False):
        self.enable_timing = enable_timing
        self._t: Optional[float] = None

    def record(self, stream=None) -> None:
        import time

        if self.enable_timing:
            import jax

            jax.effects_barrier()
            self._t = time.time()

    def synchronize(self) -> None:
        import jax

        jax.effects_barrier()

    def elapsed_time(self, end_event: "NullEvent") -> float:
        if self._t is None or end_event._t is None:
            return 0.0
        return (end_event._t - self._t) * 1000.0

    def query(self) -> bool:
        return True


class _JitGraph:
    """Graph-capture stand-in: holds a jitted callable (ref CUDA graphs →
    jax.jit compiled executable replay)."""

    def __init__(self):
        self.fn = None

    def capture(self, fn):
        import jax

        self.fn = jax.jit(fn)
        return self.fn

    def replay(self, *args):
        if self.fn is None:
            raise RuntimeError("graph not captured")
        return self.fn(*args)
