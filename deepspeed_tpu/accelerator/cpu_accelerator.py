"""CPU accelerator backend — the test/fake accelerator.

Plays the role of the reference's ``accelerator/cpu_accelerator.py``: lets
every subsystem run on a chip-less machine (JAX CPU backend, optionally with
``--xla_force_host_platform_device_count=N`` for virtual multi-device
meshes), the way the reference's CPU accelerator + gloo enables its CI.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator


class CPU_Accelerator(TPU_Accelerator):

    def __init__(self):
        super().__init__(platform="cpu")
        self._communication_backend_name = "xla-cpu"
        self._peak_rss = 0  # fallback watermark for kernels without VmHWM

    def is_synchronized_device(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return False

    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, int]:
        # PJRT CPU devices report no memory stats; fall back to /proc.
        # Fields are parsed independently: sandboxed kernels (gVisor)
        # omit VmHWM, and the watermark must then be tracked here rather
        # than dropping BOTH numbers.
        try:
            with open("/proc/self/status") as f:
                status = f.read()
            rss_kb = int(status.split("VmRSS:")[1].split()[0])
        except Exception:
            return {}
        bytes_in_use = rss_kb * 1024
        try:
            peak = int(status.split("VmHWM:")[1].split()[0]) * 1024
        except Exception:
            self._peak_rss = max(self._peak_rss, bytes_in_use)
            peak = self._peak_rss
        return {"bytes_in_use": bytes_in_use, "peak_bytes_in_use": peak}

    def total_memory(self, device_index: Optional[int] = None) -> int:
        try:
            pages = os.sysconf("SC_PHYS_PAGES")
            return pages * os.sysconf("SC_PAGE_SIZE")
        except (ValueError, OSError):
            return 0

    def available_memory(self, device_index: Optional[int] = None) -> int:
        try:
            pages = os.sysconf("SC_AVPHYS_PAGES")
            return pages * os.sysconf("SC_PAGE_SIZE")
        except (ValueError, OSError):
            return 0
