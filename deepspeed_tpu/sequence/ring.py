"""Ring attention: context parallelism by rotating K/V blocks over ICI.

The second half of the long-context story (the task the reference covers
with Ulysses all-to-all + FPDT chunking; ring attention is the
blockwise-rotation alternative of Liu et al. 2023): queries stay local to
their sequence shard while K/V blocks travel the "seq" mesh ring one
neighbour per hop (``lax.ppermute``), and a flash-style online softmax
accumulates each visiting block.  Communication per hop is O(S_local·d)
nearest-neighbour traffic that XLA overlaps with the block's attention
compute — and, unlike Ulysses, there is NO heads % sp divisibility
requirement, so it scales past the KV-head count (GQA models with 8 KV
heads on a 16-way context mesh).

Perf-grade inner block: on TPU (or under the Pallas interpreter) each
FORWARD hop is ONE fused flash pass — :func:`flash_carry_block` threads
the online softmax carry (m, l, acc) through the kernel, so no fp32
``[S_l, S_l]`` score block reaches HBM on the forward and causally-dead
tiles are skipped at the grid level.  Off-TPU the same math runs as XLA
einsums (the CPU test mesh), so parity tests cover both paths.  The
BACKWARD hops are currently XLA einsums and do materialize per-hop
score-shaped fp32 intermediates — fusing them through offset-aware
variants of the existing dq/dkv flash kernels is the queued next step
(BENCH_MEASURED_r06.json); until then long-sequence training memory is
bounded by the backward, not the forward.

Causal scheduling: with the default ``contiguous`` placement, hops whose
source block lies entirely in the masked future are skipped outright
(``lax.cond`` around the attend — no score FLOPs), but the ring is
bulk-synchronous per hop so the skip saves energy, not wall-clock (rank 0
idles while rank sp-1 works).  ``placement="striped"`` fixes the load
balance (Striped Attention, arXiv 2311.09431): shard r owns tokens
``r, r+sp, r+2sp, …``, so every hop is a ~half-masked block on every rank
— the flash kernel's tile skipping then halves causal compute uniformly.
Callers feed striped data (:func:`stripe_sequence` /
:func:`unstripe_sequence` are pure global reshapes; the engine applies
them host-side to ids/labels) and positions follow automatically.

Gradients are a hand-written second ring pass (``jax.custom_vjp``): the
forward saves (o, lse) per shard, the backward rotates K/V again and
accumulates dk/dv on buffers that TRAVEL WITH their block, delivered home
by one final ppermute.  Because the forward scan is never differentiated,
no per-hop carry residual ever crosses the shard_map partial-manual
boundary — which is what used to make the XLA SPMD partitioner report an
"involuntary full rematerialization" (a replicated [B, S_l, H] backward
residual) when ring composed with ZeRO-2 on a data×seq mesh.  The saved
(o, lse) are tagged ``checkpoint_name`` "flash_out"/"flash_lse", so the
engine's flash-aware remat policies keep them and the backward never
re-runs the forward ring (see runtime/engine.py's ring policy upgrade).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import (BATCH_AXES, SEQ_AXIS,
                                             get_topology)
from deepspeed_tpu.utils.jax_compat import get_abstract_mesh, shard_map

_NEG = -1e30

PLACEMENTS = ("contiguous", "striped")


class _RingSpec(NamedTuple):
    """Static per-call config (hashable: rides custom_vjp nondiff)."""
    sp: int
    rep: int
    scale: float
    causal: bool
    window: Optional[int]
    placement: str
    use_flash: bool


# ----------------------------------------------------------------------
# Placement helpers
# ----------------------------------------------------------------------
def ring_position_map(s: int, sp: int, placement: str = "contiguous"):
    """Global token position held by each slot of the seq-sharded array
    ([S] int32).  Under ``striped`` placement shard r's slot j holds token
    ``r + sp*j`` — feed the model positions from this map (RoPE/ALiBi stay
    exact) when its inputs went through :func:`stripe_sequence`."""
    if placement == "striped" and sp > 1:
        s_l = s // sp
        i = jnp.arange(s, dtype=jnp.int32)
        return (i // s_l) + sp * (i % s_l)
    return jnp.arange(s, dtype=jnp.int32)


def stripe_sequence(x, sp: int, axis: int = 1):
    """Reorder a GLOBAL sequence-axis array from natural token order to
    striped placement (shard r gets tokens r, r+sp, …).  Pure reshape +
    transpose — apply before sharding (host-side ids/labels, or globally
    before jit).  Works on numpy and jax arrays."""
    if sp <= 1:
        return x
    s = x.shape[axis]
    if s % sp:
        raise ValueError(f"sequence length {s} not divisible by sp={sp}")
    s_l = s // sp
    shape = x.shape
    y = x.reshape(shape[:axis] + (s_l, sp) + shape[axis + 1:])
    return y.swapaxes(axis, axis + 1).reshape(shape)


def unstripe_sequence(x, sp: int, axis: int = 1):
    """Inverse of :func:`stripe_sequence`."""
    if sp <= 1:
        return x
    s = x.shape[axis]
    if s % sp:
        raise ValueError(f"sequence length {s} not divisible by sp={sp}")
    s_l = s // sp
    shape = x.shape
    y = x.reshape(shape[:axis] + (sp, s_l) + shape[axis + 1:])
    return y.swapaxes(axis, axis + 1).reshape(shape)


def _block_positions(block_idx, s_l: int, sp: int, placement: str):
    """Traced [s_l] global positions of the block owned by ``block_idx``."""
    i = jnp.arange(s_l, dtype=jnp.int32)
    if placement == "striped":
        return block_idx + sp * i
    return block_idx * s_l + i


def _block_bounds(block_idx, s_l: int, sp: int, placement: str):
    """Traced (lo, hi) global position range of a block (strides > 0)."""
    if placement == "striped":
        return block_idx, block_idx + sp * (s_l - 1)
    return block_idx * s_l, block_idx * s_l + s_l - 1


def _hop_dead(idx, src, s_l: int, spec: _RingSpec):
    """Whether the (query block idx, key block src) hop contributes
    nothing: the source block is entirely in the causal future, or
    entirely older than the sliding window."""
    q_lo, q_hi = _block_bounds(idx, s_l, spec.sp, spec.placement)
    k_lo, k_hi = _block_bounds(src, s_l, spec.sp, spec.placement)
    dead = jnp.bool_(False)
    if spec.causal:
        dead |= k_lo > q_hi
    if spec.window is not None:
        dead |= q_lo - k_hi >= spec.window
    return dead


def _kernel_enabled() -> bool:
    """Run the Pallas carry kernel: on TPU, or whenever the flash module's
    INTERPRET flag is up (CPU parity tests)."""
    import importlib

    # the ops.pallas package re-exports the flash_mha *function* under the
    # same name as its submodule — resolve the module itself
    fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")
    if fm.INTERPRET:
        return True
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover - no backend at trace time
        return False


# ----------------------------------------------------------------------
# Local (per-shard) forward: XLA einsum path and Pallas flash path.
# Both return (o [b, s_l, nh, d], lse [b, nkv, rep, s_l] fp32).
# ----------------------------------------------------------------------
def _ring_fwd_xla(ql, kl, vl, spec: _RingSpec):
    b, s_l, nh, d = ql.shape
    nkv = kl.shape[2]
    rep = spec.rep
    # Only masked variants need the shard's ring position; dense
    # bidirectional hops never touch axis_index (whose partition-id
    # lowering old SPMD partitioners reject when it ends up dead code).
    masked = spec.causal or spec.window is not None
    idx = lax.axis_index(SEQ_AXIS) if masked else jnp.int32(0)
    # grouped-head layout: K/V stay at nkv heads END TO END — they travel
    # the ring UNREPEATED and feed the einsums unexpanded (per-hop ICI
    # traffic and per-hop HBM are both O(S_l·nkv·d))
    q5 = ql.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
    q_pos = _block_positions(idx, s_l, spec.sp, spec.placement)
    perm = [(i, (i + 1) % spec.sp) for i in range(spec.sp)]

    def attend(m, l, acc, kc, vc, src):
        k_pos = _block_positions(src, s_l, spec.sp, spec.placement)
        s = jnp.einsum("bqcgd,bscd->bcgqs", q5,
                       kc.astype(jnp.float32)) * spec.scale
        valid = jnp.ones((s_l, s_l), bool)
        if spec.causal:
            valid = q_pos[:, None] >= k_pos[None, :]
        if spec.window is not None:
            valid &= (q_pos[:, None] - k_pos[None, :]) < spec.window
        vm = valid[None, None, None]
        s = jnp.where(vm, s, _NEG)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        # exp(NEG - NEG) would be 1 on fully-masked rows — zero the masked
        # probabilities explicitly
        p = jnp.where(vm, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bcgqs,bscd->bcgqd", p, vc.astype(jnp.float32))
        return m_new, l, acc

    def maybe_attend(m, l, acc, kc, vc, src):
        if not masked:
            return attend(m, l, acc, kc, vc, src)
        return lax.cond(_hop_dead(idx, src, s_l, spec),
                        lambda: (m, l, acc),
                        lambda: attend(m, l, acc, kc, vc, src))

    def hop(carry, t):
        m, l, acc, kc, vc = carry
        src = lax.rem(idx - t + spec.sp, spec.sp)
        m, l, acc = maybe_attend(m, l, acc, kc, vc, src)
        kc = lax.ppermute(kc, SEQ_AXIS, perm)
        vc = lax.ppermute(vc, SEQ_AXIS, perm)
        return (m, l, acc, kc, vc), None

    m0 = jnp.full((b, nkv, rep, s_l, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((b, nkv, rep, s_l, 1), jnp.float32)
    a0 = jnp.zeros((b, nkv, rep, s_l, d), jnp.float32)
    # sp-1 hops permute after attending; the LAST block attends without
    # the dead ring rotation (a collective inside scan that XLA cannot
    # eliminate)
    (m, l, acc, kc, vc), _ = lax.scan(
        hop, (m0, l0, a0, kl, vl), jnp.arange(spec.sp - 1))
    src_last = lax.rem(idx + 1, spec.sp)
    m, l, acc = maybe_attend(m, l, acc, kc, vc, src_last)
    out = acc / jnp.maximum(l, 1e-20)            # [b, nkv, rep, q, d]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, s_l, nh, d)
    lse = (m + jnp.log(jnp.maximum(l, 1e-20)))[..., 0]  # [b, nkv, rep, q]
    return out.astype(ql.dtype), lse


def _ring_fwd_flash(ql, kl, vl, spec: _RingSpec):
    """Same contract as :func:`_ring_fwd_xla` with the per-hop attend
    fused into one Pallas pass (flash_carry_block): the carry (m, l, acc)
    lives in HBM between hops, aliased in place, and dead tiles cost
    neither VPU masking nor MXU FLOPs."""
    from deepspeed_tpu.ops.pallas.flash_mha import (flash_carry_block,
                                                    ring_carry_pad)

    b, s_l, nh, d = ql.shape
    nkv = kl.shape[2]
    masked = spec.causal or spec.window is not None
    idx = lax.axis_index(SEQ_AXIS) if masked else jnp.int32(0)
    stride = spec.sp if spec.placement == "striped" else 1
    s_pad = ring_carry_pad(s_l)

    def to_kernel(x):  # [b, s, h, d] -> [b, h, s_pad, d]
        x = x.swapaxes(1, 2)
        if s_pad != s_l:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s_l), (0, 0)))
        return x

    qk, kk, vk = to_kernel(ql), to_kernel(kl), to_kernel(vl)
    q_off = (idx if spec.placement == "striped"
             else idx * s_l).astype(jnp.int32)
    perm = [(i, (i + 1) % spec.sp) for i in range(spec.sp)]

    def attend(m, l, acc, kc, vc, src):
        k_off = (src if spec.placement == "striped"
                 else src * s_l).astype(jnp.int32)
        return flash_carry_block(
            qk, kc, vc, m, l, acc, q_off, k_off, q_stride=stride,
            k_stride=stride, s_real=s_l, sm_scale=spec.scale,
            causal=spec.causal, window=spec.window)

    def maybe_attend(m, l, acc, kc, vc, src):
        if not masked:
            return attend(m, l, acc, kc, vc, src)
        return lax.cond(_hop_dead(idx, src, s_l, spec),
                        lambda: (m, l, acc),
                        lambda: attend(m, l, acc, kc, vc, src))

    def hop(carry, t):
        m, l, acc, kc, vc = carry
        src = lax.rem(idx - t + spec.sp, spec.sp)
        m, l, acc = maybe_attend(m, l, acc, kc, vc, src)
        kc = lax.ppermute(kc, SEQ_AXIS, perm)
        vc = lax.ppermute(vc, SEQ_AXIS, perm)
        return (m, l, acc, kc, vc), None

    m0 = jnp.full((b, nh, s_pad, 128), _NEG, jnp.float32)
    l0 = jnp.zeros((b, nh, s_pad, 128), jnp.float32)
    a0 = jnp.zeros((b, nh, s_pad, d), jnp.float32)
    (m, l, acc, kc, vc), _ = lax.scan(
        hop, (m0, l0, a0, kk, vk), jnp.arange(spec.sp - 1))
    src_last = lax.rem(idx + 1, spec.sp)
    m, l, acc = maybe_attend(m, l, acc, kc, vc, src_last)

    m1 = m[:, :, :s_l, 0]                                # [b, nh, s_l]
    l1 = l[:, :, :s_l, 0]
    out = acc[:, :, :s_l] / jnp.maximum(l1, 1e-20)[..., None]
    out = out.swapaxes(1, 2).astype(ql.dtype)            # [b, s_l, nh, d]
    lse = m1 + jnp.log(jnp.maximum(l1, 1e-20))           # [b, nh, s_l]
    lse = lse.reshape(b, nkv, spec.rep, s_l)
    return out, lse


# ----------------------------------------------------------------------
# custom_vjp: forward ring + hand-written backward ring
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ring_local(ql, kl, vl, spec: _RingSpec):
    o, _ = (_ring_fwd_flash if spec.use_flash else _ring_fwd_xla)(
        ql, kl, vl, spec)
    return checkpoint_name(o, "flash_out")


def _ring_fwd_rule(ql, kl, vl, spec: _RingSpec):
    o, lse = (_ring_fwd_flash if spec.use_flash else _ring_fwd_xla)(
        ql, kl, vl, spec)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (ql, kl, vl, o, lse)


def _ring_bwd_rule(spec: _RingSpec, res, do):
    """Flash-style ring backward: with the forward's (o, lse) saved, each
    hop recomputes only its own p = exp(s - lse) block and accumulates
    dq locally while dk/dv TRAVEL WITH their K/V block; one final
    ppermute delivers them to their owner shard.  Dead hops (fully-masked
    source blocks) are skipped like the forward.

    The per-hop grads are XLA einsums (s/p/dp/ds are score-shaped fp32
    transients, ~4·s_l²·nkv·rep·4 B per hop) — the fused-kernel backward
    (offset-aware dq/dkv flash kernels) is the queued follow-up; see the
    module docstring."""
    ql, kl, vl, o, lse = res
    masked = spec.causal or spec.window is not None
    idx = lax.axis_index(SEQ_AXIS) if masked else jnp.int32(0)
    b, s_l, nh, d = ql.shape
    nkv = kl.shape[2]
    rep = spec.rep
    q5 = ql.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
    do5 = do.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
    o5 = o.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
    # delta = sum(do * o) per query row — [b, nkv, rep, s_l, 1]
    delta = jnp.sum(do5 * o5, axis=-1).transpose(0, 2, 3, 1)[..., None]
    lse_ = lse[..., None]                            # [b, nkv, rep, s_l, 1]
    q_pos = _block_positions(idx, s_l, spec.sp, spec.placement)
    perm = [(i, (i + 1) % spec.sp) for i in range(spec.sp)]

    def hop_grads(kc, vc, src):
        k_pos = _block_positions(src, s_l, spec.sp, spec.placement)
        kf = kc.astype(jnp.float32)
        vf = vc.astype(jnp.float32)
        s = jnp.einsum("bqcgd,bscd->bcgqs", q5, kf) * spec.scale
        valid = jnp.ones((s_l, s_l), bool)
        if spec.causal:
            valid = q_pos[:, None] >= k_pos[None, :]
        if spec.window is not None:
            valid &= (q_pos[:, None] - k_pos[None, :]) < spec.window
        vm = valid[None, None, None]
        p = jnp.where(vm, jnp.exp(s - lse_), 0.0)    # [b, c, g, q, s]
        dv_c = jnp.einsum("bcgqs,bqcgd->bscd", p, do5)
        dp = jnp.einsum("bqcgd,bscd->bcgqs", do5, vf)
        ds = p * (dp - delta) * spec.scale
        dq_c = jnp.einsum("bcgqs,bscd->bqcgd", ds, kf)
        dk_c = jnp.einsum("bcgqs,bqcgd->bscd", ds, q5)
        return dq_c, dk_c, dv_c

    def maybe_grads(kc, vc, src, zq, zk, zv):
        if not masked:
            return hop_grads(kc, vc, src)
        return lax.cond(_hop_dead(idx, src, s_l, spec),
                        lambda: (zq, zk, zv),
                        lambda: hop_grads(kc, vc, src))

    zq = jnp.zeros((b, s_l, nkv, rep, d), jnp.float32)
    zk = jnp.zeros((b, s_l, nkv, d), jnp.float32)

    def hop(carry, t):
        dq, dk_t, dv_t, kc, vc = carry
        src = lax.rem(idx - t + spec.sp, spec.sp)
        dq_c, dk_c, dv_c = maybe_grads(kc, vc, src, zq, zk, zk)
        dq = dq + dq_c
        dk_t = dk_t + dk_c
        dv_t = dv_t + dv_c
        # K/V and their accumulated grads rotate together
        kc = lax.ppermute(kc, SEQ_AXIS, perm)
        vc = lax.ppermute(vc, SEQ_AXIS, perm)
        dk_t = lax.ppermute(dk_t, SEQ_AXIS, perm)
        dv_t = lax.ppermute(dv_t, SEQ_AXIS, perm)
        return (dq, dk_t, dv_t, kc, vc), None

    (dq, dk_t, dv_t, kc, vc), _ = lax.scan(
        hop, (zq, zk, zk, kl, vl), jnp.arange(spec.sp - 1))
    src_last = lax.rem(idx + 1, spec.sp)
    dq_c, dk_c, dv_c = maybe_grads(kc, vc, src_last, zq, zk, zk)
    dq = dq + dq_c
    # the traveling grads sit one rank behind their owner — deliver home
    dk_t = lax.ppermute(dk_t + dk_c, SEQ_AXIS, perm)
    dv_t = lax.ppermute(dv_t + dv_c, SEQ_AXIS, perm)
    return (dq.reshape(b, s_l, nh, d).astype(ql.dtype),
            dk_t.astype(kl.dtype), dv_t.astype(vl.dtype))


_ring_local.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ----------------------------------------------------------------------
# Public entry
# ----------------------------------------------------------------------
def ring_attention(q, k, v, topo=None, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   window: Optional[int] = None,
                   placement: str = "contiguous"):
    """q/k/v: [B, S, H, D] GLOBAL arrays with S sharded over "seq".
    Returns [B, S, H, D].  GQA KV heads travel the ring unrepeated.  Must
    be called under jit (shard_map manual over the seq + batch axes; on
    current jax the head/tensor dims stay in GSPMD auto mode, while the
    0.4.x compat fallback runs fully manual and replicates tensor-sharded
    heads into each seq shard — see utils/jax_compat.shard_map).

    ``placement``: how sequence blocks map to shards — "contiguous"
    (shard r owns rows [r·S_l, (r+1)·S_l)) or "striped" (shard r owns
    rows r, r+sp, …; the causal-load-balanced layout — see module
    docstring; the caller must feed striped data, cf.
    :func:`stripe_sequence`)."""
    topo = topo or get_topology()
    sp = topo.sp_size if topo is not None else 1
    nh, nkv = q.shape[2], k.shape[2]
    if nh % nkv:
        raise ValueError(
            f"ring_attention: num_heads={nh} not divisible by "
            f"kv_heads={nkv} — GQA requires an integer group size")
    if window is not None:
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        if not causal:
            raise ValueError(
                "window without causal would be a ONE-SIDED band "
                "(key ∈ (qpos-window, qpos+∞)), which is almost never "
                "intended; pass causal=True for Mistral-style sliding "
                "windows")
    if placement not in PLACEMENTS:
        raise ValueError(f"placement={placement!r}: expected one of "
                         f"{PLACEMENTS}")
    rep = nh // nkv
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    if sp == 1:
        if rep != 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return _block_attend_single(q, k, v, scale, causal, window)

    spec = _RingSpec(sp=sp, rep=rep, scale=float(scale), causal=causal,
                     window=window, placement=placement,
                     use_flash=_kernel_enabled())

    def body(ql, kl, vl):
        return _ring_local(ql, kl, vl, spec)

    ctx = get_abstract_mesh()
    mesh = topo.mesh if ctx.empty else ctx
    # manual over seq + the batch axes (the ring only communicates over
    # "seq"; keeping batch sharded costs nothing).  On current jax the
    # head/tensor dims stay in GSPMD auto mode, so tensor-sharded heads
    # are NOT gathered; on 0.4.x the compat layer degrades to full manual
    # (partial-auto miscompiles axis_index/ppermute there) and unmentioned
    # axes replicate into each shard instead.
    pspec = P(BATCH_AXES, SEQ_AXIS, None, None)
    return shard_map(body, mesh=mesh, in_specs=(pspec, pspec, pspec),
                     out_specs=pspec, axis_names={SEQ_AXIS, *BATCH_AXES},
                     check_vma=False)(q, k, v)


def _block_attend_single(q, k, v, scale, causal, window):
    """sp=1 degenerate form (same math, no ring)."""
    s_len = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    valid = jnp.ones((s_len, s_len), bool)
    if causal:
        pos = jnp.arange(s_len)
        valid = pos[:, None] >= pos[None, :]
    if window is not None:
        pos = jnp.arange(s_len)
        valid &= (pos[:, None] - pos[None, :]) < window
    s = jnp.where(valid[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
