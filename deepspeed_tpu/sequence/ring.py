"""Ring attention: context parallelism by rotating K/V blocks over ICI.

The second half of the long-context story (the task the reference covers
with Ulysses all-to-all + FPDT chunking; ring attention is the
blockwise-rotation alternative of Liu et al. 2023): queries stay local to
their sequence shard while K/V blocks travel the "seq" mesh ring one
neighbour per hop (``lax.ppermute``), and a flash-style online softmax
accumulates each visiting block.  Communication per hop is O(S_local·d)
nearest-neighbour traffic that XLA overlaps with the block's attention
compute — and, unlike Ulysses, there is NO heads % sp divisibility
requirement, so it scales past the KV-head count (GQA models with 8 KV
heads on a 16-way context mesh).

Per-block math mirrors the Pallas flash kernel's online softmax
(ops/pallas/flash_mha.py) with the block loop living on the mesh instead
of the grid.  The block products are plain XLA einsums — on-chip they
fuse; swapping the inner block for the flash kernel is a later
optimization that doesn't change this interface.

Causal masking uses global positions (shard i's queries own rows
[i·S_l, (i+1)·S_l)); hops whose source block lies entirely in the masked
future contribute nothing (their probabilities are zeroed — compute is
spent but numerics are exact; skipping them is the classic ring-attention
load-imbalance optimization, also a later step).

Known partitioner wart: composed with ZeRO-2 on a data×seq mesh, XLA's
SPMD partitioner reports one "involuntary full rematerialization" for a
backward residual crossing the partial-manual boundary (it replicates a
[B, S_l, H] tensor before resharding — its own warning points to the
Shardy tracker b/433785288).  Numerics are unaffected; revisit the
in/out specs once Shardy lands.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import SEQ_AXIS, get_topology

_NEG = -1e30


def ring_attention(q, k, v, topo=None, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   window: Optional[int] = None):
    """q/k/v: [B, S, H, D] GLOBAL arrays with S sharded over "seq".
    Returns [B, S, H, D].  GQA KV heads are repeated locally.  Must be
    called under jit (partial-manual shard_map over the seq axis; batch
    and head dims stay in GSPMD auto mode)."""
    topo = topo or get_topology()
    sp = topo.sp_size if topo is not None else 1
    nh = q.shape[2]
    rep = nh // k.shape[2]  # GQA group: K/V travel the ring UNREPEATED
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    if sp == 1:
        if rep != 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return _block_attend_single(q, k, v, scale, causal, window)

    def body(ql, kl, vl):
        idx = lax.axis_index(SEQ_AXIS)
        b, s_l, nh_, d = ql.shape
        nkv = kl.shape[2]
        # grouped-head layout: K/V stay at nkv heads END TO END — they
        # travel the ring unrepeated AND feed the einsums unexpanded
        # (per-hop ICI traffic and per-hop HBM are both O(S_l·nkv·d))
        q5 = ql.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
        q_pos = idx * s_l + jnp.arange(s_l)
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def attend(m, l, acc, kc, vc, t):
            src = lax.rem(idx - t + sp, sp)
            k_pos = src * s_l + jnp.arange(s_l)
            s = jnp.einsum("bqcgd,bscd->bcgqs", q5,
                           kc.astype(jnp.float32)) * scale
            valid = jnp.ones((s_l, s_l), bool)
            if causal:
                valid = q_pos[:, None] >= k_pos[None, :]
            if window is not None:
                valid &= (q_pos[:, None] - k_pos[None, :]) < window
            vm = valid[None, None, None]
            s = jnp.where(vm, s, _NEG)
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            # exp(NEG - NEG) would be 1 on fully-masked rows — zero the
            # masked probabilities explicitly
            p = jnp.where(vm, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jnp.einsum(
                "bcgqs,bscd->bcgqd", p, vc.astype(jnp.float32))
            return m_new, l, acc

        def hop(carry, t):
            m, l, acc, kc, vc = carry
            m, l, acc = attend(m, l, acc, kc, vc, t)
            kc = lax.ppermute(kc, SEQ_AXIS, perm)
            vc = lax.ppermute(vc, SEQ_AXIS, perm)
            return (m, l, acc, kc, vc), None

        m0 = jnp.full((b, nkv, rep, s_l, 1), _NEG, jnp.float32)
        l0 = jnp.zeros((b, nkv, rep, s_l, 1), jnp.float32)
        a0 = jnp.zeros((b, nkv, rep, s_l, d), jnp.float32)
        # sp-1 hops permute after attending; the LAST block attends
        # without the dead ring rotation (a collective inside scan that
        # XLA cannot eliminate)
        (m, l, acc, kc, vc), _ = lax.scan(
            hop, (m0, l0, a0, kl, vl), jnp.arange(sp - 1))
        m, l, acc = attend(m, l, acc, kc, vc, jnp.int32(sp - 1))
        out = acc / jnp.maximum(l, 1e-20)        # [b, nkv, rep, q, d]
        out = out.transpose(0, 3, 1, 2, 4).reshape(b, s_l, nh_, d)
        return out.astype(ql.dtype)

    ctx = jax.sharding.get_abstract_mesh()
    mesh = topo.mesh if ctx.empty else ctx
    spec = P(None, SEQ_AXIS, None, None)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={SEQ_AXIS},
                         check_vma=False)(q, k, v)


def _block_attend_single(q, k, v, scale, causal, window):
    """sp=1 degenerate form (same math, no ring)."""
    s_len = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    valid = jnp.ones((s_len, s_len), bool)
    if causal:
        pos = jnp.arange(s_len)
        valid = pos[:, None] >= pos[None, :]
    if window is not None:
        pos = jnp.arange(s_len)
        valid &= (pos[:, None] - pos[None, :]) < window
    s = jnp.where(valid[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
