"""Ring attention: context parallelism by rotating K/V blocks over ICI.

The second half of the long-context story (the task the reference covers
with Ulysses all-to-all + FPDT chunking; ring attention is the
blockwise-rotation alternative of Liu et al. 2023): queries stay local to
their sequence shard while K/V blocks travel the "seq" mesh ring one
neighbour per hop (``lax.ppermute``), and a flash-style online softmax
accumulates each visiting block.  Communication per hop is O(S_local·d)
nearest-neighbour traffic that XLA overlaps with the block's attention
compute — and, unlike Ulysses, there is NO heads % sp divisibility
requirement, so it scales past the KV-head count (GQA models with 8 KV
heads on a 16-way context mesh).

Perf-grade inner block: on TPU (or under the Pallas interpreter) each
FORWARD hop is ONE fused flash pass — :func:`flash_carry_block` threads
the online softmax carry (m, l, acc) through the kernel, so no fp32
``[S_l, S_l]`` score block reaches HBM on the forward and causally-dead
tiles are skipped at the grid level.  The BACKWARD hops are fused the
same way: offset-aware dq/dkv flash kernels
(:func:`flash_ring_dq_block` / :func:`flash_ring_dkv_block`) reuse the
saved (o, lse) residuals, compute ``delta = sum(do·o)`` ONCE per shard,
and accumulate straight into HBM buffers aliased in place — backward
transient memory drops from score-shaped (four fp32 [S_l, S_l] blocks
per hop) to block-shaped ([blk, blk] VMEM tiles).  Off-TPU the same
math runs as XLA einsums (the CPU test mesh) behind the same
``_kernel_enabled()`` gate, so parity tests cover both paths.

Causal scheduling: with the default ``contiguous`` placement, hops whose
source block lies entirely in the masked future are skipped outright
(``lax.cond`` around the attend — no score FLOPs), but the ring is
bulk-synchronous per hop so the skip saves energy, not wall-clock (rank 0
idles while rank sp-1 works).  ``placement="striped"`` fixes the load
balance (Striped Attention, arXiv 2311.09431): shard r owns tokens
``r, r+sp, r+2sp, …``, so every hop is a ~half-masked block on every rank
— the flash kernel's tile skipping then halves causal compute uniformly.
Callers feed striped data (:func:`stripe_sequence` /
:func:`unstripe_sequence` are pure global reshapes; the engine applies
them host-side to ids/labels) and positions follow automatically.

Gradients are a hand-written second ring pass (``jax.custom_vjp``): the
forward saves (o, lse) per shard, the backward rotates K/V again and
accumulates dk/dv on buffers that TRAVEL WITH their block, delivered home
by one final ppermute.  Because the forward scan is never differentiated,
no per-hop carry residual ever crosses the shard_map partial-manual
boundary — which is what used to make the XLA SPMD partitioner report an
"involuntary full rematerialization" (a replicated [B, S_l, H] backward
residual) when ring composed with ZeRO-2 on a data×seq mesh.  The saved
(o, lse) are tagged ``checkpoint_name`` "flash_out"/"flash_lse", so the
engine's flash-aware remat policies keep them and the backward never
re-runs the forward ring (see runtime/engine.py's ring policy upgrade).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import (BATCH_AXES, SEQ_AXIS,
                                             get_topology)
from deepspeed_tpu.utils.jax_compat import get_abstract_mesh, shard_map

_NEG = -1e30

PLACEMENTS = ("contiguous", "striped")


class _RingSpec(NamedTuple):
    """Static per-call config (hashable: rides custom_vjp nondiff)."""
    sp: int
    rep: int
    scale: float
    causal: bool
    window: Optional[int]
    placement: str
    use_flash: bool
    # hop/compute interleave depth (step_schedule.ring_interleave): 1 =
    # attend then rotate (serial issue order); 2 = issue the next hop's
    # ppermute BEFORE the current hop's attend, so the K/V transfer is
    # dataflow-independent of the hop's kernels and the compiler can
    # overlap the two.  Math identical either way (the attend always
    # consumes the un-rotated buffers).
    interleave: int = 1
    # ring wire dtype (comm_quantization.ring_rotation): "fp32" keeps
    # the raw word-packed rotation; "int8"/"fp8" move block-quantized
    # payloads + fp32 per-row scales on the wire (module comment above
    # _rotate_quantized).  int8 dequantizes inside the flash kernels'
    # epilogues on the fused path; fp8 always decodes via the XLA codec.
    wire: str = "fp32"


# ----------------------------------------------------------------------
# Placement helpers
# ----------------------------------------------------------------------
def ring_position_map(s: int, sp: int, placement: str = "contiguous"):
    """Global token position held by each slot of the seq-sharded array
    ([S] int32).  Under ``striped`` placement shard r's slot j holds token
    ``r + sp*j`` — feed the model positions from this map (RoPE/ALiBi stay
    exact) when its inputs went through :func:`stripe_sequence`."""
    if placement == "striped" and sp > 1:
        s_l = s // sp
        i = jnp.arange(s, dtype=jnp.int32)
        return (i // s_l) + sp * (i % s_l)
    return jnp.arange(s, dtype=jnp.int32)


def stripe_sequence(x, sp: int, axis: int = 1):
    """Reorder a GLOBAL sequence-axis array from natural token order to
    striped placement (shard r gets tokens r, r+sp, …).  Pure reshape +
    transpose — apply before sharding (host-side ids/labels, or globally
    before jit).  Works on numpy and jax arrays."""
    if sp <= 1:
        return x
    s = x.shape[axis]
    if s % sp:
        raise ValueError(f"sequence length {s} not divisible by sp={sp}")
    s_l = s // sp
    shape = x.shape
    y = x.reshape(shape[:axis] + (s_l, sp) + shape[axis + 1:])
    return y.swapaxes(axis, axis + 1).reshape(shape)


def unstripe_sequence(x, sp: int, axis: int = 1):
    """Inverse of :func:`stripe_sequence`."""
    if sp <= 1:
        return x
    s = x.shape[axis]
    if s % sp:
        raise ValueError(f"sequence length {s} not divisible by sp={sp}")
    s_l = s // sp
    shape = x.shape
    y = x.reshape(shape[:axis] + (sp, s_l) + shape[axis + 1:])
    return y.swapaxes(axis, axis + 1).reshape(shape)


def _block_positions(block_idx, s_l: int, sp: int, placement: str):
    """Traced [s_l] global positions of the block owned by ``block_idx``."""
    i = jnp.arange(s_l, dtype=jnp.int32)
    if placement == "striped":
        return block_idx + sp * i
    return block_idx * s_l + i


def _block_bounds(block_idx, s_l: int, sp: int, placement: str):
    """Traced (lo, hi) global position range of a block (strides > 0)."""
    if placement == "striped":
        return block_idx, block_idx + sp * (s_l - 1)
    return block_idx * s_l, block_idx * s_l + s_l - 1


def _hop_dead(idx, src, s_l: int, spec: _RingSpec):
    """Whether the (query block idx, key block src) hop contributes
    nothing: the source block is entirely in the causal future, or
    entirely older than the sliding window."""
    q_lo, q_hi = _block_bounds(idx, s_l, spec.sp, spec.placement)
    k_lo, k_hi = _block_bounds(src, s_l, spec.sp, spec.placement)
    dead = jnp.bool_(False)
    if spec.causal:
        dead |= k_lo > q_hi
    if spec.window is not None:
        dead |= q_lo - k_hi >= spec.window
    return dead


def _kernel_enabled() -> bool:
    """Run the Pallas carry kernel: on TPU, or whenever the flash module's
    INTERPRET flag is up (CPU parity tests)."""
    import importlib

    # the ops.pallas package re-exports the flash_mha *function* under the
    # same name as its submodule — resolve the module itself
    fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")
    if fm.INTERPRET:
        return True
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover - no backend at trace time
        return False


# ----------------------------------------------------------------------
# Hop rotation: every buffer that travels the ring in one hop moves in
# ONE collective launch.
# ----------------------------------------------------------------------
def _word_count(x) -> int:
    """Whole 32-bit words needed for ``x``'s bytes (ceil)."""
    return -(-int(np.prod(x.shape)) * x.dtype.itemsize // 4)


def _to_words(x):
    """Flatten to raw 32-bit words (bit-exact).  Sub-word dtypes pack 2
    (bf16/fp16) or 4 (int8) elements per word; an element count that
    does not fill the last word is ZERO-PADDED to the word boundary —
    ``_from_words`` slices the pad back off, so callers need no shape
    alignment (regression: odd head_dim / odd-length bf16 buffers used
    to fall back to per-buffer permutes)."""
    flat = x.reshape(-1)
    if x.dtype.itemsize == 4:
        return flat if x.dtype == jnp.uint32 \
            else lax.bitcast_convert_type(flat, jnp.uint32)
    per = 4 // x.dtype.itemsize
    if flat.size % per:
        flat = jnp.pad(flat, (0, per - flat.size % per))
    return lax.bitcast_convert_type(flat.reshape(-1, per), jnp.uint32)


def _from_words(w, shape, dtype):
    n = int(np.prod(shape))
    if dtype.itemsize == 4:
        out = w if dtype == jnp.uint32 \
            else lax.bitcast_convert_type(w, dtype)
        return out.reshape(shape)
    return lax.bitcast_convert_type(w, dtype).reshape(-1)[:n].reshape(shape)


def _rotate_together(perm, *xs):
    """Rotate every traveling buffer one ring neighbour in a SINGLE
    ``lax.ppermute``: flatten each to raw 32-bit words, concatenate,
    permute once, split and bitcast back.  ``lax.ppermute`` on a tuple
    tree-maps into one collective per leaf — on the backward ring that
    was four serialized collective-permute launches per hop for
    (kc, vc, dk_t, dv_t); one fused message keeps the ICI pipe busy with
    a single transfer the compiler can overlap with the hop's kernels.
    Byte-exact for 1/2/4-byte dtypes; tail elements that do not fill a
    word are pad-carried and sliced off on arrival (see _to_words)."""
    if any(x.dtype.itemsize not in (1, 2, 4)
           for x in xs):  # pragma: no cover - no such dtype travels today
        return tuple(lax.ppermute(x, SEQ_AXIS, perm) for x in xs)
    words = lax.ppermute(jnp.concatenate([_to_words(x) for x in xs]),
                         SEQ_AXIS, perm)
    out, i = [], 0
    for x in xs:
        n = _word_count(x)
        out.append(_from_words(words[i:i + n], x.shape, x.dtype))
        i += n
    return tuple(out)


# ----------------------------------------------------------------------
# Quantized wire (comm_quantization.ring_rotation): the traveling
# buffers move as int8 (or fp8-as-uint8) payloads + per-row fp32 block
# scales, the codec shared verbatim with comm/quantized.py
# (wire_encode_rows / wire_decode_rows — blocks are the trailing head
# dim).  K/V are encoded ONCE at ring entry and the payload+scales
# travel all sp-1 hops (a single quantization however long the ring);
# the traveling dk/dv grad accumulators change every hop, so they
# re-encode per hop.  Dequant on the consuming side happens inside the
# flash kernels' epilogues (flash_mha.wire_dequant_rows — new scale
# operands) on the fused path, or via wire_decode_rows on the XLA
# fallback, so the two codecs cannot drift.
# ----------------------------------------------------------------------
def _rotate_quantized(perm, payloads, scales):
    """One hop of the quantized wire: every payload flattens into ONE
    narrow message and every fp32 scale into another; a single
    ``lax.ppermute`` call moves the pair (one collective per dtype).
    Unlike :func:`_rotate_together` the payload is NOT word-packed — the
    wire dtype stays s8/u8 in the lowered HLO, so the static census
    (analysis/) sees the narrowed collective-permute it declares.
    Returns ``(payloads', scales')``."""
    pay = jnp.concatenate([p.reshape(-1) for p in payloads])
    sc = jnp.concatenate([s.reshape(-1) for s in scales])
    pay, sc = lax.ppermute((pay, sc), SEQ_AXIS, perm)
    outp, i = [], 0
    for p in payloads:
        n = int(np.prod(p.shape))
        outp.append(pay[i:i + n].reshape(p.shape))
        i += n
    outs, i = [], 0
    for s in scales:
        n = int(np.prod(s.shape))
        outs.append(sc[i:i + n].reshape(s.shape))
        i += n
    return tuple(outp), tuple(outs)


def _rotate_kv_grads_quant(perm, wire, kp, vp, ks, vs, dk, dv):
    """Backward-hop rotation on the quantized wire: the K/V payloads and
    scales pass through encoded, the fp32 traveling grads encode for the
    wire and decode on arrival — all four payloads in one message, all
    four scale vectors in another, one ``ppermute`` call."""
    from deepspeed_tpu.comm.quantized import (wire_decode_rows,
                                              wire_encode_rows)

    dkp, dks = wire_encode_rows(dk, wire)
    dvp, dvs = wire_encode_rows(dv, wire)
    (kp, vp, dkp, dvp), (ks, vs, dks, dvs) = _rotate_quantized(
        perm, (kp, vp, dkp, dvp), (ks, vs, dks, dvs))
    return (kp, vp, ks, vs, wire_decode_rows(dkp, dks, wire),
            wire_decode_rows(dvp, dvs, wire))


def _lane128(s):
    """Lane-replicate a compact per-row scale ``[..., 1]`` to the
    128-lane layout the flash kernels read (the lse/delta convention)."""
    return jnp.broadcast_to(s, s.shape[:-1] + (128,))


def _rotate_grads_quant(perm, wire, dk, dv):
    """Grads-only quantized rotation (the interleave-2 late half and the
    final delivery hop)."""
    from deepspeed_tpu.comm.quantized import (wire_decode_rows,
                                              wire_encode_rows)

    dkp, dks = wire_encode_rows(dk, wire)
    dvp, dvs = wire_encode_rows(dv, wire)
    (dkp, dvp), (dks, dvs) = _rotate_quantized(perm, (dkp, dvp),
                                               (dks, dvs))
    return (wire_decode_rows(dkp, dks, wire),
            wire_decode_rows(dvp, dvs, wire))


# ----------------------------------------------------------------------
# Local (per-shard) forward: XLA einsum path and Pallas flash path.
# Both return (o [b, s_l, nh, d], lse [b, nkv, rep, s_l] fp32).
# ----------------------------------------------------------------------
def _ring_fwd_xla(ql, kl, vl, spec: _RingSpec):
    b, s_l, nh, d = ql.shape
    nkv = kl.shape[2]
    rep = spec.rep
    # Only masked variants need the shard's ring position; dense
    # bidirectional hops never touch axis_index (whose partition-id
    # lowering old SPMD partitioners reject when it ends up dead code).
    masked = spec.causal or spec.window is not None
    idx = lax.axis_index(SEQ_AXIS) if masked else jnp.int32(0)
    # grouped-head layout: K/V stay at nkv heads END TO END — they travel
    # the ring UNREPEATED and feed the einsums unexpanded (per-hop ICI
    # traffic and per-hop HBM are both O(S_l·nkv·d))
    q5 = ql.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
    q_pos = _block_positions(idx, s_l, spec.sp, spec.placement)
    perm = [(i, (i + 1) % spec.sp) for i in range(spec.sp)]

    def attend(m, l, acc, kc, vc, src):
        k_pos = _block_positions(src, s_l, spec.sp, spec.placement)
        s = jnp.einsum("bqcgd,bscd->bcgqs", q5,
                       kc.astype(jnp.float32)) * spec.scale
        valid = jnp.ones((s_l, s_l), bool)
        if spec.causal:
            valid = q_pos[:, None] >= k_pos[None, :]
        if spec.window is not None:
            valid &= (q_pos[:, None] - k_pos[None, :]) < spec.window
        vm = valid[None, None, None]
        s = jnp.where(vm, s, _NEG)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        # exp(NEG - NEG) would be 1 on fully-masked rows — zero the masked
        # probabilities explicitly
        p = jnp.where(vm, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bcgqs,bscd->bcgqd", p, vc.astype(jnp.float32))
        return m_new, l, acc

    def maybe_attend(m, l, acc, kc, vc, src):
        if not masked:
            return attend(m, l, acc, kc, vc, src)
        return lax.cond(_hop_dead(idx, src, s_l, spec),
                        lambda: (m, l, acc),
                        lambda: attend(m, l, acc, kc, vc, src))

    m0 = jnp.full((b, nkv, rep, s_l, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((b, nkv, rep, s_l, 1), jnp.float32)
    a0 = jnp.zeros((b, nkv, rep, s_l, d), jnp.float32)

    quant = spec.wire != "fp32"
    if quant:
        from deepspeed_tpu.comm.quantized import (wire_decode_rows,
                                                  wire_encode_rows)

        # hop 0 is the shard's OWN block: it never touches the wire, so
        # it attends EXACTLY (never causally dead either — the diagonal
        # is always live); only the traveling copy quantizes.  Encoding
        # happens ONCE here: payload + per-row scales travel all sp-1
        # hops, one quantization however long the ring.
        m, l, acc = attend(m0, l0, a0, kl, vl, idx)
        kp, ks = wire_encode_rows(kl, spec.wire)
        vp, vs = wire_encode_rows(vl, spec.wire)

        def maybe_attend_q(m, l, acc, kp, ks, vp, vs, src):
            def live():
                kf = wire_decode_rows(kp, ks, spec.wire)
                vf = wire_decode_rows(vp, vs, spec.wire)
                return attend(m, l, acc, kf, vf, src)

            if not masked:
                return live()
            return lax.cond(_hop_dead(idx, src, s_l, spec),
                            lambda: (m, l, acc), live)

        # first rotation peeled out of the scan (the scan body attends
        # then rotates, same shape as the fp32-wire loop)
        (kp, vp), (ks, vs) = _rotate_quantized(perm, (kp, vp), (ks, vs))

        def hop(carry, t):
            m, l, acc, kp, vp, ks, vs = carry
            src = lax.rem(idx - t - 1 + spec.sp, spec.sp)
            if spec.interleave > 1:
                (nkp, nvp), (nks, nvs) = _rotate_quantized(
                    perm, (kp, vp), (ks, vs))
                m, l, acc = maybe_attend_q(m, l, acc, kp, ks, vp, vs, src)
                return (m, l, acc, nkp, nvp, nks, nvs), None
            m, l, acc = maybe_attend_q(m, l, acc, kp, ks, vp, vs, src)
            (kp, vp), (ks, vs) = _rotate_quantized(perm, (kp, vp),
                                                   (ks, vs))
            return (m, l, acc, kp, vp, ks, vs), None

        (m, l, acc, kp, vp, ks, vs), _ = lax.scan(
            hop, (m, l, acc, kp, vp, ks, vs), jnp.arange(spec.sp - 2))
        src_last = lax.rem(idx + 1, spec.sp)
        m, l, acc = maybe_attend_q(m, l, acc, kp, ks, vp, vs, src_last)
    else:
        # hop 0 = the shard's own block: attended first (it is never
        # causally dead), with the first rotation peeled out of the scan
        # — the same skeleton as the quantized branch, so the static
        # collective census counts both wires with identical op
        # multiplicity (analysis/; the scan body still holds sp-2
        # attend-then-rotate hops and the LAST block attends without the
        # dead ring rotation XLA cannot eliminate)
        m, l, acc = attend(m0, l0, a0, kl, vl, idx)
        kc, vc = _rotate_together(perm, kl, vl)

        def hop(carry, t):
            m, l, acc, kc, vc = carry
            src = lax.rem(idx - t - 1 + spec.sp, spec.sp)
            if spec.interleave > 1:
                # rotate-ahead (interleave 2): the permute consumes only
                # the incoming buffers, so issuing it before the attend
                # makes transfer and compute dataflow-independent — the
                # scheduler is free to run the hop's kernels under the
                # K/V transfer
                nkc, nvc = _rotate_together(perm, kc, vc)
                m, l, acc = maybe_attend(m, l, acc, kc, vc, src)
                return (m, l, acc, nkc, nvc), None
            m, l, acc = maybe_attend(m, l, acc, kc, vc, src)
            kc, vc = _rotate_together(perm, kc, vc)
            return (m, l, acc, kc, vc), None

        (m, l, acc, kc, vc), _ = lax.scan(
            hop, (m, l, acc, kc, vc), jnp.arange(spec.sp - 2))
        src_last = lax.rem(idx + 1, spec.sp)
        m, l, acc = maybe_attend(m, l, acc, kc, vc, src_last)
    out = acc / jnp.maximum(l, 1e-20)            # [b, nkv, rep, q, d]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, s_l, nh, d)
    lse = (m + jnp.log(jnp.maximum(l, 1e-20)))[..., 0]  # [b, nkv, rep, q]
    return out.astype(ql.dtype), lse


def _ring_fwd_flash(ql, kl, vl, spec: _RingSpec):
    """Same contract as :func:`_ring_fwd_xla` with the per-hop attend
    fused into one Pallas pass (flash_carry_block): the carry (m, l, acc)
    lives in HBM between hops, aliased in place, and dead tiles cost
    neither VPU masking nor MXU FLOPs."""
    from deepspeed_tpu.ops.pallas.flash_mha import (flash_carry_block,
                                                    ring_carry_pad)

    b, s_l, nh, d = ql.shape
    nkv = kl.shape[2]
    masked = spec.causal or spec.window is not None
    idx = lax.axis_index(SEQ_AXIS) if masked else jnp.int32(0)
    stride = spec.sp if spec.placement == "striped" else 1
    s_pad = ring_carry_pad(s_l)

    def to_kernel(x):  # [b, s, h, d] -> [b, h, s_pad, d]
        x = x.swapaxes(1, 2)
        if s_pad != s_l:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s_l), (0, 0)))
        return x

    qk, kk, vk = to_kernel(ql), to_kernel(kl), to_kernel(vl)
    q_off = (idx if spec.placement == "striped"
             else idx * s_l).astype(jnp.int32)
    perm = [(i, (i + 1) % spec.sp) for i in range(spec.sp)]

    def attend(m, l, acc, kc, vc, src, ks=None, vs=None):
        k_off = (src if spec.placement == "striped"
                 else src * s_l).astype(jnp.int32)
        kw = dict(q_stride=stride, k_stride=stride, s_real=s_l,
                  sm_scale=spec.scale, causal=spec.causal,
                  window=spec.window)
        if ks is not None:
            # quantized wire, fused dequant: the int8 payload feeds the
            # kernel directly with its per-row scales lane-replicated —
            # no fp32 K/V copy ever exists in HBM
            kw.update(k_scale=_lane128(ks), v_scale=_lane128(vs))
        return flash_carry_block(qk, kc, vc, m, l, acc, q_off, k_off,
                                 **kw)

    def maybe_attend(m, l, acc, kc, vc, src):
        if not masked:
            return attend(m, l, acc, kc, vc, src)
        return lax.cond(_hop_dead(idx, src, s_l, spec),
                        lambda: (m, l, acc),
                        lambda: attend(m, l, acc, kc, vc, src))

    m0 = jnp.full((b, nh, s_pad, 128), _NEG, jnp.float32)
    l0 = jnp.zeros((b, nh, s_pad, 128), jnp.float32)
    a0 = jnp.zeros((b, nh, s_pad, d), jnp.float32)

    quant = spec.wire != "fp32"
    if quant:
        from deepspeed_tpu.comm.quantized import (wire_decode_rows,
                                                  wire_encode_rows)

        # hop 0 = the shard's own block: exact attend (it never touches
        # the wire, and the diagonal is never dead); encode once for the
        # traveling copy (pad rows quantize to exact zeros)
        m, l, acc = attend(m0, l0, a0, kk, vk, idx)
        kp, ks = wire_encode_rows(kk, spec.wire)
        vp, vs = wire_encode_rows(vk, spec.wire)
        kernel_dequant = spec.wire == "int8"

        def maybe_attend_q(m, l, acc, kp, ks, vp, vs, src):
            def live():
                if kernel_dequant:
                    return attend(m, l, acc, kp, vp, src, ks=ks, vs=vs)
                # fp8 wire: the kernel has no fp8 lane — decode via the
                # XLA codec and run the plain kernel on the values
                kf = wire_decode_rows(kp, ks, spec.wire).astype(qk.dtype)
                vf = wire_decode_rows(vp, vs, spec.wire).astype(qk.dtype)
                return attend(m, l, acc, kf, vf, src)

            if not masked:
                return live()
            return lax.cond(_hop_dead(idx, src, s_l, spec),
                            lambda: (m, l, acc), live)

        # first rotation peeled out of the scan (the scan body attends
        # then rotates, same shape as the fp32-wire loop)
        (kp, vp), (ks, vs) = _rotate_quantized(perm, (kp, vp), (ks, vs))

        def hop(carry, t):
            m, l, acc, kp, vp, ks, vs = carry
            src = lax.rem(idx - t - 1 + spec.sp, spec.sp)
            if spec.interleave > 1:
                (nkp, nvp), (nks, nvs) = _rotate_quantized(
                    perm, (kp, vp), (ks, vs))
                m, l, acc = maybe_attend_q(m, l, acc, kp, ks, vp, vs, src)
                return (m, l, acc, nkp, nvp, nks, nvs), None
            m, l, acc = maybe_attend_q(m, l, acc, kp, ks, vp, vs, src)
            (kp, vp), (ks, vs) = _rotate_quantized(perm, (kp, vp),
                                                   (ks, vs))
            return (m, l, acc, kp, vp, ks, vs), None

        (m, l, acc, kp, vp, ks, vs), _ = lax.scan(
            hop, (m, l, acc, kp, vp, ks, vs), jnp.arange(spec.sp - 2))
        src_last = lax.rem(idx + 1, spec.sp)
        m, l, acc = maybe_attend_q(m, l, acc, kp, ks, vp, vs, src_last)
    else:
        # hop 0 = own block, first rotation peeled — same skeleton as
        # the quantized branch (census op-multiplicity symmetry; see
        # _ring_fwd_xla)
        m, l, acc = attend(m0, l0, a0, kk, vk, idx)
        kc, vc = _rotate_together(perm, kk, vk)

        def hop(carry, t):
            m, l, acc, kc, vc = carry
            src = lax.rem(idx - t - 1 + spec.sp, spec.sp)
            if spec.interleave > 1:
                # rotate-ahead (interleave 2): the permute consumes only
                # the incoming buffers, so issuing it before the attend
                # makes transfer and compute dataflow-independent — the
                # scheduler is free to run the hop's kernels under the
                # K/V transfer
                nkc, nvc = _rotate_together(perm, kc, vc)
                m, l, acc = maybe_attend(m, l, acc, kc, vc, src)
                return (m, l, acc, nkc, nvc), None
            m, l, acc = maybe_attend(m, l, acc, kc, vc, src)
            kc, vc = _rotate_together(perm, kc, vc)
            return (m, l, acc, kc, vc), None

        (m, l, acc, kc, vc), _ = lax.scan(
            hop, (m, l, acc, kc, vc), jnp.arange(spec.sp - 2))
        src_last = lax.rem(idx + 1, spec.sp)
        m, l, acc = maybe_attend(m, l, acc, kc, vc, src_last)

    m1 = m[:, :, :s_l, 0]                                # [b, nh, s_l]
    l1 = l[:, :, :s_l, 0]
    out = acc[:, :, :s_l] / jnp.maximum(l1, 1e-20)[..., None]
    out = out.swapaxes(1, 2).astype(ql.dtype)            # [b, s_l, nh, d]
    lse = m1 + jnp.log(jnp.maximum(l1, 1e-20))           # [b, nh, s_l]
    lse = lse.reshape(b, nkv, spec.rep, s_l)
    return out, lse


# ----------------------------------------------------------------------
# custom_vjp: forward ring + hand-written backward ring
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ring_local(ql, kl, vl, spec: _RingSpec):
    o, _ = (_ring_fwd_flash if spec.use_flash else _ring_fwd_xla)(
        ql, kl, vl, spec)
    return checkpoint_name(o, "flash_out")


def _ring_fwd_rule(ql, kl, vl, spec: _RingSpec):
    o, lse = (_ring_fwd_flash if spec.use_flash else _ring_fwd_xla)(
        ql, kl, vl, spec)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (ql, kl, vl, o, lse)


def _ring_bwd_rule(spec: _RingSpec, res, do):
    """Flash-style ring backward: with the forward's (o, lse) saved, each
    hop recomputes only its own p = exp(s - lse) block and accumulates
    dq locally while dk/dv TRAVEL WITH their K/V block; one final
    ppermute delivers them to their owner shard.  Dead hops (fully-masked
    source blocks) are skipped like the forward, and every hop moves all
    four traveling buffers (kc, vc, dk_t, dv_t) in ONE stacked permute
    (:func:`_rotate_together`).

    On TPU / under the Pallas interpreter (``spec.use_flash``, the same
    gate as the forward) each hop's grads are TWO fused flash passes —
    offset-aware dq and dkv kernels accumulating in place — so no
    score-shaped fp32 transient reaches HBM.  Off-TPU the grads are XLA
    einsums (the CPU parity fallback), which do materialize the four
    fp32 [S_l, S_l] blocks per hop."""
    if spec.use_flash:
        return _ring_bwd_flash(spec, res, do)
    return _ring_bwd_xla(spec, res, do)


def _ring_bwd_xla(spec: _RingSpec, res, do):
    """XLA einsum backward hop (CPU/parity fallback): score-shaped fp32
    transients (s/p/dp/ds, ~4·s_l²·nkv·rep·4 B per hop)."""
    ql, kl, vl, o, lse = res
    masked = spec.causal or spec.window is not None
    idx = lax.axis_index(SEQ_AXIS) if masked else jnp.int32(0)
    b, s_l, nh, d = ql.shape
    nkv = kl.shape[2]
    rep = spec.rep
    q5 = ql.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
    do5 = do.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
    o5 = o.astype(jnp.float32).reshape(b, s_l, nkv, rep, d)
    from deepspeed_tpu.ops.pallas.flash_mha import attn_delta

    # delta = sum(do * o) per query row — [b, nkv, rep, s_l, 1]
    delta = attn_delta(o5, do5).transpose(0, 2, 3, 1)[..., None]
    lse_ = lse[..., None]                            # [b, nkv, rep, s_l, 1]
    q_pos = _block_positions(idx, s_l, spec.sp, spec.placement)
    perm = [(i, (i + 1) % spec.sp) for i in range(spec.sp)]

    def hop_grads(kc, vc, src):
        k_pos = _block_positions(src, s_l, spec.sp, spec.placement)
        kf = kc.astype(jnp.float32)
        vf = vc.astype(jnp.float32)
        s = jnp.einsum("bqcgd,bscd->bcgqs", q5, kf) * spec.scale
        valid = jnp.ones((s_l, s_l), bool)
        if spec.causal:
            valid = q_pos[:, None] >= k_pos[None, :]
        if spec.window is not None:
            valid &= (q_pos[:, None] - k_pos[None, :]) < spec.window
        vm = valid[None, None, None]
        p = jnp.where(vm, jnp.exp(s - lse_), 0.0)    # [b, c, g, q, s]
        dv_c = jnp.einsum("bcgqs,bqcgd->bscd", p, do5)
        dp = jnp.einsum("bqcgd,bscd->bcgqs", do5, vf)
        ds = p * (dp - delta) * spec.scale
        dq_c = jnp.einsum("bcgqs,bscd->bqcgd", ds, kf)
        dk_c = jnp.einsum("bcgqs,bqcgd->bscd", ds, q5)
        return dq_c, dk_c, dv_c

    def maybe_grads(kc, vc, src, zq, zk, zv):
        if not masked:
            return hop_grads(kc, vc, src)
        return lax.cond(_hop_dead(idx, src, s_l, spec),
                        lambda: (zq, zk, zv),
                        lambda: hop_grads(kc, vc, src))

    zq = jnp.zeros((b, s_l, nkv, rep, d), jnp.float32)
    zk = jnp.zeros((b, s_l, nkv, d), jnp.float32)
    # distinct zero block for dv: shape-identical to zk today, but dk/dv
    # layouts must be free to diverge without silently wrong grads
    zv = jnp.zeros((b, s_l, nkv, d), jnp.float32)

    quant = spec.wire != "fp32"
    if quant:
        from deepspeed_tpu.comm.quantized import (wire_decode_rows,
                                                  wire_encode_rows)

        def maybe_grads_q(kp, ks, vp, vs, src):
            def live():
                return hop_grads(wire_decode_rows(kp, ks, spec.wire),
                                 wire_decode_rows(vp, vs, spec.wire), src)

            if not masked:
                return live()
            return lax.cond(_hop_dead(idx, src, s_l, spec),
                            lambda: (zq, zk, zv), live)

        # own-block grads are exact (hop 0 never touches the wire and
        # the diagonal is never dead); encode once for the traveling copy
        dq, dk_t, dv_t = hop_grads(kl, vl, idx)
        kp, ks = wire_encode_rows(kl, spec.wire)
        vp, vs = wire_encode_rows(vl, spec.wire)
        # first rotation peeled out of the scan; K/V payloads and the
        # freshly-accumulated traveling grads move together
        kp, vp, ks, vs, dk_t, dv_t = _rotate_kv_grads_quant(
            perm, spec.wire, kp, vp, ks, vs, dk_t, dv_t)

        def hop(carry, t):
            dq, dk_t, dv_t, kp, vp, ks, vs = carry
            src = lax.rem(idx - t - 1 + spec.sp, spec.sp)
            if spec.interleave > 1:
                (nkp, nvp), (nks, nvs) = _rotate_quantized(
                    perm, (kp, vp), (ks, vs))
                dq_c, dk_c, dv_c = maybe_grads_q(kp, ks, vp, vs, src)
                dk_t, dv_t = _rotate_grads_quant(perm, spec.wire,
                                                 dk_t + dk_c, dv_t + dv_c)
                return (dq + dq_c, dk_t, dv_t, nkp, nvp, nks, nvs), None
            dq_c, dk_c, dv_c = maybe_grads_q(kp, ks, vp, vs, src)
            kp, vp, ks, vs, dk_t, dv_t = _rotate_kv_grads_quant(
                perm, spec.wire, kp, vp, ks, vs, dk_t + dk_c, dv_t + dv_c)
            return (dq + dq_c, dk_t, dv_t, kp, vp, ks, vs), None

        (dq, dk_t, dv_t, kp, vp, ks, vs), _ = lax.scan(
            hop, (dq, dk_t, dv_t, kp, vp, ks, vs),
            jnp.arange(spec.sp - 2))
        src_last = lax.rem(idx + 1, spec.sp)
        dq_c, dk_c, dv_c = maybe_grads_q(kp, ks, vp, vs, src_last)
        dq = dq + dq_c
        # delivery hop: the traveling grads quantize one last time
        dk_t, dv_t = _rotate_grads_quant(perm, spec.wire,
                                         dk_t + dk_c, dv_t + dv_c)
        return (dq.reshape(b, s_l, nh, d).astype(ql.dtype),
                dk_t.astype(kl.dtype), dv_t.astype(vl.dtype))

    # hop 0 = own block, first rotation peeled — same skeleton as the
    # quantized branch (census op-multiplicity symmetry)
    if spec.interleave > 1:
        # rotate-ahead: K/V depart before even the own-block grads
        nkc, nvc = _rotate_together(perm, kl, vl)
        dq, dk_t, dv_t = hop_grads(kl, vl, idx)
        dk_t, dv_t = _rotate_together(perm, dk_t, dv_t)
        kc, vc = nkc, nvc
    else:
        dq, dk_t, dv_t = hop_grads(kl, vl, idx)
        # K/V and their accumulated grads rotate together, in one launch
        kc, vc, dk_t, dv_t = _rotate_together(perm, kl, vl, dk_t, dv_t)

    def hop(carry, t):
        dq, dk_t, dv_t, kc, vc = carry
        src = lax.rem(idx - t - 1 + spec.sp, spec.sp)
        if spec.interleave > 1:
            # rotate-ahead: K/V depart before the hop's grads are
            # computed (overlapping the grad einsums); the traveling
            # grads must wait for their accumulation, so the single
            # fused 4-buffer permute splits into two 2-buffer permutes —
            # the interleave trades a second launch for an earlier K/V
            # transfer
            nkc, nvc = _rotate_together(perm, kc, vc)
            dq_c, dk_c, dv_c = maybe_grads(kc, vc, src, zq, zk, zv)
            dk_t, dv_t = _rotate_together(perm, dk_t + dk_c, dv_t + dv_c)
            return (dq + dq_c, dk_t, dv_t, nkc, nvc), None
        dq_c, dk_c, dv_c = maybe_grads(kc, vc, src, zq, zk, zv)
        dq = dq + dq_c
        dk_t = dk_t + dk_c
        dv_t = dv_t + dv_c
        # K/V and their accumulated grads rotate together, in one launch
        kc, vc, dk_t, dv_t = _rotate_together(perm, kc, vc, dk_t, dv_t)
        return (dq, dk_t, dv_t, kc, vc), None

    (dq, dk_t, dv_t, kc, vc), _ = lax.scan(
        hop, (dq, dk_t, dv_t, kc, vc), jnp.arange(spec.sp - 2))
    src_last = lax.rem(idx + 1, spec.sp)
    dq_c, dk_c, dv_c = maybe_grads(kc, vc, src_last, zq, zk, zv)
    dq = dq + dq_c
    # the traveling grads sit one rank behind their owner — deliver home
    dk_t, dv_t = _rotate_together(perm, dk_t + dk_c, dv_t + dv_c)
    return (dq.reshape(b, s_l, nh, d).astype(ql.dtype),
            dk_t.astype(kl.dtype), dv_t.astype(vl.dtype))


def _ring_bwd_flash(spec: _RingSpec, res, do):
    """Fused backward hop: offset-aware dq/dkv flash kernels
    (flash_ring_dq_block / flash_ring_dkv_block) reuse the saved
    (o, lse), consume ``delta = sum(do·o)`` computed ONCE per shard, and
    accumulate into fp32 HBM buffers aliased in place — per-hop
    transients are [blk, blk] VMEM tiles, never an [S_l, S_l] score
    block.  Dead tiles inside a live hop are skipped at the kernel grid
    level from the same traced offsets the forward carry kernel uses."""
    from deepspeed_tpu.ops.pallas.flash_mha import (bwd_lane_residuals,
                                                    flash_ring_dq_block,
                                                    flash_ring_dkv_block,
                                                    ring_carry_pad)

    ql, kl, vl, o, lse = res
    b, s_l, nh, d = ql.shape
    nkv = kl.shape[2]
    masked = spec.causal or spec.window is not None
    idx = lax.axis_index(SEQ_AXIS) if masked else jnp.int32(0)
    stride = spec.sp if spec.placement == "striped" else 1
    s_pad = ring_carry_pad(s_l)

    def to_kernel(x):  # [b, s, h, d] -> [b, h, s_pad, d]
        x = x.swapaxes(1, 2)
        if s_pad != s_l:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s_l), (0, 0)))
        return x

    qk, kk, vk, dok = (to_kernel(x) for x in (ql, kl, vl, do))
    # residual prep shared with the local flash backward (one helper so
    # the two paths can't drift): lane-replicated lse + per-shard delta
    lsep, deltap = bwd_lane_residuals(
        o.swapaxes(1, 2), do.swapaxes(1, 2), lse.reshape(b, nh, s_l),
        s_pad)
    q_off = (idx if spec.placement == "striped"
             else idx * s_l).astype(jnp.int32)
    perm = [(i, (i + 1) % spec.sp) for i in range(spec.sp)]

    def hop_grads(dq, dk_t, dv_t, kc, vc, src, ks=None, vs=None):
        k_off = (src if spec.placement == "striped"
                 else src * s_l).astype(jnp.int32)
        kw = dict(q_stride=stride, k_stride=stride, s_real=s_l,
                  sm_scale=spec.scale, causal=spec.causal,
                  window=spec.window)
        if ks is not None:
            # quantized wire, fused dequant (see _ring_fwd_flash.attend)
            kw.update(k_scale=_lane128(ks), v_scale=_lane128(vs))
        dq = flash_ring_dq_block(qk, kc, vc, dok, lsep, deltap, dq,
                                 q_off, k_off, **kw)
        dk_t, dv_t = flash_ring_dkv_block(qk, kc, vc, dok, lsep, deltap,
                                          dk_t, dv_t, q_off, k_off, **kw)
        return dq, dk_t, dv_t

    def maybe_grads(dq, dk_t, dv_t, kc, vc, src):
        if not masked:
            return hop_grads(dq, dk_t, dv_t, kc, vc, src)
        return lax.cond(_hop_dead(idx, src, s_l, spec),
                        lambda: (dq, dk_t, dv_t),
                        lambda: hop_grads(dq, dk_t, dv_t, kc, vc, src))

    dq0 = jnp.zeros((b, nh, s_pad, d), jnp.float32)
    zk = jnp.zeros((b, nkv, s_pad, d), jnp.float32)
    zv = jnp.zeros((b, nkv, s_pad, d), jnp.float32)

    quant = spec.wire != "fp32"
    if quant:
        from deepspeed_tpu.comm.quantized import (wire_decode_rows,
                                                  wire_encode_rows)

        kernel_dequant = spec.wire == "int8"

        def maybe_grads_q(dq, dk_t, dv_t, kp, ks, vp, vs, src):
            def live():
                if kernel_dequant:
                    return hop_grads(dq, dk_t, dv_t, kp, vp, src,
                                     ks=ks, vs=vs)
                kf = wire_decode_rows(kp, ks, spec.wire).astype(qk.dtype)
                vf = wire_decode_rows(vp, vs, spec.wire).astype(qk.dtype)
                return hop_grads(dq, dk_t, dv_t, kf, vf, src)

            if not masked:
                return live()
            return lax.cond(_hop_dead(idx, src, s_l, spec),
                            lambda: (dq, dk_t, dv_t), live)

        # own-block grads are exact (hop 0 never touches the wire and
        # the diagonal is never dead); encode once for the traveling copy
        dq, dk_t, dv_t = hop_grads(dq0, zk, zv, kk, vk, idx)
        kp, ks = wire_encode_rows(kk, spec.wire)
        vp, vs = wire_encode_rows(vk, spec.wire)
        # first rotation peeled out of the scan
        kp, vp, ks, vs, dk_t, dv_t = _rotate_kv_grads_quant(
            perm, spec.wire, kp, vp, ks, vs, dk_t, dv_t)

        def hop(carry, t):
            dq, dk_t, dv_t, kp, vp, ks, vs = carry
            src = lax.rem(idx - t - 1 + spec.sp, spec.sp)
            if spec.interleave > 1:
                (nkp, nvp), (nks, nvs) = _rotate_quantized(
                    perm, (kp, vp), (ks, vs))
                dq, dk_t, dv_t = maybe_grads_q(dq, dk_t, dv_t, kp, ks,
                                               vp, vs, src)
                dk_t, dv_t = _rotate_grads_quant(perm, spec.wire,
                                                 dk_t, dv_t)
                return (dq, dk_t, dv_t, nkp, nvp, nks, nvs), None
            dq, dk_t, dv_t = maybe_grads_q(dq, dk_t, dv_t, kp, ks,
                                           vp, vs, src)
            kp, vp, ks, vs, dk_t, dv_t = _rotate_kv_grads_quant(
                perm, spec.wire, kp, vp, ks, vs, dk_t, dv_t)
            return (dq, dk_t, dv_t, kp, vp, ks, vs), None

        (dq, dk_t, dv_t, kp, vp, ks, vs), _ = lax.scan(
            hop, (dq, dk_t, dv_t, kp, vp, ks, vs),
            jnp.arange(spec.sp - 2))
        src_last = lax.rem(idx + 1, spec.sp)
        dq, dk_t, dv_t = maybe_grads_q(dq, dk_t, dv_t, kp, ks, vp, vs,
                                       src_last)
        # delivery hop: the traveling grads quantize one last time
        dk_t, dv_t = _rotate_grads_quant(perm, spec.wire, dk_t, dv_t)
        dq = dq[:, :, :s_l].swapaxes(1, 2).astype(ql.dtype)
        dk = dk_t[:, :, :s_l].swapaxes(1, 2).astype(kl.dtype)
        dv = dv_t[:, :, :s_l].swapaxes(1, 2).astype(vl.dtype)
        return dq, dk, dv

    # hop 0 = own block, first rotation peeled — same skeleton as the
    # quantized branch (census op-multiplicity symmetry)
    if spec.interleave > 1:
        # rotate-ahead: K/V depart before even the own-block grads
        kc, vc = _rotate_together(perm, kk, vk)
        dq, dk_t, dv_t = hop_grads(dq0, zk, zv, kk, vk, idx)
        dk_t, dv_t = _rotate_together(perm, dk_t, dv_t)
    else:
        dq, dk_t, dv_t = hop_grads(dq0, zk, zv, kk, vk, idx)
        # K/V and their accumulated grads rotate together, in one launch
        kc, vc, dk_t, dv_t = _rotate_together(perm, kk, vk, dk_t, dv_t)

    def hop(carry, t):
        dq, dk_t, dv_t, kc, vc = carry
        src = lax.rem(idx - t - 1 + spec.sp, spec.sp)
        if spec.interleave > 1:
            # rotate-ahead: same split as the XLA backward — K/V depart
            # under the fused grad kernels, traveling grads follow
            nkc, nvc = _rotate_together(perm, kc, vc)
            dq, dk_t, dv_t = maybe_grads(dq, dk_t, dv_t, kc, vc, src)
            dk_t, dv_t = _rotate_together(perm, dk_t, dv_t)
            return (dq, dk_t, dv_t, nkc, nvc), None
        dq, dk_t, dv_t = maybe_grads(dq, dk_t, dv_t, kc, vc, src)
        # K/V and their accumulated grads rotate together, in one launch
        kc, vc, dk_t, dv_t = _rotate_together(perm, kc, vc, dk_t, dv_t)
        return (dq, dk_t, dv_t, kc, vc), None

    (dq, dk_t, dv_t, kc, vc), _ = lax.scan(
        hop, (dq, dk_t, dv_t, kc, vc), jnp.arange(spec.sp - 2))
    src_last = lax.rem(idx + 1, spec.sp)
    dq, dk_t, dv_t = maybe_grads(dq, dk_t, dv_t, kc, vc, src_last)
    # the traveling grads sit one rank behind their owner — deliver home
    dk_t, dv_t = _rotate_together(perm, dk_t, dv_t)
    dq = dq[:, :, :s_l].swapaxes(1, 2).astype(ql.dtype)
    dk = dk_t[:, :, :s_l].swapaxes(1, 2).astype(kl.dtype)
    dv = dv_t[:, :, :s_l].swapaxes(1, 2).astype(vl.dtype)
    return dq, dk, dv


_ring_local.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ----------------------------------------------------------------------
# Public entry
# ----------------------------------------------------------------------
def ring_attention(q, k, v, topo=None, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   window: Optional[int] = None,
                   placement: str = "contiguous",
                   interleave: int = 1,
                   wire_dtype: str = "fp32"):
    """q/k/v: [B, S, H, D] GLOBAL arrays with S sharded over "seq".
    Returns [B, S, H, D].  GQA KV heads travel the ring unrepeated.  Must
    be called under jit (shard_map manual over the seq + batch axes; on
    current jax the head/tensor dims stay in GSPMD auto mode, while the
    0.4.x compat fallback runs fully manual and replicates tensor-sharded
    heads into each seq shard — see utils/jax_compat.shard_map).

    ``placement``: how sequence blocks map to shards — "contiguous"
    (shard r owns rows [r·S_l, (r+1)·S_l)) or "striped" (shard r owns
    rows r, r+sp, …; the causal-load-balanced layout — see module
    docstring; the caller must feed striped data, cf.
    :func:`stripe_sequence`).

    ``wire_dtype`` (comm_quantization.ring_rotation): "fp32" = the raw
    word-packed rotation; "int8"/"fp8" = block-quantized payloads +
    per-row fp32 scales on the wire — K/V encoded once at ring entry,
    traveling dk/dv re-encoded per hop, dequant in the flash kernels'
    epilogues (int8 + the ``_kernel_enabled()`` gate) or via the shared
    XLA codec otherwise (docs/RING_ATTENTION.md, docs/QUANTIZED_COMM.md).
    Ignored at sp == 1 (no ring, nothing travels)."""
    topo = topo or get_topology()
    sp = topo.sp_size if topo is not None else 1
    nh, nkv = q.shape[2], k.shape[2]
    if nh % nkv:
        raise ValueError(
            f"ring_attention: num_heads={nh} not divisible by "
            f"kv_heads={nkv} — GQA requires an integer group size")
    if window is not None:
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        if not causal:
            raise ValueError(
                "window without causal would be a ONE-SIDED band "
                "(key ∈ (qpos-window, qpos+∞)), which is almost never "
                "intended; pass causal=True for Mistral-style sliding "
                "windows")
    if placement not in PLACEMENTS:
        raise ValueError(f"placement={placement!r}: expected one of "
                         f"{PLACEMENTS}")
    if interleave not in (1, 2):
        raise ValueError(f"interleave={interleave!r}: expected 1 (attend "
                         "then rotate) or 2 (rotate-ahead)")
    if wire_dtype != "fp32":
        from deepspeed_tpu.comm.quantized import validate_wire_dtype

        validate_wire_dtype(wire_dtype)
    rep = nh // nkv
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    if sp == 1:
        if rep != 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return _block_attend_single(q, k, v, scale, causal, window)

    spec = _RingSpec(sp=sp, rep=rep, scale=float(scale), causal=causal,
                     window=window, placement=placement,
                     use_flash=_kernel_enabled(),
                     interleave=int(interleave),
                     wire=str(wire_dtype))

    def body(ql, kl, vl):
        return _ring_local(ql, kl, vl, spec)

    ctx = get_abstract_mesh()
    mesh = topo.mesh if ctx.empty else ctx
    # manual over seq + the batch axes (the ring only communicates over
    # "seq"; keeping batch sharded costs nothing).  On current jax the
    # head/tensor dims stay in GSPMD auto mode, so tensor-sharded heads
    # are NOT gathered; on 0.4.x the compat layer degrades to full manual
    # (partial-auto miscompiles axis_index/ppermute there) and unmentioned
    # axes replicate into each shard instead.
    pspec = P(BATCH_AXES, SEQ_AXIS, None, None)
    return shard_map(body, mesh=mesh, in_specs=(pspec, pspec, pspec),
                     out_specs=pspec, axis_names={SEQ_AXIS, *BATCH_AXES},
                     check_vma=False)(q, k, v)


def _block_attend_single(q, k, v, scale, causal, window):
    """sp=1 degenerate form (same math, no ring)."""
    s_len = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    valid = jnp.ones((s_len, s_len), bool)
    if causal:
        pos = jnp.arange(s_len)
        valid = pos[:, None] >= pos[None, :]
    if window is not None:
        pos = jnp.arange(s_len)
        valid &= (pos[:, None] - pos[None, :]) < window
    s = jnp.where(valid[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
