"""Ulysses sequence parallelism.

Re-design of ``deepspeed/sequence/layer.py`` (DistributedAttention :331,
``_SeqAllToAll`` :277, ``single_all_to_all`` :221): activations are
sequence-sharded everywhere except inside attention, which is head-sharded;
the layout switch seq-sharded ↔ head-sharded is an all-to-all over the
"seq" mesh axis.

Two equivalent TPU-native realisations are provided:

* :func:`ulysses_sharding_constraints` — the GSPMD form used by the engine's
  compiled path: ``with_sharding_constraint`` pins q/k/v to head-sharded and
  the attention output back to seq-sharded, and XLA lowers the resharding to
  ICI all-to-alls (verified in tests by inspecting the HLO).  This is the
  idiomatic replacement for the reference's explicit ``dist.all_to_all``.
* :class:`DistributedAttention` — an explicit ``shard_map`` wrapper with
  hand-written ``lax.all_to_all`` for API parity with the reference (usable
  with any local attention callable).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (BATCH_AXES, SEQ_AXIS,
                                             TENSOR_AXIS, MeshTopology,
                                             get_topology)
from deepspeed_tpu.utils.jax_compat import manual_axis_names, shard_map


def _constraint(x, spec):
    topo = get_topology()
    if topo is None:
        return x
    manual = manual_axis_names()
    if manual:
        # inside a shard_map body (e.g. the pipeline stage_fn on 0.4.x,
        # where the compat shard_map is FULL-manual): a constraint naming
        # a manually-bound axis is a hard partitioner error, and inside a
        # manual region per-shard layouts are explicit so the hint buys
        # nothing — skip it
        named = {a for part in spec if part is not None
                 for a in (part if isinstance(part, (tuple, list))
                           else (part,))}
        if named & manual:
            return x
    return lax.with_sharding_constraint(x, NamedSharding(topo.mesh, spec))


def ulysses_qkv_constraint(q, k, v):
    """Pin q/k/v [B, S, H, D] to head-sharded over the seq axis (XLA inserts
    the seq→head all-to-all). KV heads may be fewer than sp_size (GQA): then
    KV stays seq-sharded and XLA all-gathers inside attention instead.

    Composed with tensor parallelism the heads are already tp-sharded, so
    the target layout shards heads JOINTLY over (tensor, seq) — pinning
    them to seq alone asks the partitioner for a tensor→seq relayout it
    cannot express and it hard-aborts. Requires heads % (tp·sp) == 0."""
    topo = get_topology()
    if topo is None or topo.sp_size == 1:
        return q, k, v
    sp, tp = topo.sp_size, topo.tp_size
    grp = sp * tp
    head_spec = (P(BATCH_AXES, None, (TENSOR_AXIS, SEQ_AXIS), None)
                 if tp > 1 else P(BATCH_AXES, None, SEQ_AXIS, None))
    q = _constraint(q, head_spec) if q.shape[2] % grp == 0 else q
    k = _constraint(k, head_spec) if k.shape[2] % grp == 0 else k
    v = _constraint(v, head_spec) if v.shape[2] % grp == 0 else v
    return q, k, v


def ulysses_output_constraint(out):
    """Pin attention output [B, S, H*D] back to seq-sharded (head→seq
    all-to-all).  Under tp the hidden dim stays TENSOR-sharded — that is
    the row-parallel wo matmul's natural input layout (its contracting dim
    is tp-sharded), so no tensor-axis all-gather is forced here."""
    topo = get_topology()
    if topo is None or topo.sp_size == 1:
        return out
    hid = TENSOR_AXIS if topo.tp_size > 1 else None
    return _constraint(out, P(BATCH_AXES, SEQ_AXIS, hid))


def single_all_to_all(x, scatter_idx: int, gather_idx: int, axis: str = SEQ_AXIS):
    """Explicit all-to-all layout switch (ref single_all_to_all, layer.py:221).
    Must run inside shard_map over ``axis``."""
    return lax.all_to_all(x, axis, split_axis=scatter_idx, concat_axis=gather_idx,
                          tiled=True)


class DistributedAttention:
    """Ulysses attention wrapper (ref DistributedAttention, layer.py:331).

    ``local_attn(q, k, v) -> out`` operates on [B, S_full, H_local, D].
    ``__call__`` takes seq-sharded q/k/v [B, S_local, H, D] *global* arrays
    and runs the scatter-heads/gather-seq a2a → attn → inverse pipeline
    under shard_map.
    """

    def __init__(self, local_attn: Callable, topology: Optional[MeshTopology] = None,
                 scatter_idx: int = 2, gather_idx: int = 1):
        self.local_attn = local_attn
        self.topo = topology or get_topology()
        self.scatter_idx = scatter_idx  # heads dim
        self.gather_idx = gather_idx  # seq dim

    def __call__(self, q, k, v):
        topo = self.topo or get_topology()
        if topo is None or topo.sp_size == 1:
            return self.local_attn(q, k, v)
        sp = topo.sp_size
        if q.shape[self.scatter_idx] % sp != 0:
            raise ValueError(
                f"query heads ({q.shape[self.scatter_idx]}) must be divisible by "
                f"sequence-parallel size {sp} (ref layer.py uneven-heads fallback)")
        if k.shape[self.scatter_idx] % sp != 0:
            # GQA with fewer KV heads than sp ranks: expand KV to the query
            # head count so the head scatter divides evenly (the reference's
            # uneven-head handling, sequence/layer.py:111).
            rep = q.shape[self.scatter_idx] // k.shape[self.scatter_idx]
            k = jnp.repeat(k, rep, axis=self.scatter_idx)
            v = jnp.repeat(v, rep, axis=self.scatter_idx)
        mesh = topo.mesh
        in_spec = P(BATCH_AXES, SEQ_AXIS, None, None)  # seq-sharded
        out_spec = in_spec

        def body(q_l, k_l, v_l):
            # [B, S/sp, H, D] → all-to-all → [B, S, H/sp, D]
            q_h = single_all_to_all(q_l, self.scatter_idx, self.gather_idx)
            k_h = single_all_to_all(k_l, self.scatter_idx, self.gather_idx)
            v_h = single_all_to_all(v_l, self.scatter_idx, self.gather_idx)
            out = self.local_attn(q_h, k_h, v_h)  # [B, S, H/sp, D]
            # inverse: scatter seq, gather heads
            return single_all_to_all(out, self.gather_idx, self.scatter_idx)

        return shard_map(body, mesh=mesh, in_specs=(in_spec, in_spec, in_spec),
                             out_specs=out_spec, check_vma=False)(q, k, v)


class UlyssesAttentionHF(DistributedAttention):
    """Alias mirroring the ALST HF integration entry point
    (ref runtime/sequence_parallel/ulysses_sp.py:49)."""
