"""FPDT — fully pipelined distributed transformer for multi-million-token
contexts.

Re-design of the reference's Ulysses-Offload / FPDT stack
(``deepspeed/sequence/fpdt_layer.py``: ``FPDT_Attention`` :971, chunk
offloading :510, chunked FFN :1056, chunked logits :1137).  The reference
streams sequence chunks through attention eagerly, parking already-computed
KV chunks in pinned host memory and fetching them back per query chunk.

The TPU-native realisation keeps the same capability — activation memory
O(chunk) instead of O(seq) — but expresses it as compiled XLA:

* :func:`chunked_attention` — online-softmax (flash-style) streaming
  attention written as a ``lax.scan`` over query chunks with an inner scan
  over KV chunks.  Peak live attention memory is one [Cq, Ck] score tile per
  head instead of the full [S, S] matrix; XLA's latency-hiding scheduler
  overlaps chunk loads with compute, which is the role the reference's
  explicit double-buffered host prefetch plays.
* ``offload_kv=True`` parks the full K/V in ``pinned_host`` memory and
  fetches one chunk per inner-scan step — the ZeRO-Offload-style host
  tiering of fpdt_layer.py:510 — when the backend supports memory kinds
  (real TPUs; probed via runtime.offload.host_offload_supported).
* :class:`FPDTAttention` — composes Ulysses head-scatter all-to-all with
  chunked attention, mirroring FPDT's "Ulysses + sequence chunking"
  composition.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.parallel.topology import get_topology
from deepspeed_tpu.sequence.layer import DistributedAttention


def _split_chunks(x, chunk: int, axis: int):
    """[..., S, ...] → [..., S//chunk, chunk, ...] moving the chunk count to
    the front for scan."""
    s = x.shape[axis]
    if s % chunk != 0:
        raise ValueError(f"sequence length {s} not divisible by chunk {chunk}")
    n = s // chunk
    new_shape = x.shape[:axis] + (n, chunk) + x.shape[axis + 1:]
    x = x.reshape(new_shape)
    return jnp.moveaxis(x, axis, 0)


def _merge_chunks(x, axis: int):
    """Inverse of :func:`_split_chunks`."""
    x = jnp.moveaxis(x, 0, axis)
    new_shape = x.shape[:axis] + (x.shape[axis] * x.shape[axis + 1],) + x.shape[axis + 2:]
    return x.reshape(new_shape)


def chunked_attention(q, k, v, chunk_size: int, causal: bool = True,
                      sm_scale: Optional[float] = None,
                      offload_kv: bool = False):
    """Streaming attention over sequence chunks (ref FPDT_Attention,
    fpdt_layer.py:971).

    q/k/v: [B, S, H, D] (KV heads may divide query heads — GQA-native: the
    score einsum groups query heads per KV head instead of repeating KV,
    so a GQA model streams 1/group the KV bytes per chunk fetch).
    Returns [B, S, H, D].  Numerics match full softmax attention: the inner
    scan carries the usual (max, sum, weighted-acc) online-softmax state.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    nh, nkv = q.shape[2], k.shape[2]
    if nh % nkv != 0:
        raise ValueError(f"query heads {nh} not a multiple of kv heads {nkv}")
    grp = nh // nkv

    orig_dtype = q.dtype
    qc = _split_chunks(q, chunk_size, axis=1)          # [Nq, B, Cq, H, D]
    kc = _split_chunks(k, chunk_size, axis=1)          # [Nk, B, Ck, H, D]
    vc = _split_chunks(v, chunk_size, axis=1)
    nq = qc.shape[0]

    offload_kv = offload_kv and _memory_space_supported()
    if offload_kv:
        kc, vc = _park_on_host(kc), _park_on_host(vc)

    neg_inf = jnp.finfo(jnp.float32).min

    def q_step(_, qi_and_idx):
        q_i, i = qi_and_idx
        q_i = q_i.astype(jnp.float32) * sm_scale
        b, cq, h, d = q_i.shape
        q_i = q_i.reshape(b, cq, nkv, grp, d)
        m0 = jnp.full((b, nkv, grp, cq), neg_inf, jnp.float32)
        l0 = jnp.zeros((b, nkv, grp, cq), jnp.float32)
        a0 = jnp.zeros((b, nkv, grp, cq, d), jnp.float32)

        def kv_step(carry, kv_and_idx):
            m, l, acc = carry
            k_j, v_j, j = kv_and_idx
            if offload_kv:
                k_j, v_j = _fetch_from_host(k_j), _fetch_from_host(v_j)
            k_j = k_j.astype(jnp.float32)
            v_j = v_j.astype(jnp.float32)
            # [B, nkv, grp, Cq, Ck]
            s = jnp.einsum("bqhgd,bkhd->bhgqk", q_i, k_j)
            if causal:
                qpos = i * chunk_size + lax.broadcasted_iota(jnp.int32, (cq, k_j.shape[1]), 0)
                kpos = j * chunk_size + lax.broadcasted_iota(jnp.int32, (cq, k_j.shape[1]), 1)
                s = jnp.where(qpos >= kpos, s, neg_inf)
            m_new = jnp.maximum(m, s.max(axis=-1))
            # guard fully-masked rows (future chunks) against exp(-inf - -inf)
            p = jnp.exp(s - m_new[..., None])
            p = jnp.where(jnp.isfinite(m_new)[..., None], p, 0.0)
            alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
            l = l * alpha + p.sum(axis=-1)
            acc = acc * alpha[..., None] + jnp.einsum("bhgqk,bkhd->bhgqd", p, v_j)
            return (m_new, l, acc), None

        (m, l, acc), _ = lax.scan(
            kv_step, (m0, l0, a0),
            (kc, vc, jnp.arange(kc.shape[0], dtype=jnp.int32)))
        out = acc / jnp.maximum(l, 1e-30)[..., None]   # [B, nkv, grp, Cq, D]
        return None, jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, cq, h, d)

    _, out = lax.scan(q_step, None, (qc, jnp.arange(nq, dtype=jnp.int32)))
    return _merge_chunks(out, axis=1).astype(orig_dtype)


_MEM_SPACE_PROBE: dict = {}


def _memory_space_supported() -> bool:
    """Compile-probe pinned_host placement under jit (real TPUs: yes; the
    multi-device CPU test backend: no)."""
    plat = jax.devices()[0].platform
    if plat not in _MEM_SPACE_PROBE:
        try:
            from deepspeed_tpu.runtime.infinity import DEVICE, HOST

            def f(a):
                h = jax.device_put(a, HOST)
                return jax.device_put(h, DEVICE)

            jax.jit(f)(jnp.ones((4,))).block_until_ready()
            _MEM_SPACE_PROBE[plat] = True
        except Exception:
            _MEM_SPACE_PROBE[plat] = False
    return _MEM_SPACE_PROBE[plat]


def _park_on_host(x):
    """Move chunked KV to pinned host memory when the backend supports it
    (ref chunk offloading, fpdt_layer.py:510)."""
    try:
        from deepspeed_tpu.runtime.infinity import HOST

        return jax.device_put(x, HOST)
    except Exception:  # CPU test backend: memory kinds unsupported → no-op
        return x


def _fetch_from_host(x):
    try:
        from deepspeed_tpu.runtime.infinity import DEVICE

        return jax.device_put(x, DEVICE)
    except Exception:
        return x


def chunked_ffn(fn, x, num_chunks: int, remat: bool = True):
    """Apply a feed-forward callable over sequence chunks sequentially
    (ref chunked FFN, fpdt_layer.py:1056): live activation memory is one
    chunk's worth; each chunk is rematerialised in backward.

    ``fn(x_chunk) -> y_chunk`` must be shape-preserving in the seq dim.
    x: [B, S, E] → [B, S, E].
    """
    if x.shape[1] % num_chunks != 0:
        raise ValueError(f"seq {x.shape[1]} not divisible by {num_chunks} chunks")
    body = jax.checkpoint(fn) if remat else fn
    xc = _split_chunks(x, x.shape[1] // num_chunks, axis=1)  # [N, B, C, E]

    def step(_, xi):
        return None, body(xi)

    _, yc = lax.scan(step, None, xc)
    return _merge_chunks(yc, axis=1)


class FPDTAttention:
    """Ulysses all-to-all + chunked streaming attention (ref FPDT_Attention,
    fpdt_layer.py:971).

    Sequence-sharded q/k/v [B, S_local, H, D] are head-scattered over the
    ``seq`` mesh axis (Ulysses a2a), then each rank runs chunked attention
    over the full gathered sequence with O(chunk) live memory, then the
    inverse a2a restores seq sharding.  ``offload_kv`` parks gathered KV in
    pinned host memory between chunk fetches on backends that support it.
    """

    def __init__(self, chunk_size: int, causal: bool = True,
                 offload_kv: bool = False, topology=None):
        self.chunk_size = chunk_size
        self.causal = causal
        self.offload_kv = offload_kv
        local = partial(chunked_attention, chunk_size=chunk_size, causal=causal,
                        offload_kv=offload_kv)
        self._dist = DistributedAttention(local, topology=topology)

    def __call__(self, q, k, v):
        topo = self._dist.topo or get_topology()
        if topo is None or topo.sp_size == 1:
            return chunked_attention(q, k, v, self.chunk_size, self.causal,
                                     offload_kv=self.offload_kv)
        return self._dist(q, k, v)
