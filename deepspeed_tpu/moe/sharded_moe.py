"""Mixture-of-Experts: top-k gating with einsum- and sort-based dispatch,
plus an explicit expert-parallel (shard_map + all_to_all) path.

Re-design of ``deepspeed/moe/sharded_moe.py`` (TopKGate :452, top1/top2/topk
gating :183/:290/:374, capacity :161, ``_AllToAll`` dispatch :96).  Three
formulations, one capacity/FCFS semantics:

* **einsum dispatch** (GShard-style): dispatch/combine are [T, E, C] one-hot
  einsums that XLA fuses.  Ideal for small E·C; memory is O(T·E·C).
* **sort dispatch**: flatten the (token, choice) pairs choice-major, stable
  argsort by expert, rank-within-expert via an exclusive-cumsum of counts,
  then a gather into the [E, C, H] expert buffer (and its transpose-gather
  for combine).  Memory is O(T·k + E·C·H) — no [T, E, C] one-hot ever
  materialises — matching the reference's einsum→sort evolution
  (sharded_moe.py:374 uses one-hots; the ragged-ops kernels in
  inference/v2 sort).  Identical drop order to the einsum path: experts
  fill first-come-first-served, first-choice assignments before second.
* **moe_forward_ep**: the expert mesh axis is made *manual* with
  ``shard_map(axis_names={"expert"})`` so the dispatch/return exchanges
  are explicit ``lax.all_to_all`` over ICI — the TPU-native `_AllToAll`
  (ref sharded_moe.py:96) — instead of relying on the automatic SPMD
  partitioner, which involuntarily replicates the dispatch einsum
  (observed in the round-2 multichip dryrun).  Other mesh axes (data,
  tensor, seq) stay automatic.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import EXPERT_AXIS, get_topology
from deepspeed_tpu.utils.jax_compat import (axis_bound_manually,
                                            get_abstract_mesh, shard_map)

# Above this many one-hot elements (T·E·C) "auto" dispatch switches from the
# einsum formulation to the sort-based one (the one-hot would dominate HBM
# traffic; the sorted path is O(T·k)).
_SORT_DISPATCH_THRESHOLD = 1 << 22


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float, k: int,
              min_capacity: int = 4) -> int:
    """Ref: moe/sharded_moe.py:161 — tokens per expert budget."""
    cap = int(capacity_factor * k * num_tokens / num_experts)
    return max(cap, min_capacity)


def top_k_gating(logits: jnp.ndarray, k: int, capacity_factor: float,
                 min_capacity: int = 4, norm_topk: bool = False,
                 select_logits: Optional[jnp.ndarray] = None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k gating with capacity. ``logits``: [T, E] (fp32).

    Returns (l_aux, combine_weights [T, E, C], dispatch_mask [T, E, C]).
    Implements the same load-balancing auxiliary loss as the reference
    (mean(token-fraction-per-expert · router-prob-per-expert) · E).
    ``select_logits``: when given (RSample noisy gating), expert CHOICE
    uses these noisy logits while gate values and the aux loss stay on
    the clean ``logits`` — the reference's split (sharded_moe.py:202).
    """
    t, e = logits.shape
    c = _capacity(t, e, capacity_factor, k, min_capacity)
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

    # Iteratively pick top-k experts per token (static k, unrolled).
    masked = jax.nn.softmax(select_logits, axis=-1) \
        if select_logits is not None else probs
    combine = jnp.zeros((t, e, c), dtype=logits.dtype)
    dispatch = jnp.zeros((t, e, c), dtype=bool)
    # occupancy[e] tracked via cumsum of one-hot selections across tokens
    occupancy = jnp.zeros((e,), dtype=jnp.int32)
    l_aux = jnp.zeros((), dtype=logits.dtype)

    for i in range(k):
        idx = jnp.argmax(masked, axis=-1)  # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [T, E]
        if i == 0:
            # aux loss uses the first-choice assignment (ref top2gating)
            me = jnp.mean(probs, axis=0)
            ce = jnp.mean(onehot.astype(logits.dtype), axis=0)
            l_aux = jnp.sum(me * ce) * e
        # position of each token within its chosen expert's queue
        pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot + occupancy[None, :]  # [T, E]
        pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [T]
        keep = pos < c
        gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0] * keep
        pos_onehot = jax.nn.one_hot(jnp.where(keep, pos, c), c + 1, dtype=logits.dtype)[:, :c]
        combine = combine + gate[:, None, None] * onehot[:, :, None] * pos_onehot[:, None, :]
        dispatch = dispatch | ((onehot[:, :, None] * pos_onehot[:, None, :]) > 0)
        occupancy = occupancy + jnp.sum(onehot * keep[:, None], axis=0)
        masked = masked * (1 - onehot)

    # renormalise combine weights over selected experts: norm_topk (HF
    # mixtral norm_topk_prob) always sums kept weights to 1; the default
    # is the drop-aware top2gating scaling (ref top2gating denom)
    if k > 1:
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        if norm_topk:
            combine = combine / jnp.maximum(denom, 1e-9)
        else:
            combine = combine / jnp.maximum(denom, 1e-9) \
                * jnp.minimum(denom, 1.0)
    return l_aux, combine, dispatch


def top_k_gating_sorted(logits: jnp.ndarray, k: int, capacity_factor: float,
                        min_capacity: int = 4, norm_topk: bool = False,
                        select_logits: Optional[jnp.ndarray] = None):
    """Sort-based top-k gating: no [T, E, C] one-hot.

    Returns (l_aux, slot [T·k] int32 in [0, E·C] with E·C = dropped,
    gate [T·k] fp, c).  Flat entries are **choice-major** (entry
    ``i`` is choice ``i // T`` of token ``i % T``) so that, after the
    stable sort by expert, first-choice assignments fill an expert's
    queue before second choices — the exact FCFS drop order of the
    iterative einsum path above.
    """
    t, e = logits.shape
    c = _capacity(t, e, capacity_factor, k, min_capacity)
    probs = jax.nn.softmax(logits, axis=-1)

    if select_logits is not None:
        # RSample: choose experts by the noisy logits, keep clean gates
        _, top_i = jax.lax.top_k(select_logits, k)   # [T, k]
        top_p = jnp.take_along_axis(probs, top_i, axis=-1)
    else:
        top_p, top_i = jax.lax.top_k(probs, k)       # [T, k]
    # aux loss from the first-choice assignment, via scatter-add counts
    # (no [T, E] one-hot)
    counts0 = jnp.zeros((e,), probs.dtype).at[top_i[:, 0]].add(1.0)
    l_aux = jnp.sum(jnp.mean(probs, axis=0) * (counts0 / t)) * e

    e_flat = top_i.swapaxes(0, 1).reshape(-1)        # [k·T] choice-major
    g_flat = top_p.swapaxes(0, 1).reshape(-1)
    n = e_flat.shape[0]

    perm = jnp.argsort(e_flat, stable=True)
    sorted_e = e_flat[perm]
    counts = jnp.zeros((e,), jnp.int32).at[e_flat].add(1)
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(counts)[:-1]])
    rank_sorted = jnp.arange(n, dtype=jnp.int32) - offsets[sorted_e]
    slot_sorted = jnp.where(rank_sorted < c, sorted_e * c + rank_sorted, e * c)
    slot = jnp.zeros((n,), jnp.int32).at[perm].set(slot_sorted)

    kept = slot < e * c
    gate = g_flat * kept
    if k > 1:
        # renormalise over a token's kept choices (ref top2gating denom;
        # norm_topk = HF mixtral norm_topk_prob semantics)
        per_tok = gate.reshape(k, t)
        denom = jnp.sum(per_tok, axis=0, keepdims=True)
        if norm_topk:
            per_tok = per_tok / jnp.maximum(denom, 1e-9)
        else:
            per_tok = per_tok / jnp.maximum(denom, 1e-9) \
                * jnp.minimum(denom, 1.0)
        gate = per_tok.reshape(-1)
    return l_aux, slot, gate, c


def _expert_ffn(dispatched: jnp.ndarray, p: Dict[str, jnp.ndarray], dt):
    """Batched expert FFN: [E, C, H] → [E, C, H] (one big MXU batch)."""
    if "wg" in p:
        gate = jax.nn.silu(jnp.einsum("ech,ehf->ecf", dispatched, p["wg"].astype(dt)))
        up = jnp.einsum("ech,ehf->ecf", dispatched, p["wi"].astype(dt))
        hidden = gate * up
    else:
        hidden = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", dispatched, p["wi"].astype(dt)),
                             approximate=True)
    return jnp.einsum("ecf,efh->ech", hidden, p["wo"].astype(dt))


def _resolve_dispatch(cfg, t: int, e: int, c: int) -> str:
    mode = getattr(cfg, "moe_dispatch", "auto")
    if mode == "auto":
        return "sorted" if t * e * c > _SORT_DISPATCH_THRESHOLD else "einsum"
    if mode not in _DISPATCHERS:
        raise ValueError(f"moe_dispatch={mode!r}: expected 'auto', "
                         f"{' or '.join(map(repr, _DISPATCHERS))}")
    return mode


def _dispatch_combine_einsum(tokens, logits, cfg, dt, select_logits=None):
    """Einsum formulation: returns (dispatched [E,C,H], combine_fn, aux)."""
    l_aux, combine, dispatch = top_k_gating(
        logits, cfg.top_k, cfg.capacity_factor,
        norm_topk=getattr(cfg, "moe_norm_topk", False),
        select_logits=select_logits)
    dispatched = jnp.einsum("tec,th->ech", dispatch.astype(dt), tokens)

    def combine_fn(expert_out):
        return jnp.einsum("tec,ech->th", combine.astype(dt), expert_out)

    return dispatched, combine_fn, l_aux


def _dispatch_combine_sorted(tokens, logits, cfg, dt, select_logits=None):
    """Sort formulation: gather into [E,C,H] and its transpose for combine."""
    t, h = tokens.shape
    e = logits.shape[1]
    k = cfg.top_k
    l_aux, slot, gate, c = top_k_gating_sorted(
        logits, k, cfg.capacity_factor,
        norm_topk=getattr(cfg, "moe_norm_topk", False),
        select_logits=select_logits)
    token_of = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)     # choice-major
    # slot → source token (E·C+1 wide so the trash slot can't clip-corrupt;
    # empty slots keep the out-of-range sentinel t, gathered as zeros below)
    slot_token = jnp.full((e * c + 1,), t, jnp.int32).at[slot].set(token_of)[:e * c]
    dispatched = jnp.take(tokens, slot_token, axis=0, mode="fill",
                          fill_value=0).reshape(e, c, h)

    def combine_fn(expert_out):
        flat = expert_out.reshape(e * c, h)
        # dropped entries carry the out-of-range slot e*c → zero fill
        contrib = gate.astype(dt)[:, None] * jnp.take(
            flat, slot, axis=0, mode="fill", fill_value=0)     # [k·T, H]
        return jnp.sum(contrib.reshape(k, t, h), axis=0)

    return dispatched, combine_fn, l_aux


_DISPATCHERS = {"einsum": _dispatch_combine_einsum,
                "sorted": _dispatch_combine_sorted}


def _validate_noisy_policy(cfg) -> Optional[str]:
    """Reference noisy_gate_policy (sharded_moe.py:193-202) — one
    validation point for every gating path."""
    policy = getattr(cfg, "moe_noisy_gate_policy", None)
    if policy not in (None, "RSample", "Jitter"):
        raise ValueError(f"noisy_gate_policy={policy!r}: expected "
                         "'RSample', 'Jitter', or None")
    return policy


def _jitter_tokens(tokens, key):
    """'Jitter': multiply the ROUTER's input by uniform(1±1e-2); experts
    still see the clean tokens."""
    eps = 1e-2
    jit = jax.random.uniform(key, tokens.shape,
                             minval=1.0 - eps, maxval=1.0 + eps)
    return tokens * jit.astype(tokens.dtype)


def _rsample_logits(logits, key):
    """'RSample': gumbel-noised logits for expert CHOICE only (gates and
    the aux loss stay on the clean logits)."""
    return logits + jax.random.gumbel(key, logits.shape)


def moe_forward(x: jnp.ndarray, p: Dict[str, jnp.ndarray], cfg,
                noise_key=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE FFN over [B, S, H] activations (single expert group / no manual
    expert axis — expert weights may still be auto-sharded by the mesh).

    Ref call stack: MoE layer → TopKGate → dispatch → Experts → combine
    (deepspeed/moe/layer.py:17, sharded_moe.py:96).
    """
    b, s, h = x.shape
    dt = x.dtype
    tokens = x.reshape(b * s, h)
    # router defaults to fp32 (routing decisions are precision-sensitive;
    # the reference keeps gate logits fp32 too, sharded_moe.py:452) —
    # overridable through the autocast policy's fp32_ops
    from deepspeed_tpu.models.transformer import op_fp32

    rt = jnp.float32 if op_fp32(cfg, "router") else dt
    policy = _validate_noisy_policy(cfg)
    gate_in = _jitter_tokens(tokens, noise_key) \
        if noise_key is not None and policy == "Jitter" else tokens
    logits = (gate_in.astype(rt) @ p["router"].astype(rt)).astype(jnp.float32)
    select = _rsample_logits(logits, noise_key) \
        if noise_key is not None and policy == "RSample" else None
    t, e = logits.shape
    c = _capacity(t, e, cfg.capacity_factor, cfg.top_k)
    mode = _resolve_dispatch(cfg, t, e, c)
    dispatched, combine_fn, l_aux = _DISPATCHERS[mode](tokens, logits, cfg,
                                                       dt, select)
    expert_out = _expert_ffn(dispatched, p, dt)
    out = combine_fn(expert_out)
    out = _residual_mix(tokens, out, p, dt)
    out = out + _shared_expert_out(tokens, p, dt)
    return out.reshape(b, s, h), l_aux.astype(jnp.float32)


def _residual_mix(tokens: jnp.ndarray, routed: jnp.ndarray,
                  p: Dict[str, jnp.ndarray], dt):
    """Residual MoE (PR-MoE, ref moe/layer.py:124-135 use_residual /
    arXiv:2201.05596): a dense expert-shaped MLP runs every token and
    ``softmax(x @ coef)`` mixes it with the routed output —
    ``routed·c₀ + mlp·c₁``.  Identity when params carry no 'residual'."""
    if "residual" not in p:
        return routed
    rp = p["residual"]
    if "wg" in rp:
        hdn = jax.nn.silu(tokens @ rp["wg"].astype(dt)) \
            * (tokens @ rp["wi"].astype(dt))
    else:
        hdn = jax.nn.gelu(tokens @ rp["wi"].astype(dt), approximate=True)
    mlp_out = hdn @ rp["wo"].astype(dt)
    # the 2-way mixing head is tiny and decision-like — fp32, as with the
    # router/shared gates
    coef = jax.nn.softmax(
        tokens.astype(jnp.float32) @ p["coef_w"].astype(jnp.float32)
        + p["coef_b"].astype(jnp.float32), axis=-1).astype(dt)
    return routed * coef[:, 0:1] + mlp_out * coef[:, 1:2]


def _shared_expert_out(tokens: jnp.ndarray, p: Dict[str, jnp.ndarray], dt):
    """Qwen2-MoE shared expert: a dense FFN over every token, scaled by
    sigmoid(x @ shared_gate) and added to the routed output (HF
    Qwen2MoeSparseMoeBlock).  Zero when the params carry no 'shared'."""
    if "shared" not in p:
        return jnp.zeros((), dt)
    sp = p["shared"]
    if "wg" in sp:
        hdn = jax.nn.silu(tokens @ sp["wg"].astype(dt)) \
            * (tokens @ sp["wi"].astype(dt))
    else:
        hdn = jax.nn.gelu(tokens @ sp["wi"].astype(dt))
    y = hdn @ sp["wo"].astype(dt)
    gate = jax.nn.sigmoid(
        tokens.astype(jnp.float32) @ p["shared_gate"].astype(jnp.float32))
    return y * gate.astype(dt)


def moe_forward_ep(x: jnp.ndarray, p: Dict[str, jnp.ndarray], cfg,
                   topo=None, noise_key=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel MoE with explicit all-to-all over the "expert" mesh
    axis (manual shard_map axis; data/tensor/seq stay automatic).

    Per shard: route the local tokens to all E experts, exchange the
    [E, C_loc, H] dispatch buffer so each shard holds its E/ep experts'
    tokens from every peer ([E/ep, ep·C_loc, H]), run the local expert FFN,
    exchange back, combine locally.  This is the reference's `_AllToAll`
    dispatch (sharded_moe.py:96) compiled onto ICI, and it removes the
    automatic partitioner's involuntary replication of the dispatch einsum.
    """
    topo = topo or get_topology()
    ep = topo.ep_size
    b, s, h = x.shape
    dt = x.dtype
    e_total = p["wi"].shape[0]
    if e_total % ep:
        raise ValueError(f"num_experts={e_total} not divisible by the "
                         f"expert mesh axis ({ep})")
    if b % ep:
        raise ValueError(f"batch={b} not divisible by the expert mesh axis "
                         f"({ep}); the expert axis is part of the data-"
                         "parallel product")

    def body(xs, ps):
        bl = xs.shape[0]
        tokens = xs.reshape(bl * s, h)
        # per-shard decorrelated noise key (tokens differ per shard)
        nk = jax.random.fold_in(noise_key, lax.axis_index(EXPERT_AXIS)) \
            if noise_key is not None else None
        policy = _validate_noisy_policy(cfg)
        gate_in = _jitter_tokens(tokens, nk) \
            if nk is not None and policy == "Jitter" else tokens
        # fp32 router matmul: routing precision, and the replicated router's
        # backward psum must not be bf16 (XLA CPU's AllReducePromotion
        # aborts on the bf16 all-reduce that shard_map's transpose of a
        # replicated input otherwise emits)
        logits = gate_in.astype(jnp.float32) @ ps["router"].astype(jnp.float32)
        select = _rsample_logits(logits, nk) \
            if nk is not None and policy == "RSample" else None
        t, e = logits.shape
        c = _capacity(t, e, cfg.capacity_factor, cfg.top_k)
        mode = _resolve_dispatch(cfg, t, e, c)
        dispatched, combine_fn, l_aux = _DISPATCHERS[mode](tokens, logits,
                                                           cfg, dt, select)
        # [E, C_loc, H] → [E/ep, ep·C_loc, H]: shard i keeps experts
        # [i·E/ep, (i+1)·E/ep) and receives their queues from every peer
        dispatched = lax.all_to_all(dispatched, EXPERT_AXIS, split_axis=0,
                                    concat_axis=1, tiled=True)
        expert_out = _expert_ffn(dispatched, ps, dt)
        expert_out = lax.all_to_all(expert_out, EXPERT_AXIS, split_axis=1,
                                    concat_axis=0, tiled=True)
        out = combine_fn(expert_out)
        l_aux = lax.pmean(l_aux, EXPERT_AXIS)
        return out.reshape(bl, s, h), l_aux.astype(jnp.float32)

    # tokens' batch dim is sharded over the expert axis (it is part of the
    # data-parallel product); expert weights over their leading expert dim;
    # the router is replicated.  The shared expert (dense, every token) is
    # computed outside the manual region under the auto partitioner.
    routed_p = {k: v for k, v in p.items()
                if k not in ("shared", "shared_gate", "residual",
                             "coef_w", "coef_b")}
    p_specs = {key: P(EXPERT_AXIS) if key != "router" else P()
               for key in routed_p}
    # inside another shard_map (e.g. the pipeline's manual "pipe" axis) the
    # inner shard_map must be built on the *context* mesh, whose outer axes
    # are already marked Manual — passing the raw device mesh is rejected
    if axis_bound_manually(EXPERT_AXIS):
        # 0.4.x full-manual fallback pipelines: every mesh axis (expert
        # included) is already manual here, so a nested shard_map cannot
        # re-manualize it.  Emulate its boundary by hand — the enclosing
        # region replicates x and the expert params (pipeline in_specs
        # P()/P(pipe)), so slice this rank's token/expert shards, run the
        # body (its collectives bind to the enclosing axis names), and
        # stitch the token shards back with an all_gather.
        from deepspeed_tpu.utils.jax_compat import axis_size as _axis_size

        ep = _axis_size(EXPERT_AXIS)
        eidx = lax.axis_index(EXPERT_AXIS)
        tb = x.shape[0]
        x_l = lax.dynamic_slice_in_dim(x, eidx * (tb // ep), tb // ep, 0)
        p_l = {k: (v if k == "router" else jax.tree.map(
            lambda a: lax.dynamic_slice_in_dim(
                a, eidx * (a.shape[0] // ep), a.shape[0] // ep, 0), v))
            for k, v in routed_p.items()}
        out_l, l_aux = body(x_l, p_l)
        out = lax.all_gather(out_l, EXPERT_AXIS, axis=0, tiled=True)
        # l_aux stays the rank-local value — the mapped version's P()
        # out_spec does the same under check_vma=False (each rank's gate
        # statistics over its token shard; the engine's aux coefficient
        # tolerates the shard-local estimate)
    else:
        ctx = get_abstract_mesh()
        mesh = topo.mesh if ctx.empty else ctx
        mapped = shard_map(
            body, mesh=mesh, axis_names={EXPERT_AXIS},
            in_specs=(P(EXPERT_AXIS), p_specs),
            out_specs=(P(EXPERT_AXIS), P()))
        out, l_aux = mapped(x, routed_p)
    # dense-per-token branches (PR-MoE residual mix, qwen2-moe shared
    # expert) run outside the manual region under the auto partitioner
    if "residual" in p:
        out = _residual_mix(x.reshape(b * s, h), out.reshape(b * s, h), p,
                            dt).reshape(x.shape)
    if "shared" in p:
        out = out + _shared_expert_out(x.reshape(b * s, h), p,
                                       dt).reshape(x.shape)
    return out, l_aux
