"""Mixture-of-Experts: top-k gating + capacity-based einsum dispatch.

Re-design of ``deepspeed/moe/sharded_moe.py`` (TopKGate :452, top1/top2/topk
gating :183/:290/:374, capacity :161, ``_AllToAll`` dispatch :96).  The
reference's einsum-dispatch formulation is itself GShard-derived, which is
exactly the TPU-idiomatic shape: dispatch/combine are one-hot einsums that
XLA fuses, and expert parallelism is expressed by sharding the stacked
expert weights over the ``"expert"`` mesh axis — XLA then inserts the
all-to-all that the reference performs eagerly with ``_AllToAll.apply``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.topology import EXPERT_AXIS, get_topology


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float, k: int,
              min_capacity: int = 4) -> int:
    """Ref: moe/sharded_moe.py:161 — tokens per expert budget."""
    cap = int(capacity_factor * k * num_tokens / num_experts)
    return max(cap, min_capacity)


def top_k_gating(logits: jnp.ndarray, k: int, capacity_factor: float,
                 min_capacity: int = 4) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k gating with capacity. ``logits``: [T, E] (fp32).

    Returns (l_aux, combine_weights [T, E, C], dispatch_mask [T, E, C]).
    Implements the same load-balancing auxiliary loss as the reference
    (mean(token-fraction-per-expert · router-prob-per-expert) · E).
    """
    t, e = logits.shape
    c = _capacity(t, e, capacity_factor, k, min_capacity)
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

    # Iteratively pick top-k experts per token (static k, unrolled).
    masked = probs
    combine = jnp.zeros((t, e, c), dtype=logits.dtype)
    dispatch = jnp.zeros((t, e, c), dtype=bool)
    # occupancy[e] tracked via cumsum of one-hot selections across tokens
    occupancy = jnp.zeros((e,), dtype=jnp.int32)
    l_aux = jnp.zeros((), dtype=logits.dtype)

    for i in range(k):
        idx = jnp.argmax(masked, axis=-1)  # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [T, E]
        if i == 0:
            # aux loss uses the first-choice assignment (ref top2gating)
            me = jnp.mean(probs, axis=0)
            ce = jnp.mean(onehot.astype(logits.dtype), axis=0)
            l_aux = jnp.sum(me * ce) * e
        # position of each token within its chosen expert's queue
        pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot + occupancy[None, :]  # [T, E]
        pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [T]
        keep = pos < c
        gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0] * keep
        pos_onehot = jax.nn.one_hot(jnp.where(keep, pos, c), c + 1, dtype=logits.dtype)[:, :c]
        combine = combine + gate[:, None, None] * onehot[:, :, None] * pos_onehot[:, None, :]
        dispatch = dispatch | ((onehot[:, :, None] * pos_onehot[:, None, :]) > 0)
        occupancy = occupancy + jnp.sum(onehot * keep[:, None], axis=0)
        masked = masked * (1 - onehot)

    # renormalise combine weights over selected experts (ref top2gating denom)
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9) * jnp.minimum(denom, 1.0) \
        if k > 1 else combine
    return l_aux, combine, dispatch


def moe_forward(x: jnp.ndarray, p: Dict[str, jnp.ndarray], cfg) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE FFN over [B, S, H] activations.

    Expert weights ``p["wi"/"wg"/"wo"]`` have a leading expert axis that the
    engine shards over the "expert" mesh axis; the dispatch einsum then
    compiles to an all-to-all over ICI (ref _AllToAll, sharded_moe.py:96).
    """
    b, s, h = x.shape
    dt = x.dtype
    tokens = x.reshape(b * s, h)
    router_logits = (tokens @ p["router"].astype(dt)).astype(jnp.float32)
    l_aux, combine, dispatch = top_k_gating(router_logits, cfg.top_k, cfg.capacity_factor)

    # dispatch: [T,E,C] × [T,H] → [E,C,H]
    dispatched = jnp.einsum("tec,th->ech", dispatch.astype(dt), tokens)
    # expert FFN (batched over experts → rides the MXU in one big batched matmul)
    if "wg" in p:
        gate = jax.nn.silu(jnp.einsum("ech,ehf->ecf", dispatched, p["wg"].astype(dt)))
        up = jnp.einsum("ech,ehf->ecf", dispatched, p["wi"].astype(dt))
        hidden = gate * up
    else:
        hidden = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", dispatched, p["wi"].astype(dt)),
                             approximate=True)
    expert_out = jnp.einsum("ecf,efh->ech", hidden, p["wo"].astype(dt))
    # combine: [T,E,C] × [E,C,H] → [T,H]
    out = jnp.einsum("tec,ech->th", combine.astype(dt), expert_out)
    return out.reshape(b, s, h), l_aux.astype(jnp.float32)
