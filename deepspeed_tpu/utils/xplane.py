"""XPlane trace analysis: device-op timelines and collective/compute
overlap.

The on-chip counterpart of the Domino overlap claim (ref
blogs/deepspeed-domino/README.md:126 — "50-100% of the communication is
hidden"): given an XPlane capture (``jax.profiler.start_trace``), extract
each TPU device plane's op events, classify them as collectives
(all-reduce / all-gather / reduce-scatter / collective-permute /
all-to-all) or compute (fusion / dot / convolution / custom-call), and
measure what fraction of collective wall-time overlaps compute on the
same device — the direct evidence that XLA scheduled chunk B's matmuls
under chunk A's all-reduce.

Parsing uses the xplane proto bundled with tensorflow
(``tensorflow.tsl.profiler.protobuf.xplane_pb2``); everything here is
pure-host analysis, importable without a TPU.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Sequence, Tuple

_COLLECTIVE_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                       "collective-permute", "all-to-all")
# NOTE: no "while" here — the scan-loop parent event spans the whole
# layer loop (collectives included) and would count every in-loop
# collective as hidden, inflating the metric toward 1.0
_COMPUTE_MARKERS = ("fusion", "dot", "convolution", "custom-call")


def find_xplane_files(logdir: str) -> List[str]:
    return sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                            recursive=True))


def load_xspace(path: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def device_op_intervals(xspace, device_substr: str = "TPU"
                        ) -> Dict[str, Dict[str, List[Tuple[int, int]]]]:
    """Per device plane: {"collective": [(start_ps, end_ps)...],
    "compute": [...]} from the XLA-op lines."""
    out: Dict[str, Dict[str, List[Tuple[int, int]]]] = {}
    for plane in xspace.planes:
        if device_substr not in plane.name:
            continue
        buckets = {"collective": [], "compute": []}
        meta = plane.event_metadata
        # TPU device planes carry several hierarchy lines ("XLA Modules",
        # "Steps", "XLA Ops"); only the op-level line has leaf events —
        # parent module/step spans would swallow the collectives.
        op_lines = [ln for ln in plane.lines if "op" in ln.name.lower()]
        for line in (op_lines or plane.lines):
            base = line.timestamp_ns * 1000  # → ps
            for ev in line.events:
                name = meta[ev.metadata_id].name.lower()
                start = base + ev.offset_ps
                end = start + ev.duration_ps
                if any(m in name for m in _COLLECTIVE_MARKERS):
                    buckets["collective"].append((start, end))
                elif any(m in name for m in _COMPUTE_MARKERS):
                    buckets["compute"].append((start, end))
        if buckets["collective"] or buckets["compute"]:
            out[plane.name] = buckets
    return out


def _merge(intervals: Sequence[Tuple[int, int]]) -> List[Tuple[int, int]]:
    merged: List[Tuple[int, int]] = []
    for s, e in sorted(intervals):
        if merged and s <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], e))
        else:
            merged.append((s, e))
    return merged


def overlap_fraction(collective: Sequence[Tuple[int, int]],
                     compute: Sequence[Tuple[int, int]]) -> float:
    """Fraction of total collective time that coincides with compute on
    the same timeline.  1.0 = fully hidden communication."""
    coll = _merge(collective)
    comp = _merge(compute)
    total = sum(e - s for s, e in coll)
    if total == 0:
        return 0.0
    covered = 0
    j = 0
    for s, e in coll:
        while j < len(comp) and comp[j][1] <= s:
            j += 1
        k = j
        while k < len(comp) and comp[k][0] < e:
            covered += min(e, comp[k][1]) - max(s, comp[k][0])
            k += 1
    return covered / total


def top_device_ops(xspace, device_substr: str = "TPU",
                   k: int = 10) -> List[Dict]:
    """Top-k device ops by total self time across matching planes.

    Aggregates leaf op-line events by metadata name; returns
    ``[{"name", "total_ms", "count"}, ...]`` sorted by total time.  When
    no plane matches ``device_substr`` (e.g. a CPU capture, host events
    only), falls back to every plane that has op-shaped lines so the
    caller still sees *something* — flagged by the caller, not here."""
    totals: Dict[str, List[float]] = {}

    def scan(plane) -> None:
        meta = plane.event_metadata
        op_lines = [ln for ln in plane.lines if "op" in ln.name.lower()]
        for line in (op_lines or plane.lines):
            for ev in line.events:
                name = meta[ev.metadata_id].name
                rec = totals.setdefault(name, [0.0, 0])
                rec[0] += ev.duration_ps / 1e9  # ps → ms
                rec[1] += 1

    matched = [p for p in xspace.planes if device_substr in p.name]
    for plane in (matched or xspace.planes):
        scan(plane)
    ranked = sorted(totals.items(), key=lambda kv: -kv[1][0])[:k]
    return [{"name": n, "total_ms": round(t, 4), "count": c}
            for n, (t, c) in ranked]


def classify_op(name: str) -> str:
    """``"collective"`` / ``"compute"`` / ``"other"`` for one device-op
    name — the same marker tables the overlap fraction uses, exposed so
    report consumers (the overlap scheduler) classify identically."""
    n = name.lower()
    if any(m in n for m in _COLLECTIVE_MARKERS):
        return "collective"
    if any(m in n for m in _COMPUTE_MARKERS):
        return "compute"
    return "other"


def dominant_collective(top_ops: Sequence[Dict]) -> Optional[Dict]:
    """Largest collective by total self time in a ``top_device_ops``-shaped
    table → ``{"name", "total_ms"}`` (``None`` when no op classifies as a
    collective — e.g. a CPU capture's host planes)."""
    best: Optional[Dict] = None
    for op in top_ops or ():
        if classify_op(op.get("name", "")) != "collective":
            continue
        if best is None or op.get("total_ms", 0.0) > best["total_ms"]:
            best = {"name": op["name"],
                    "total_ms": float(op.get("total_ms", 0.0))}
    return best


def analyze_logdir(logdir: str, device_substr: str = "TPU") -> Dict:
    """Aggregate overlap stats over every device plane in a capture."""
    files = find_xplane_files(logdir)
    if not files:
        return {"error": f"no xplane files under {logdir}"}
    per_device = {}
    for path in files:
        for dev, b in device_op_intervals(load_xspace(path),
                                          device_substr).items():
            # multi-host captures: every host names its plane
            # /device:TPU:0 — key by file too so hosts don't overwrite
            if len(files) > 1:
                dev = f"{os.path.basename(path)}:{dev}"
            frac = overlap_fraction(b["collective"], b["compute"])
            per_device[dev] = {
                "overlap_fraction": round(frac, 4),
                "collective_ms": round(sum(e - s for s, e
                                           in _merge(b["collective"]))
                                       / 1e9, 3),
                "compute_ms": round(sum(e - s for s, e
                                        in _merge(b["compute"])) / 1e9, 3),
            }
    if not per_device:
        return {"error": "no device planes matched "
                         f"{device_substr!r} (CPU captures carry host "
                         "events only)"}
    fracs = [d["overlap_fraction"] for d in per_device.values()]
    return {"devices": per_device,
            "mean_overlap_fraction": round(sum(fracs) / len(fracs), 4)}
