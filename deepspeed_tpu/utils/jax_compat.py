"""Version-portable spellings of the jax APIs this repo leans on.

The codebase targets the current jax API surface (``jax.shard_map`` with
``axis_names``/``check_vma``, ``jax.sharding.get_abstract_mesh``,
``pltpu.CompilerParams``, ``jax.memory.Space``), but CI images and TPU
pods pin older 0.4.x releases where the same features exist under their
pre-stabilization names (``jax.experimental.shard_map`` with
``auto``/``check_rep``, ``pltpu.TPUCompilerParams``,
``TransferToMemoryKind``).  Every call site imports the helpers here so
the version split lives in exactly one file.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "get_abstract_mesh", "tpu_compiler_params",
           "axis_size", "axis_bound_manually", "memory_spaces"]


def memory_spaces():
    """``(HOST, DEVICE)`` placement targets for ``device_put`` inside
    jit: the ``jax.memory.Space`` enum where it exists (jax >= 0.5);
    on 0.4.x the string-keyed ``TransferToMemoryKind`` carries the same
    placement semantics (``pinned_host`` / ``device``)."""
    try:
        return jax.memory.Space.Host, jax.memory.Space.Device
    except AttributeError:
        from jax._src.sharding_impls import TransferToMemoryKind

        return (TransferToMemoryKind("pinned_host"),
                TransferToMemoryKind("device"))


def axis_bound_manually(axis_name: str) -> bool:
    """Whether ``axis_name`` is already bound as a manual axis at trace
    time on a 0.4.x jax (always False on current jax, where nested
    shard_map resolves through the abstract-mesh context instead).  Used
    by callers that would nest a shard_map over an axis the 0.4.x
    full-manual fallback has already manualized — there the body can run
    directly on the local shard."""
    if hasattr(jax, "shard_map"):
        return False
    from jax._src import core as _core

    try:
        _core.axis_frame(axis_name)
        return True
    except NameError:
        return False


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis (or product over a sequence of
    axes) inside shard_map — ``lax.axis_size`` on current jax,
    ``core.axis_frame`` (which returns the size) on 0.4.x."""
    names = ((axis_name,) if isinstance(axis_name, str) else tuple(axis_name))
    if hasattr(jax.lax, "axis_size"):
        n = 1
        for name in names:
            n *= int(jax.lax.axis_size(name))
        return n
    from jax._src import core as _core

    n = 1
    for name in names:
        n *= int(_core.axis_frame(name))
    return n


def shard_map(f, mesh, in_specs, out_specs, axis_names=None,
              check_vma: bool = False):
    """``jax.shard_map`` with the new-API signature on every jax.

    ``axis_names``: the axes the body is *manual* over (None = all mesh
    axes).  On 0.4.x this maps to the complementary ``auto`` frozenset and
    ``check_vma`` to ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map

    # 0.4.x partial-manual (the `auto` frozenset) miscompiles the patterns
    # this repo needs (axis_index lowers to an unpartitionable PartitionId;
    # scan+ppermute trips a manual-subgroup check in the SPMD partitioner),
    # so fall back to FULL manual: axes the caller left automatic are
    # simply unmentioned in the specs (= replicated into each shard), which
    # is semantically identical and only costs a reshard at the boundary.
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


class _EmptyMesh:
    """Stand-in for an empty abstract mesh on jax versions without
    mesh contexts: ``.empty`` is the only attribute call sites read."""

    empty = True


def get_abstract_mesh():
    """Current abstract mesh context (``.empty`` when not under one)."""
    try:
        return jax.sharding.get_abstract_mesh()
    except AttributeError:
        return _EmptyMesh()


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (named ``TPUCompilerParams`` on 0.4.x)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)


def set_num_cpu_devices(n: int) -> None:
    """``jax.config.update("jax_num_cpu_devices", n)`` where the option
    exists (jax >= 0.5); on 0.4.x the option is absent and the caller's
    ``--xla_force_host_platform_device_count`` XLA_FLAGS entry (read at
    CPU-client creation) is the only mechanism — a silent no-op here."""
    try:
        jax.config.update("jax_num_cpu_devices", max(int(n), 1))
    except AttributeError:
        pass


def manual_axis_names():
    """Mesh axes currently bound MANUALLY (i.e. we are inside a shard_map
    body over them).  On 0.4.x the compat ``shard_map`` above falls back
    to full-manual, where a ``with_sharding_constraint`` naming any bound
    axis is a hard error — layout-hint call sites consult this set and
    skip the hint instead (inside a manual region per-shard layouts are
    explicit, so the hint is meaningless there anyway).  Returns the
    empty set when the introspection API is absent (newer jax: partial-
    manual makes the constraint legal, so applying it stays correct)."""
    try:
        from jax._src import core as _core

        return set(_core.get_axis_env().axis_sizes)
    except Exception:
        return set()
