"""Comms logging — per-op counts/sizes/estimated bandwidth.

Analog of ``deepspeed/utils/comms_logging.py`` (CommsLogger :67) and the
``timed_op`` wrapper (comm/comm.py:102).  On TPU the collectives are compiled
into the XLA program, so per-op wall times are not observable from Python;
instead we record *trace-time* op counts and message sizes (exact) and
estimate bus bandwidth from the algorithm's volume factor, which is what the
reference's ``get_bw`` (:34) computes analytically anyway.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Any, Dict

from deepspeed_tpu.utils.logging import log_dist


def _msg_size_bytes(x: Any) -> int:
    try:
        import numpy as np

        size = int(np.prod(x.shape)) if hasattr(x, "shape") else 1
        itemsize = x.dtype.itemsize if hasattr(x, "dtype") else 4
        return size * itemsize
    except Exception:
        return 0


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n: int) -> Dict[str, float]:
    """Algorithmic vs bus bandwidth, matching ref ``get_bw`` semantics.

    ``n <= 1`` (single device, or a degenerate world) is clamped to a
    volume factor of 1.0: the ring formulas give 0 (all_reduce:
    ``2(n-1)/n``) which used to zero out busbw — there is no inter-chip
    traffic, so bus == algorithmic is the honest number, not 0."""
    if duration_s <= 0:
        return {"algbw_gbps": 0.0, "busbw_gbps": 0.0}
    algbw = size_bytes * 8 / duration_s / 1e9
    n = max(int(n), 1)
    if n == 1:
        factor = 1.0
    elif comm_op in ("all_reduce",):
        factor = 2 * (n - 1) / n
    elif comm_op in ("all_gather", "reduce_scatter", "all_to_all"):
        factor = (n - 1) / n
    else:
        factor = 1.0
    return {"algbw_gbps": algbw, "busbw_gbps": algbw * factor}


class CommsLogger:
    """Records collective op invocations (trace-time on TPU)."""

    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, prof_ops=None, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        self.comms_dict: Dict[str, Dict[int, list]] = defaultdict(lambda: defaultdict(lambda: [0, 0]))

    def configure(self, cfg) -> None:
        self.enabled = cfg.enabled
        self.verbose = cfg.verbose
        self.prof_all = cfg.prof_all
        self.prof_ops = list(cfg.prof_ops)
        self.debug = cfg.debug

    def record(self, op_name: str, x: Any, axis: Any) -> None:
        if not self.enabled:
            return
        if not self.prof_all and op_name not in self.prof_ops:
            return
        size = _msg_size_bytes(x)
        rec = self.comms_dict[op_name][size]
        rec[0] += 1
        rec[1] += size
        if self.verbose:
            log_dist(f"comm op: {op_name} | msg size: {size} B | axis: {axis}")

    def totals(self) -> Dict[str, Dict[str, int]]:
        """Cumulative per-op volume: {op: {"count": n, "bytes": b}} —
        the exact numbers the telemetry StepRecord's comm field carries."""
        out: Dict[str, Dict[str, int]] = {}
        for op_name, sizes in self.comms_dict.items():
            count = sum(c for c, _ in sizes.values())
            total = sum(b for _, b in sizes.values())
            out[op_name] = {"count": count, "bytes": total}
        return out

    def log_summary(self) -> None:
        """Ref: dist.log_summary (comm/comm.py:435).  Each op also gets a
        TOTAL row so overall bytes-per-collective is readable without
        summing message-size buckets by hand."""
        lines = ["Comm. Op            Message Size        Count       Total Bytes"]
        totals = self.totals()
        for op_name, sizes in sorted(self.comms_dict.items()):
            for size, (count, total) in sorted(sizes.items()):
                lines.append(f"{op_name:<20}{size:<20}{count:<12}{total}")
            tot = totals[op_name]
            lines.append(f"{op_name:<20}{'TOTAL':<20}"
                         f"{tot['count']:<12}{tot['bytes']}")
        log_dist("\n".join(lines))

    def reset(self) -> None:
        self.comms_dict.clear()


_COMMS_LOGGER = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return _COMMS_LOGGER
