"""Bounded JAX backend probing.

``jax.devices()`` blocks INDEFINITELY when the default platform's runtime
is unreachable (e.g. a down TPU tunnel), so anything that might touch an
uninitialized backend probes it in a subprocess with a deadline first.
Shared by ``bench.py`` and ``__graft_entry__.py``.
"""

from __future__ import annotations

import subprocess
import sys
from typing import Optional


def backend_is_live() -> bool:
    """Whether THIS process already initialized a JAX backend (checking a
    live backend is instant and safe; only first-touch can hang)."""
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def probe_default_backend(min_devices: int = 1,
                          timeout_s: float = 120.0) -> Optional[str]:
    """Probe the default backend in a subprocess.  Returns None when it is
    reachable with >= min_devices, else a diagnostic string."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             f"import jax; raise SystemExit(0 if len(jax.devices()) >= "
             f"{int(min_devices)} else 1)"],
            capture_output=True, timeout=timeout_s)
        if r.returncode == 0:
            return None
        tail = r.stderr.decode(errors="replace").strip()[-200:]
        return f"device probe exited rc={r.returncode}: {tail}"
    except subprocess.TimeoutExpired:
        return f"device probe timed out after {timeout_s:.0f}s (tunnel down?)"
