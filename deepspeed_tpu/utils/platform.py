"""Platform pinning helper.

The axon TPU plugin pins ``jax_platforms`` via ``jax.config`` at import,
so the ``JAX_PLATFORMS`` env var alone is silently ignored — and with the
TPU tunnel down, any default-backend touch blocks forever.  Every CLI
entry point that must respect the env (dstpu_bench, the autotuner trial
runner, dstpu_report) calls this ONE helper before touching a backend;
the full comma-separated list is passed through so JAX's fallback
semantics (e.g. ``tpu,cpu``) keep working.
"""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Re-pin ``jax_platforms`` from ``$JAX_PLATFORMS`` if set (no-op
    otherwise).  Call BEFORE any backend touch."""
    val = os.environ.get("JAX_PLATFORMS")
    if not val:
        return
    import jax

    jax.config.update("jax_platforms",
                      ",".join(p.strip() for p in val.split(",") if p.strip()))
