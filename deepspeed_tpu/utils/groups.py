"""Process-group getters, mapped onto mesh axes.

Compat shim for ``deepspeed/utils/groups.py`` (get_data_parallel_group
:126, get_tensor_model_parallel_group :110, the world-size/rank getters,
and the _get_expert_* family): reference user code imports these to pass
groups into collectives and to branch on parallel coordinates.  Under
SPMD a "group" for in-jit collectives IS a mesh axis name (or a tuple of
them), directly accepted by every ``ds.comm`` collective's ``group=``
argument — so the *_group() getters return axis names, and the
world-size/rank getters answer from the live topology.

Rank and world-size getters delegate to ``ds.comm.get_rank/
get_world_size(group=...)`` — one implementation of the
coordinate-along-axes rule, shared with the host-object collectives."""

from __future__ import annotations

from typing import Sequence, Tuple, Union

from deepspeed_tpu.parallel.topology import (DATA_AXIS, EXPERT_AXIS,
                                             PIPE_AXIS, SEQ_AXIS,
                                             SUBDATA_AXIS, TENSOR_AXIS,
                                             get_topology)

GroupName = Union[str, Tuple[str, ...]]


def _topo():
    topo = get_topology()
    if topo is None:
        raise RuntimeError(
            "no topology initialized — build the engine (ds.initialize) "
            "or call comm.init_distributed first")
    return topo


def _axis_coord(axis_names: Sequence[str]) -> int:
    from deepspeed_tpu.comm import comm

    _topo()  # uniform RuntimeError when no topology is live
    return comm.get_rank(group=tuple(axis_names))


# -- data parallel ----------------------------------------------------
def get_data_parallel_group() -> GroupName:
    """The reference's DP group = data×subdata×expert here (the axes ZeRO
    reduces gradients over).  Usable directly as ``group=`` in ds.comm."""
    return (DATA_AXIS, SUBDATA_AXIS, EXPERT_AXIS)


def get_data_parallel_world_size() -> int:
    from deepspeed_tpu.comm import comm

    _topo()
    return comm.get_world_size(group=get_data_parallel_group())


def get_data_parallel_rank() -> int:
    return _axis_coord([DATA_AXIS, SUBDATA_AXIS, EXPERT_AXIS])


# -- tensor / model parallel ------------------------------------------
def get_tensor_model_parallel_group() -> GroupName:
    return TENSOR_AXIS


get_model_parallel_group = get_tensor_model_parallel_group


def get_tensor_model_parallel_world_size() -> int:
    return _topo().tp_size


get_model_parallel_world_size = get_tensor_model_parallel_world_size


def get_tensor_model_parallel_rank() -> int:
    return _axis_coord([TENSOR_AXIS])


get_model_parallel_rank = get_tensor_model_parallel_rank


# -- pipeline ---------------------------------------------------------
def get_pipeline_model_parallel_group() -> GroupName:
    return PIPE_AXIS


def get_pipeline_model_parallel_world_size() -> int:
    return _topo().pp_size


def get_pipeline_model_parallel_rank() -> int:
    return _axis_coord([PIPE_AXIS])


# -- sequence parallel ------------------------------------------------
def get_sequence_parallel_group() -> GroupName:
    return SEQ_AXIS


def get_sequence_parallel_world_size() -> int:
    return _topo().sp_size


def get_sequence_parallel_rank() -> int:
    return _axis_coord([SEQ_AXIS])


# -- expert parallel (ref _get_expert_parallel_group family) ----------
def _get_expert_parallel_group(group_name: str = "") -> GroupName:
    """Reference MoE code keys expert groups by "ep_size_N" names; every
    MoE layer here shares the one expert mesh axis."""
    return EXPERT_AXIS


def _get_expert_parallel_world_size(group_name: str = "") -> int:
    return _topo().ep_size


def _get_expert_parallel_rank(group_name: str = "") -> int:
    return _axis_coord([EXPERT_AXIS])


def _get_expert_data_parallel_group(group_name: str = "") -> GroupName:
    """DP-within-experts: the data axes excluding the expert axis."""
    return (DATA_AXIS, SUBDATA_AXIS)


def _get_expert_data_parallel_world_size(group_name: str = "") -> int:
    topo = _topo()
    return topo.sizes[DATA_AXIS] * topo.sizes[SUBDATA_AXIS]


def _get_expert_data_parallel_rank(group_name: str = "") -> int:
    return _axis_coord([DATA_AXIS, SUBDATA_AXIS])


def get_world_group() -> GroupName:
    return tuple(_topo().sizes)


def get_world_size() -> int:
    return _topo().world_size
