"""NUMA-aware core binding for launched host processes.

Analog of ``deepspeed/utils/numa.py`` (``get_numactl_cmd`` :104,
``get_numa_cores`` :24): on multi-socket TPU hosts the input pipeline,
AIO threads, and host optimizer (csrc/cpu_optimizer) are CPU-bound, so
binding each local rank to its slice of cores — and its memory to the
matching NUMA node — avoids cross-socket traffic.

Differences from the reference: missing ``numactl`` degrades to an empty
prefix (the reference prints an install nag); no psutil dependency
(``os.cpu_count``); HBM-flat/fake-NUMA special cases are collapsed into
the general membind rule (bind memory iff the rank's cores sit in one
node).
"""

from __future__ import annotations

import functools
import glob
import os
import shutil
import subprocess
from typing import List, Optional, Sequence, Tuple


def parse_range_list(spec: str) -> List[int]:
    """"0-7,16-23" → [0..7, 16..23] (ref parse_range_list)."""
    cores: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            lo_i, hi_i = int(lo), int(hi)
            if hi_i < lo_i:
                raise ValueError(f"bad core range {part!r}")
            cores.extend(range(lo_i, hi_i + 1))
        else:
            cores.append(int(part))
    if len(set(cores)) != len(cores):
        raise ValueError(f"duplicate cores in {spec!r}")
    return sorted(cores)


def physical_cores() -> List[int]:
    """One logical CPU per physical core (the first thread sibling),
    mirroring the reference's ``psutil.cpu_count(logical=False)`` basis;
    falls back to all logical CPUs when sysfs is unavailable."""
    paths = glob.glob(
        "/sys/devices/system/cpu/cpu*/topology/thread_siblings_list")
    firsts = set()
    for p in paths:
        try:
            with open(p) as f:
                firsts.add(parse_range_list(f.read().strip())[0])
        except (OSError, ValueError):
            return list(range(os.cpu_count() or 1))
    return sorted(firsts) if firsts else list(range(os.cpu_count() or 1))


@functools.lru_cache(maxsize=1)
def get_numa_cores() -> List[List[int]]:
    """Per-NUMA-node core lists via ``numactl --hardware`` (cached —
    topology is static); [] when numactl is unavailable (ref
    get_numa_cores, numa.py:24)."""
    if shutil.which("numactl") is None:
        return []
    try:
        out = subprocess.check_output(["numactl", "--hardware"],
                                      text=True, timeout=10)
    except Exception:
        return []
    nodes: List[List[int]] = []
    for line in out.splitlines():
        if line.startswith("node ") and " cpus:" in line:
            cores = line.split("cpus:", 1)[1].split()
            nodes.append([int(c) for c in cores])
    return nodes


def get_numactl_cmd(bind_core_list: Optional[str], num_local_procs: int,
                    local_rank: int) -> Tuple[List[str], Sequence[int]]:
    """numactl prefix + this rank's core slice (ref get_numactl_cmd,
    numa.py:104).  Empty prefix when numactl is missing."""
    if "KMP_AFFINITY" in os.environ:
        raise ValueError(
            "KMP_AFFINITY conflicts with numactl core binding — unset it "
            "before launching with --bind_cores_to_rank")
    if bind_core_list:
        core_list: Sequence[int] = parse_range_list(bind_core_list)
    else:
        core_list = physical_cores()
    per_rank = len(core_list) // num_local_procs
    if per_rank < 1:
        raise ValueError(
            f"{len(core_list)} cores cannot give every one of "
            f"{num_local_procs} local ranks a core")
    mine = list(core_list)[per_rank * local_rank:per_rank * (local_rank + 1)]
    if shutil.which("numactl") is None:
        return [], mine
    cmd = ["numactl", "-C", f"{mine[0]}-{mine[-1]}"
           if mine == list(range(mine[0], mine[-1] + 1))
           else ",".join(map(str, mine))]
    # bind memory too when the slice lives inside one NUMA node
    for node, cores in enumerate(get_numa_cores()):
        if cores and set(mine) <= set(cores):
            cmd += ["-m", str(node)]
            break
    return cmd, mine
