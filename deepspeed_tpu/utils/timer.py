"""Wall-clock timers and throughput accounting.

TPU-native analog of ``deepspeed/utils/timer.py``: instead of CUDA events we
block on JAX async dispatch with ``jax.block_until_ready`` (opt-in, since on
TPU every forced sync costs pipeline overlap).  Timer names mirror the
reference (``SynchronizedWallClockTimer``, ``ThroughputTimer``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


class Timer:
    """One named timer supporting start/stop/elapsed with accumulation."""

    def __init__(self, name: str, synchronize: bool = False):
        self.name = name
        self.synchronize = synchronize
        self.started = False
        self._start_time = 0.0
        self._elapsed = 0.0
        self._count = 0
        self._records: List[float] = []

    def _sync(self, obj: Any = None) -> None:
        if self.synchronize:
            import jax

            if obj is not None:
                jax.block_until_ready(obj)
            else:
                # Drain all pending device work.
                jax.effects_barrier()

    def start(self) -> None:
        if self.started:
            return
        self._sync()
        self._start_time = time.perf_counter()
        self.started = True

    def stop(self, record: bool = True, ready: Any = None) -> None:
        if not self.started:
            return
        self._sync(ready)
        dt = time.perf_counter() - self._start_time
        self._elapsed += dt
        self._count += 1
        if record:
            self._records.append(dt)
        self.started = False

    def reset(self) -> None:
        self.started = False
        self._elapsed = 0.0
        self._count = 0
        self._records = []

    def elapsed(self, reset: bool = True) -> float:
        """Total elapsed seconds since last reset.

        Reading with ``reset=True`` while the timer is RUNNING must not
        kill the in-flight interval: the accumulators clear, but the
        timer stays started with its start time rebased to now (so the
        eventual ``stop()`` records only the post-read remainder)."""
        now = time.perf_counter()
        value = self._elapsed
        if self.started:
            value += now - self._start_time
        if reset:
            was_running = self.started
            self.reset()
            if was_running:
                self.started = True
                self._start_time = now
        return value

    def mean(self) -> float:
        return self._elapsed / self._count if self._count else 0.0


class SynchronizedWallClockTimer:
    """Group of named timers. ``timer(name)`` creates on first use."""

    def __init__(self, synchronize: bool = False):
        self.timers: Dict[str, Timer] = {}
        self.synchronize = synchronize

    def __call__(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name, synchronize=self.synchronize)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: List[str], reset: bool = True, ranks=None) -> None:
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0
                parts.append(f"{name}: {ms:.2f}ms")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts), ranks=ranks)

    def get_mean(self, names: List[str]) -> Dict[str, float]:
        return {n: self.timers[n].mean() * 1000.0 for n in names if n in self.timers}


class ThroughputTimer:
    """samples/sec + tokens/sec tracking across steps (ref: utils/timer.py).

    ``batch_size`` is the global train batch; call ``start()``/``stop()``
    around each step. The first ``start_step`` steps are treated as warmup.
    """

    def __init__(self,
                 batch_size: int,
                 start_step: int = 2,
                 steps_per_output: Optional[int] = None,
                 monitor_memory: bool = False):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self._start_time = 0.0
        self.started = False

    def start(self) -> None:
        self.started = True
        self._start_time = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True) -> None:
        if not self.started:
            return
        self.started = False
        duration = time.perf_counter() - self._start_time
        if global_step:
            self.global_step_count += 1
            if self.global_step_count > self.start_step:
                self.total_elapsed_time += duration
                self.step_elapsed_time += duration
            if (report_speed and self.steps_per_output
                    and self.global_step_count % self.steps_per_output == 0):
                log_dist(
                    f"step={self.global_step_count}, "
                    f"samples/sec={self.avg_samples_per_sec():.2f}")
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        counted = self.global_step_count - self.start_step
        if counted > 0 and self.total_elapsed_time > 0:
            return counted * self.batch_size / self.total_elapsed_time
        return 0.0
