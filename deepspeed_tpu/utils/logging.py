"""Rank-aware logging for deepspeed_tpu.

TPU-native analog of the reference logging utilities
(``deepspeed/utils/logging.py``): a singleton ``logger`` plus ``log_dist``
which filters by JAX process index instead of torch.distributed rank.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    logger_ = logging.getLogger(name)
    logger_.setLevel(level)
    logger_.propagate = False
    if not logger_.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s",
                datefmt="%Y-%m-%d %H:%M:%S",
            ))
        logger_.addHandler(handler)
    return logger_


_default_level = LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(), logging.INFO)
logger = _create_logger(level=_default_level)


def _process_index() -> int:
    """Current process index; 0 in single-process mode.

    Lazy so that importing logging never forces distributed init.
    """
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable in this image
        return 0


def log_dist(message: str, ranks=None, level=logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (default: rank 0).

    ``ranks=[-1]`` logs on every process. Mirrors the reference ``log_dist``
    (deepspeed/utils/logging.py) with process-index semantics.  ``level``
    may be an int or a level name ("warning").
    """
    if isinstance(level, str):
        resolved = logging.getLevelName(level.upper())
        if not isinstance(resolved, int):
            raise ValueError(f"unknown log level name {level!r}")
        level = resolved
    ranks = ranks or [0]
    my_rank = _process_index()
    if my_rank in ranks or -1 in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str) -> None:
    _warn_cached(message)


@functools.lru_cache(None)
def _warn_cached(message: str) -> None:
    logger.warning(message)


def should_log_le(max_log_level_str: str) -> bool:
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    max_log_level_str = max_log_level_str.lower()
    if max_log_level_str not in LOG_LEVELS:
        raise ValueError(f"{max_log_level_str} is not one of the logging levels")
    return logger.getEffectiveLevel() <= LOG_LEVELS[max_log_level_str]
