"""Safe accessors for ZeRO-sharded params / optimizer state / gradients.

Analog of ``deepspeed/utils/tensor_fragment.py`` (safe_get_full_fp32_param
:134, safe_get_full_optimizer_state :169, safe_get_full_grad :207, the
set_* mirrors, and the stage-3 local-shard variants) — the documented
debugging surface for reaching inside a partitioned engine.

The reference addresses fragments through attributes patched onto
``torch.nn.Parameter``; here params are a functional pytree, so the
address is the engine plus a PATH ("layers/attn/wq", the same strings
``parallel/sharding.py`` rules match).  "Full" accessors return/accept
the complete logical array regardless of ZeRO stage (fetching a sharded
jax.Array materializes every shard on host — exactly the reference's
assemble semantics); "local" accessors work on THIS process's
addressable shard.  Optimizer-state keys use the torch names
(``exp_avg``/``exp_avg_sq``/``momentum``/``sum``) mapped onto the optax
chain's fields (mu/nu/trace/sum).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.parallel.sharding import path_str

# torch optimizer-state key → optax state field
_STATE_KEYS = {
    "exp_avg": "mu",
    "exp_avg_sq": "nu",
    "momentum": "trace",
    "momentum_buffer": "trace",
    "sum": "sum",  # adagrad accumulator (scale_by_rss)
}


def _find_leaf(tree, path: str):
    """Leaf whose sharding-rule path equals ``path`` (path_str form)."""
    hits = [(path_str(p), leaf) for p, leaf
            in jax.tree_util.tree_flatten_with_path(tree)[0]]
    for p, leaf in hits:
        if p == path:
            return leaf
    known = ", ".join(sorted(p for p, _ in hits)[:12])
    raise KeyError(f"no param at path {path!r}; first paths: {known} …")


def _set_leaf(tree, path: str, value):
    matched = []

    def rebuild(p, leaf):
        if path_str(p) == path:
            matched.append(True)
            return value
        return leaf

    out = jax.tree_util.tree_map_with_path(rebuild, tree)
    if not matched:
        raise KeyError(f"no param at path {path!r}")
    return out


def _guard_param_resident(engine, path: str, writing: bool = False) -> None:
    if (getattr(engine, "_param_store", None) is not None
            and path.startswith("layers/")):
        raise RuntimeError(
            "layer params are NVMe-store-resident between steps "
            "(ZeRO-Infinity offload_param device=nvme) — not addressable "
            "through the safe accessors")
    if writing and getattr(engine, "_super_opt", None) is not None:
        raise RuntimeError(
            "SuperOffload keeps authoritative fp32 masters host-side — a "
            "device-param write would be silently overwritten by the next "
            "step. Edit the host store directly (engine._super_opt holds "
            "the masters/moments; see runtime/superoffload.py)")


def _fetch_full(arr) -> np.ndarray:
    """Full host value of a (possibly cross-host-sharded) jax.Array —
    the reference's assemble semantics.  Multi-process arrays ride
    process_allgather (np.asarray raises on non-addressable shards)."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def _locate_state(engine, field: str, path: str):
    """(moment subtree, path within it, writeback) for the optax chain's
    ``field`` — handling the param-streaming engine's split
    {"stream": ..., "resident": ...} state, whose stream subtree mirrors
    params["layers"] with layer-relative paths."""
    state = engine.opt_state
    if state is None:
        raise RuntimeError(
            "optimizer state is not engine-resident (NVMe/SuperOffload "
            "store is authoritative between steps)")

    def moment_of(sub, write):
        for leaf_state in jax.tree_util.tree_leaves(
                sub, is_leaf=lambda x: hasattr(x, "_fields")):
            if hasattr(leaf_state, field):
                def writeback(new_tree, target=leaf_state):
                    def swap(ls):
                        if ls is target:
                            return ls._replace(**{field: new_tree})
                        return ls

                    write(jax.tree_util.tree_map(
                        swap, sub, is_leaf=lambda x: hasattr(x, "_fields")))

                return getattr(leaf_state, field), writeback
        raise KeyError(f"optimizer {engine.optimizer.name!r} carries no "
                       f"{field!r} state")

    if isinstance(state, dict) and set(state) == {"stream", "resident"}:
        if path.startswith("layers/"):
            sub_path = path[len("layers/"):]
            def write(new): engine.opt_state = {**engine.opt_state,
                                                "stream": new}
            tree, wb = moment_of(state["stream"], write)
        else:
            sub_path = path
            def write(new): engine.opt_state = {**engine.opt_state,
                                                "resident": new}
            tree, wb = moment_of(state["resident"], write)
        return tree, sub_path, wb

    def write(new):
        engine.opt_state = new

    tree, wb = moment_of(state, write)
    return tree, path, wb


def safe_get_full_fp32_param(engine, path: str) -> np.ndarray:
    """Full fp32 view of a (possibly ZeRO-sharded) parameter.
    Ref: safe_get_full_fp32_param (tensor_fragment.py:134)."""
    _guard_param_resident(engine, path)
    return _fetch_full(_find_leaf(engine.params, path)).astype(np.float32, copy=False)


def safe_set_full_fp32_param(engine, path: str, value) -> None:
    """Replace a parameter with a full-value update, re-placed onto its
    original sharding.  Ref: safe_set_full_fp32_param."""
    _guard_param_resident(engine, path, writing=True)
    old = _find_leaf(engine.params, path)
    new = jnp.asarray(value, old.dtype).reshape(old.shape)
    new = jax.device_put(new, old.sharding)
    engine.params = _set_leaf(engine.params, path, new)


def safe_get_full_optimizer_state(engine, path: str,
                                  optim_state_key: str) -> np.ndarray:
    """Full fp32 optimizer state of a parameter, by torch key name.
    Ref: safe_get_full_optimizer_state (tensor_fragment.py:169)."""
    field = _STATE_KEYS.get(optim_state_key)
    if field is None:
        raise KeyError(f"unknown optimizer state key {optim_state_key!r} "
                       f"(known: {sorted(_STATE_KEYS)})")
    tree, sub_path, _ = _locate_state(engine, field, path)
    return _fetch_full(_find_leaf(tree, sub_path)).astype(np.float32, copy=False)


def safe_set_full_optimizer_state(engine, path: str, value,
                                  optim_state_key: str) -> None:
    """Ref: safe_set_full_optimizer_state."""
    field = _STATE_KEYS.get(optim_state_key)
    if field is None:
        raise KeyError(f"unknown optimizer state key {optim_state_key!r}")
    tree, sub_path, writeback = _locate_state(engine, field, path)
    old = _find_leaf(tree, sub_path)
    new = jax.device_put(jnp.asarray(value, old.dtype).reshape(old.shape),
                         old.sharding)
    writeback(_set_leaf(tree, sub_path, new))


def _grad_unscale(engine) -> float:
    """fp16 dynamic loss scaling stores SCALED grads in the buffer
    (unscaling happens inside apply_update); divide it out so the
    accessor matches the reference's true-gradient semantics."""
    ls = getattr(engine, "loss_scale_state", None)
    if not ls:
        return 1.0
    return float(np.asarray(ls.get("scale", 1.0)))


def safe_get_full_grad(engine, path: str) -> Optional[np.ndarray]:
    """Accumulated gradient of a parameter between ``backward()`` and
    ``step()`` on the forward/backward/step trio path (the fused
    train_batch consumes grads inside one compiled step — as in the
    reference, None means no gradient is live).  fp16 loss scaling is
    divided out.  Ref: safe_get_full_grad (tensor_fragment.py:207)."""
    buf = getattr(engine, "_grad_buffer", None)
    if buf is None:
        return None
    g = _fetch_full(_find_leaf(buf, path)).astype(np.float32, copy=False)
    return g / _grad_unscale(engine)


# --------------------------------------------------------------------
# Local (this-process shard) API — ref tensor_fragment.py Local API
# --------------------------------------------------------------------
def _local_shard(arr) -> np.ndarray:
    """This process's DISTINCT shards (one per unique index — a
    replicated leaf yields its single full copy, not one per device),
    stacked when several devices hold different partitions locally."""
    seen = {}
    for s in arr.addressable_shards:
        key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
        if key not in seen:
            seen[key] = np.asarray(s.data)
    shards = list(seen.values())
    if len(shards) == 1:
        return shards[0]
    return np.stack(shards)


def safe_get_local_fp32_param(engine, path: str) -> np.ndarray:
    """THIS process's distinct shard(s) of a parameter (stacked when
    several devices hold different partitions locally; a replicated leaf
    returns one full copy).  Ref: safe_get_local_fp32_param."""
    _guard_param_resident(engine, path)
    return _local_shard(_find_leaf(engine.params, path)).astype(np.float32, copy=False)


def safe_get_local_optimizer_state(engine, path: str,
                                   optim_state_key: str) -> np.ndarray:
    field = _STATE_KEYS.get(optim_state_key)
    if field is None:
        raise KeyError(f"unknown optimizer state key {optim_state_key!r}")
    tree, sub_path, _ = _locate_state(engine, field, path)
    return _local_shard(_find_leaf(tree, sub_path)).astype(np.float32, copy=False)


def safe_get_local_grad(engine, path: str) -> Optional[np.ndarray]:
    buf = getattr(engine, "_grad_buffer", None)
    if buf is None:
        return None
    g = _local_shard(_find_leaf(buf, path)).astype(np.float32, copy=False)
    return g / _grad_unscale(engine)
