"""Device tracing — the TPU analog of the reference's NVTX ranges and
pytorch-profiler integration (``deepspeed/utils/nvtx.py instrument_w_nvtx``,
``accelerator range_push/range_pop``, ``docs/_tutorials/pytorch-profiler.md``).

On TPU the profiler artifact is an XPlane trace viewable in
TensorBoard/XProf/Perfetto: ``jax.profiler.start_trace(logdir)`` captures
host + device timelines, ``TraceAnnotation`` plays the role of
``nvtx.range_push`` (named host spans that bracket the device ops they
dispatch), and ``StepTraceAnnotation`` marks training steps so the trace
viewer groups per-step work.  The engine drives this from the
``"profiler"`` config block (see runtime/config.py ProfilerConfig);
:class:`TraceProfiler` is the standalone surface.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from deepspeed_tpu.utils.logging import logger


def instrument_w_trace(func=None, *, name: Optional[str] = None):
    """Decorator: run the function under a named trace annotation (ref
    instrument_w_nvtx, utils/nvtx.py) — shows up as a host span in the
    XPlane trace when a capture is active; free otherwise."""

    def deco(f):
        label = name or getattr(f, "__qualname__", getattr(f, "__name__",
                                                           "fn"))

        @functools.wraps(f)
        def wrapped(*args, **kw):
            with jax.profiler.TraceAnnotation(label):
                return f(*args, **kw)

        return wrapped

    return deco(func) if func is not None else deco


def range_push(msg: str) -> None:
    """Delegates to the accelerator's range stack (the single owner —
    a second independent stack here would let mixed push/pop pairs exit
    the wrong annotation).  Ref accelerator range_push,
    abstract_accelerator.py:190."""
    from deepspeed_tpu.accelerator import get_accelerator

    get_accelerator().range_push(msg)


def range_pop() -> None:
    """Pop the innermost accelerator range.  Unbalanced pops (empty
    stack) warn and no-op rather than raising — see
    ``abstract_accelerator.range_pop``."""
    from deepspeed_tpu.accelerator import get_accelerator

    get_accelerator().range_pop()


class TraceProfiler:
    """Windowed XPlane capture driven by step numbers.

    ``maybe_start/maybe_stop(step)`` bracket the configured
    [start_step, start_step + num_steps) window; ``step(n)`` returns a
    ``StepTraceAnnotation`` context for one train step (the TensorBoard
    profile plugin uses these markers for its per-step breakdown)."""

    def __init__(self, output_dir: str, start_step: int = 1,
                 num_steps: int = 3):
        self.output_dir = output_dir
        self.start_step = int(start_step)
        self.num_steps = int(num_steps)
        self.active = False
        self.done = False

    def maybe_start(self, step: int) -> None:
        if self.done or self.active or step < self.start_step:
            return
        if step >= self.start_step + self.num_steps:
            # resumed past the configured window (e.g. checkpoint reload
            # with start_step=1): capturing one arbitrary late step would
            # not be what the config asked for
            logger.warning(
                f"TraceProfiler: step {step} is past the configured window "
                f"[{self.start_step}, {self.start_step + self.num_steps}) "
                "— skipping capture")
            self.done = True
            return
        try:
            jax.profiler.start_trace(self.output_dir)
            self.active = True
            logger.info(f"TraceProfiler: capturing steps "
                        f"[{step}, {step + self.num_steps}) → "
                        f"{self.output_dir}")
        except Exception as e:  # profiler already active elsewhere
            logger.warning(f"TraceProfiler: start_trace failed: {e}")
            self.done = True

    def step(self, step: int):
        if self.active:
            return jax.profiler.StepTraceAnnotation("train_batch",
                                                    step_num=step)
        import contextlib

        return contextlib.nullcontext()

    def maybe_stop(self, step: int) -> None:
        if not self.active or step < self.start_step + self.num_steps:
            return
        self.close()

    def close(self) -> None:
        """Flush an active capture (engine.destroy() calls this so a run
        that ends inside the window still writes its trace)."""
        if not self.active:
            return
        try:
            # drain the device before stopping: train_batch returns at
            # dispatch time, and stop_trace while the window's steps are
            # still executing truncates their device timeline.  Fetching
            # a fresh op's VALUE is the hard sync (TPU streams are
            # in-order; plain block_until_ready returns early under the
            # axon relay).
            import numpy as _np

            import jax.numpy as _jnp

            float(_np.asarray(_jnp.zeros(())))
            jax.profiler.stop_trace()
        finally:
            self.active = False
            self.done = True
        logger.info(f"TraceProfiler: trace written to {self.output_dir}")
