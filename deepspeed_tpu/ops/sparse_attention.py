"""Block-sparse attention with DeepSpeed-compatible sparsity configs.

Analog of ``deepspeed/ops/sparse_attention/`` (``sparsity_config.py``
configs, ``sparse_self_attention.py``, Triton ``matmul.py``/``softmax.py``).
The reference builds a per-head block *layout* [H, nb, nb] and runs
Triton block-sparse kernels.  Here the same configs build the same
layouts; :func:`sparse_attention` dispatches to the Pallas block-sparse
kernel (ops/pallas/block_sparse_mha.py) on TPU — dead layout tiles are
skipped at the grid level, costing neither FLOPs nor K/V bandwidth, the
analog of the reference's Triton SDD/DSD block skipping — and falls back
to a dense attention masked at block granularity elsewhere.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class SparsityConfig:
    """Base (ref sparsity_config.py SparsityConfig): block layout builder."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"seq len {seq_len} not divisible by block "
                             f"{self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), dtype=np.int64)

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attend (ref DenseSparsityConfig) — for testing parity."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local windows + periodic global blocks (ref FixedSparsityConfig).

    Each query block attends its own ``num_local_blocks`` window plus the
    last ``num_global_blocks`` of every window (the "summary" blocks).
    """

    def __init__(self, num_heads: int, block: int = 16,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False, **kw):
        super().__init__(num_heads, block, kw.get("different_layout_per_head", False))
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for q in range(nb):
            w0 = (q // self.num_local_blocks) * self.num_local_blocks
            # local window
            for k in range(w0, min(w0 + self.num_local_blocks, nb)):
                layout[:, q, k] = 1
            # global (summary) blocks: last num_global_blocks of each
            # preceding window
            for wstart in range(0, nb, self.num_local_blocks):
                gstart = wstart + self.num_local_blocks - self.num_global_blocks
                for k in range(max(wstart, gstart), min(wstart + self.num_local_blocks, nb)):
                    if k <= q or self.attention == "bidirectional":
                        layout[:, q, k] = 1
                    if self.horizontal_global_attention:
                        layout[:, k, q] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + explicit global blocks (ref
    BSLongformerSparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices=(0,), attention: str = "bidirectional",
                 **kw):
        super().__init__(num_heads, block, kw.get("different_layout_per_head", False))
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        half = self.num_sliding_window_blocks // 2
        for q in range(nb):
            for k in range(max(0, q - half), min(nb, q + half + 1)):
                layout[:, q, k] = 1
        for g in self.global_block_indices:
            if g < nb:
                layout[:, g, :] = 1  # global row
                layout[:, :, g] = 1  # global column
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding + global blocks (ref BigBirdSparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, attention: str = "bidirectional",
                 seed: int = 0, **kw):
        super().__init__(num_heads, block,
                         kw.get("different_layout_per_head", False))
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        half = self.num_sliding_window_blocks // 2
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_heads if self.different_layout_per_head else 1):
            for q in range(nb):
                for k in range(max(0, q - half), min(nb, q + half + 1)):
                    layout[h, q, k] = 1
                ks = rng.choice(nb, size=min(self.num_random_blocks, nb),
                                replace=False)
                layout[h, q, ks] = 1
            g = self.num_global_blocks
            layout[h, :g, :] = 1
            layout[h, :, :g] = 1
        if not self.different_layout_per_head:
            layout[:] = layout[0]
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Per-head variable local windows + globals (ref
    VariableSparsityConfig, simplified: explicit window list)."""

    def __init__(self, num_heads: int, block: int = 16,
                 local_window_blocks=(4,), global_block_indices=(0,),
                 attention: str = "bidirectional", **kw):
        super().__init__(num_heads, block, True)
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for h in range(self.num_heads):
            w = self.local_window_blocks[min(h, len(self.local_window_blocks) - 1)]
            for q in range(nb):
                w0 = (q // w) * w
                layout[h, q, w0:min(w0 + w, nb)] = 1
        for g in self.global_block_indices:
            if g < nb:
                layout[:, g, :] = 1
                layout[:, :, g] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


# ----------------------------------------------------------------------
def layout_to_token_mask(layout: np.ndarray, block: int) -> jnp.ndarray:
    """[H, nb, nb] block layout → [H, S, S] boolean token mask."""
    m = jnp.asarray(layout, jnp.bool_)
    return jnp.repeat(jnp.repeat(m, block, axis=1), block, axis=2)


def sparse_attention(q, k, v, sparsity_config: SparsityConfig,
                     causal: bool = False,
                     sm_scale: Optional[float] = None,
                     impl: str = "auto") -> jnp.ndarray:
    """Block-sparse attention (ref SparseSelfAttention forward).

    q/k/v: [B, S, H, D] → [B, S, H, D] (GQA: k/v may carry fewer heads).
    The block layout masks the score matrix; causal composes a
    lower-triangular mask on top.  ``impl='auto'`` takes the Pallas
    block-skipping kernel on TPU (ops/pallas/block_sparse_mha.py — dead
    layout tiles cost neither FLOPs nor K/V DMA, the reference's Triton
    matmul.py behavior); ``'xla'`` forces the dense-masked lowering.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = q.shape[1]
    layout = sparsity_config.make_layout(s)

    if impl in ("auto", "pallas"):
        import importlib

        bsm = importlib.import_module(
            "deepspeed_tpu.ops.pallas.block_sparse_mha")
        fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")
        on_tpu = jax.default_backend() == "tpu"
        lb = sparsity_config.block
        ok = (s % lb == 0 and bsm.supports(s, q.shape[-1], lb, q.shape[2],
                                           layout_heads=layout.shape[0]))
        if impl == "pallas" and not ok:
            raise ValueError(
                f"impl='pallas' but the block-sparse kernel does not apply "
                f"(seq {s}, block {lb}, heads {q.shape[2]} vs layout "
                f"{layout.shape[0]}) — fix the config or use impl='auto'")
        if (on_tpu or fm.INTERPRET or impl == "pallas") and ok:
            out = bsm.block_sparse_mha(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), layout, lb, causal=causal,
                sm_scale=sm_scale)
            return out.transpose(0, 2, 1, 3)

    if k.shape[2] != q.shape[2]:  # GQA: expand kv heads for the dense path
        g = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    mask = layout_to_token_mask(layout, sparsity_config.block)  # [H, S, S]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * sm_scale,
                        k.astype(jnp.float32))
    neg = jnp.finfo(jnp.float32).min
    scores = jnp.where(mask[None], scores, neg)
    if causal:
        cm = jnp.tril(jnp.ones((s, s), jnp.bool_))
        scores = jnp.where(cm[None, None], scores, neg)
    # rows with no visible keys (can happen off-layout) → uniform zeros
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(jnp.isfinite(scores).any(-1, keepdims=True), probs, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
