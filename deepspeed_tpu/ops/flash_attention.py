"""Flash attention for TPU.

Replaces the reference's fused attention CUDA kernels
(``csrc/transformer``/FlashAttention paths) with the Pallas TPU flash
attention kernel (tiled online-softmax over VMEM blocks, custom VJP).  On
non-TPU backends (the 8-device CPU test mesh) it falls back to a numerically
equivalent XLA implementation so the same model code runs everywhere.

Layout contract: q, k, v are ``[batch, seq, heads, head_dim]`` (the model's
natural layout); the kernel operates in ``[batch, heads, seq, head_dim]``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _xla_attention(q, k, v, causal: bool, sm_scale: float):
    b, s_q, h, d = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    if causal:
        s_k = k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block_for(s: int, max_block: int = 512) -> int | None:
    """Largest block ≤ max_block that divides ``s`` and is a multiple of
    the 128-lane register width; None if the kernel can't tile ``s``."""
    for blk in range(min(max_block, s), 127, -128):
        if blk % 128 == 0 and s % blk == 0:
            return blk
    return None


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "impl"))
def flash_attention(q, k, v, causal: bool = True, sm_scale: float | None = None,
                    impl: str = "auto"):
    """Multi-head attention over [B, S, H, D] tensors.

    ``impl``: "auto" (pallas on TPU, XLA elsewhere) | "pallas" | "xla".
    GQA is handled by repeating KV heads before the kernel.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    nh, nkv = q.shape[2], k.shape[2]
    if nkv != nh:
        k = jnp.repeat(k, nh // nkv, axis=2)
        v = jnp.repeat(v, nh // nkv, axis=2)

    use_pallas = impl == "pallas" or (impl == "auto" and _on_tpu())
    # the TPU kernel needs the block size to divide the sequence; pick the
    # largest lane-aligned divisor ≤ 512, else fall back to the XLA path
    blk = _block_for(q.shape[1]) if use_pallas else None
    if not use_pallas or blk is None:
        return _xla_attention(q, k, v, causal, sm_scale)

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as pallas_flash)

    qt = q.swapaxes(1, 2)  # [B, H, S, D]
    kt = k.swapaxes(1, 2)
    vt = v.swapaxes(1, 2)
    sizes = BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=blk, block_k_major_dkv=blk, block_k_dkv=blk,
        block_q_dkv=blk, block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk)
    out = pallas_flash(qt, kt, vt, causal=causal, sm_scale=sm_scale,
                       block_sizes=sizes)
    return out.swapaxes(1, 2)
