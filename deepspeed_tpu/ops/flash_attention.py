"""Flash attention for TPU.

Replaces the reference's fused attention CUDA kernels
(``csrc/transformer``/FlashAttention paths). The default TPU path is the
**repo-owned** Pallas kernel (`deepspeed_tpu.ops.pallas.flash_mha`):
GQA-native (KV never repeated), any sequence length (tail-pad + in-kernel
mask — no silent O(S²) fallback), saved-residual backward. The upstream
jax library kernel remains available as ``impl="pallas_lib"``; non-TPU
backends (the 8-device CPU test mesh) use a numerically equivalent XLA
implementation so the same model code runs everywhere.

Layout contract: q, k, v are ``[batch, seq, heads, head_dim]`` (the model's
natural layout); the kernels operate in ``[batch, heads, seq, head_dim]``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

_warned_fallback = False


def _repeat_kv(q, k, v):
    """Repeat KV heads up to the query head count (GQA -> MHA) for the
    paths whose kernels are not GQA-native."""
    nh, nkv = q.shape[2], k.shape[2]
    if nkv != nh:
        k = jnp.repeat(k, nh // nkv, axis=2)
        v = jnp.repeat(v, nh // nkv, axis=2)
    return k, v


def _xla_attention(q, k, v, causal: bool, sm_scale: float,
                   window: int | None = None):
    b, s_q, h, d = q.shape
    k, v = _repeat_kv(q, k, v)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    s_k = k.shape[1]
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
    if window is not None:
        qpos = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0) + (s_k - s_q)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        wm = qpos - kpos < window
        mask = wm if mask is None else mask & wm
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block_for(s: int, max_block: int = 512) -> int | None:
    """Largest block ≤ max_block that divides ``s`` and is a multiple of
    the 128-lane register width; None if the library kernel can't tile
    ``s``."""
    for blk in range(min(max_block, s), 127, -128):
        if blk % 128 == 0 and s % blk == 0:
            return blk
    return None


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _lib_flash(q, k, v, causal, sm_scale, blk):
    """Upstream jax.experimental Pallas kernel (KV repeated to MHA)."""
    k, v = _repeat_kv(q, k, v)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as pallas_flash)

    qt = q.swapaxes(1, 2)  # [B, H, S, D]
    kt = k.swapaxes(1, 2)
    vt = v.swapaxes(1, 2)
    sizes = BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=blk, block_k_major_dkv=blk, block_k_dkv=blk,
        block_q_dkv=blk, block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk)
    out = pallas_flash(qt, kt, vt, causal=causal, sm_scale=sm_scale,
                       block_sizes=sizes)
    return out.swapaxes(1, 2)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale", "impl",
                                             "window"))
def flash_attention(q, k, v, causal: bool = True, sm_scale: float | None = None,
                    impl: str = "auto", window: int | None = None):
    """Multi-head attention over [B, S, H, D] tensors.

    ``impl``: "auto" (repo Pallas kernel on TPU, XLA elsewhere) | "pallas"
    (repo kernel) | "pallas_lib" (upstream library kernel) | "xla".
    """
    global _warned_fallback
    if window is not None and window <= 0:
        raise ValueError(f"window must be positive (got {window}); pass "
                         "None to disable sliding-window masking")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    if impl == "xla" or not (impl in ("auto", "pallas", "pallas_lib")
                             and _on_tpu()):
        return _xla_attention(q, k, v, causal, sm_scale, window=window)

    if impl == "pallas_lib":
        if window is not None:  # library kernel has no window support
            impl = "pallas"
        else:
            blk = _block_for(q.shape[1])
            if blk is None:
                if not _warned_fallback:
                    logger.warning(
                        "flash_attention: seq %d has no 128-aligned divisor; "
                        "library kernel unavailable, using XLA attention",
                        q.shape[1])
                    _warned_fallback = True
                return _xla_attention(q, k, v, causal, sm_scale,
                                      window=window)
            return _lib_flash(q, k, v, causal, sm_scale, blk)

    from deepspeed_tpu.ops.pallas import flash_mha
    from deepspeed_tpu.ops.pallas.flash_mha import supports

    if not supports(q.shape[1], q.shape[-1]):
        # beyond even the KV-blocked path's ceiling (S·D > 2^25) — shard
        # the sequence (Ulysses/FPDT) at such lengths. Last resorts: the
        # library kernel (repeats KV, no window), then XLA.
        blk = _block_for(q.shape[1]) if window is None else None
        if blk is not None:
            return _lib_flash(q, k, v, causal, sm_scale, blk)
        if not _warned_fallback:
            logger.warning(
                "flash_attention: seq %d (head_dim %d) exceeds kernel "
                "budgets; using XLA attention", q.shape[1], q.shape[-1])
            _warned_fallback = True
        return _xla_attention(q, k, v, causal, sm_scale, window=window)

    out = flash_mha(q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2),
                    causal, sm_scale, window)
    return out.swapaxes(1, 2)
