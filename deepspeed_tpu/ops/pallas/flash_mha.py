"""Repo-owned Pallas flash attention for TPU training.

TPU replacement for the reference's fused attention CUDA kernels
(``csrc/transformer/inference/csrc/softmax.cu``,
``deepspeed/ops/transformer`` FlashAttention paths) — written from scratch
for the TPU memory hierarchy rather than ported:

* **Full KV resident in VMEM** per (batch, kv-head) program. At training
  sequence lengths (S·D ≤ ~512K elements, e.g. 8K × 64) K and V fit on-chip,
  so each q-block does a single-shot softmax over one [bq, S] score matrix —
  two big MXU matmuls — instead of the chunked online-softmax loop a GPU
  kernel needs.
* **KV-blocked long-context path**: beyond the VMEM-resident budget a
  second set of kernels runs a 4D grid (B, H, nq, nk) with classic online
  softmax over 512-row KV blocks — (m, l, acc) accumulators in VMEM
  scratch persist across the sequential k steps; causally-dead blocks skip
  both compute (``pl.when``) and bandwidth (their block index clamps to
  the last live block, which the pipeline recognises as unchanged and
  does not refetch) — lifting the ceiling to S·D ≤ 2²⁵ (256K tokens
  at d=128) while keeping the same GQA index maps. This serves the Ulysses
  per-shard sequence lengths of the 1M-token long-context milestone
  without ever repeating KV (the library-kernel fallback the round-2
  verdict flagged).
* **GQA-native**: the kernel grid runs over query heads and the K/V
  BlockSpec index map folds ``h → h // group`` — KV is never repeated in
  HBM (the reference repeats KV to full MHA; VERDICT round-1 flagged the
  8× KV-bandwidth waste for Llama-3-70B-class models).
* **Any length**: the wrapper pads S up to a lane-aligned block multiple.
  Tail-padding is masked in-kernel (pad keys never attended, pad query rows
  sliced off), so there is no silent O(S²) XLA fallback for S % 128 != 0.
* **Saved-residual backward**: a custom VJP saves (q, k, v, o, lse) and the
  outputs are tagged with ``checkpoint_name`` ("flash_out"/"flash_lse"), so
  the engine's remat policy can keep them and the backward never re-runs the
  forward kernel (the upstream library kernel always recomputes under
  remat).

Layout contract: q is ``[B, Hq, S, D]``, k/v are ``[B, Hkv, S, D]``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# K + V resident per program: S * D * 2 bytes * 2 tensors ≤ ~4 MB
_MAX_KV_ELEMS = 1 << 20  # S * D
# KV-blocked path ceiling: bounded by the fp32 [B, H, S, 128]
# lane-replicated lse/delta residuals in HBM, not VMEM (256K at d=128)
_MAX_BLOCKED_ELEMS = 1 << 25  # S * D
# q/k block edges for the KV-blocked path (scores tile = bq×bk×4 B in
# VMEM).  None → per-call heuristic (_choose_blocks); tools/
# bench_flash_longseq.py sweeps explicit values on-chip.  Measured r04
# (v5e, S=32k MHA, full fwd+bwd with dk/dv live): 1024×1024 runs 57.8
# TF/s (d=64) / 113.4 TF/s (d=128) vs 37.4 / 72.2 for 512×512 — ~1.55×;
# bigger tiles amortize the per-tile online-softmax state updates and
# masking work.
_BLK_Q = None
_BLK_K = None


def _choose_blocks(group: int):
    """1024² tiles for MHA; 512² under GQA, whose grouped dkv kernel holds
    the whole [group, bq(, 128-lane fp32 lse/delta)] q-side per program —
    at group 4, d=128 the 1024-edge blocks overrun scoped VMEM.

    Overrides: setting either _BLK_Q/_BLK_K fills the other from it.
    Both must be powers of two — s_pad uses max(bq, bk) as the common
    block multiple, which is only the lcm for powers of two (a 384-edge
    override would silently leave tail query rows uncomputed)."""
    if _BLK_Q is not None or _BLK_K is not None:
        bq = _BLK_Q or _BLK_K
        bk = _BLK_K or _BLK_Q
        if (bq & (bq - 1)) or (bk & (bk - 1)):
            raise ValueError(
                f"_BLK_Q/_BLK_K must be powers of two, got ({bq}, {bk})")
        return bq, bk
    # GQA: widen only the k edge — the grouped dkv q-side (group·bq rows
    # of q/do plus 128-lane fp32 lse/delta, double-buffered) bounds bq,
    # while bk only adds one bf16 KV block; (512, 1024) measured ~1.3×
    # over 512² on the MHA sweep with the same VMEM-light footprint
    return (1024, 1024) if group == 1 else (512, 1024)

# Set True (tests/conftest or CI) to run the kernels through the Pallas
# interpreter so numerics are checkable on the CPU mesh.
INTERPRET = False


def _choose_bq(s_pad: int, scores_budget: int = 1 << 20) -> int:
    """Largest q-block in {512, 384, 256, 128} dividing s_pad with a
    [bq, s_pad] fp32 score matrix within budget (≤ 4 MB)."""
    for bq in (512, 384, 256, 128):
        if s_pad % bq == 0 and bq * s_pad <= scores_budget:
            return bq
    return 128


# Resident-path sequence ceiling.  Measured r04 (v5e, d=64, MHA): past
# ~2k the KV-blocked kernels overtake the one-shot-softmax resident path
# (fwd+bwd 1.5x faster at 4k, 1.8x at 8k) — the resident bwd's grouped
# full-sequence q-side stops paying for itself once the score matrix
# spans many 128-row strips.  Below 2k the two are equal and resident
# keeps the smaller launch graph.
_RESIDENT_MAX_SEQ = 2048


def _supports_resident(s: int, d: int) -> bool:
    """Whether the VMEM-resident strategy applies: K+V resident within
    budget AND a q-block exists whose score matrix fits (so _choose_bq's
    fallback can never exceed the documented bound) AND the sequence is
    short enough that the one-shot softmax still beats the blocked path
    (see _RESIDENT_MAX_SEQ)."""
    s_pad = -(-s // 128) * 128
    return (s_pad * d <= _MAX_KV_ELEMS and 128 * s_pad <= (1 << 20)
            and s_pad <= _RESIDENT_MAX_SEQ)


def supports(s: int, d: int) -> bool:
    """Kernel applicability (resident or KV-blocked path)."""
    s_pad = -(-s // 128) * 128
    return s_pad * d <= _MAX_BLOCKED_ELEMS


# ----------------------------------------------------------------------
# Kernels
# ----------------------------------------------------------------------
def _scores(q, k, sm_scale):
    """[bq, d] x [s, d] -> scaled fp32 scores [bq, s] (MXU)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return s * sm_scale


def _mask(scores, q0, bq, s_pad, s_real, causal, window=None):
    return jnp.where(_block_mask(bq, s_pad, q0, 0, s_real, causal,
                                 window=window),
                     scores, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                sm_scale, causal, bq, s_pad, s_real, window=None):
    lse_ref = rest[0] if rest else None
    iq = pl.program_id(2)
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = _scores(q, k, sm_scale)
    s = _mask(s, iq * bq, bq, s_pad, s_real, causal, window=window)
    m = jnp.max(s, axis=1, keepdims=True)                      # [bq, 1]
    p = jnp.exp(s - m)                                          # fp32
    l = jnp.sum(p, axis=1, keepdims=True)
    o = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0] = (o / l).astype(o_ref.dtype)
    if lse_ref is not None:
        # [bq, 1] broadcast over a 128-lane minor dim. Mosaic requires the
        # minor block dim to be 128-aligned, so a rank-3 [B,H,S] lse output
        # is not expressible; the upstream library kernel uses this same
        # 128-lane-replicated layout. The 3D residual handed to the remat
        # policy is the lane-0 slice, so only the transient HBM write pays
        # the 128x. Primal-only calls (need_lse=False) skip it entirely.
        lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (s.shape[0], 128))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               sm_scale, causal, bq, s_pad, s_real, window=None):
    iq = pl.program_id(2)
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, :, 0:1]                                 # [bq, 1]
    delta = delta_ref[0, 0, :, 0:1]
    s = _scores(q, k, sm_scale)
    s = _mask(s, iq * bq, bq, s_pad, s_real, causal, window=window)
    p = jnp.exp(s - lse)                                        # [bq, s]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * sm_scale
    dq = jax.lax.dot_general(ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, sm_scale, causal, bk, s_pad, s_real,
                group, window=None):
    ik = pl.program_id(2)
    k = k_ref[0, 0]                                             # [bk, d]
    v = v_ref[0, 0]
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    k0 = ik * bk
    for g in range(group):                                      # static loop
        q = q_ref[0, g]                                         # [s, d]
        do = do_ref[0, g]
        lse = lse_ref[0, g, :, 0:1]                             # [s, 1]
        delta = delta_ref[0, g, :, 0:1]
        s = _scores(q, k, sm_scale)                             # [s, bk]
        rows = lax.broadcasted_iota(jnp.int32, (s_pad, bk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (s_pad, bk), 1) + k0
        valid = (cols < s_real) & (rows < s_real)
        if causal:
            valid &= cols <= rows
        if window is not None:
            valid &= rows - cols < window
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)                                    # [s, bk]
        # pad query rows have lse = 0 from masked fwd rows; kill them
        p = jnp.where(valid, p, 0.0)
        pT = p.astype(do.dtype)
        dv += jax.lax.dot_general(pT, do, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale                        # [s, bk]
        dk += jax.lax.dot_general(ds.astype(q.dtype), q,
                                  (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# ----------------------------------------------------------------------
# KV-blocked kernels (long context): grid (B, H, nq, nk) with nk (or nq
# for dkv) innermost-sequential; online-softmax state in VMEM scratch.
# ----------------------------------------------------------------------
def _block_mask(bq, bk, q0, k0, s_real, causal, with_rows=False,
                window=None):
    rows = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + q0
    cols = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + k0
    valid = cols < s_real
    if with_rows:
        valid &= rows < s_real
    if causal:
        valid &= cols <= rows
    if window is not None:
        # Mistral sliding window: key within the last `window` positions
        valid &= rows - cols < window
    return valid


def _tile_alive(iq, ik, bq, bk, causal, window):
    """Grid-level skip predicate: None when every tile is live (dense
    non-causal, no window); else a traced bool.  A tile is dead when the
    causal triangle or the sliding window excludes every (q, k) pair in
    it — dead tiles cost no FLOPs (on the causal paths their DMA is also
    clamped away by _clamped_kv_index; non-causal windows skip compute
    only)."""
    pred = None
    if causal:
        pred = ik * bk <= iq * bq + bq - 1
    if window is not None:
        wa = iq * bq - ik * bk - bk + 1 < window
        pred = wa if pred is None else jnp.logical_and(pred, wa)
    return pred


def _tile_interior(iq, ik, bq, bk, s_real, causal, window,
                   check_rows=False):
    """Whether a tile needs NO masking at all: every column in-range and
    (under causal/window) every (q, k) pair valid.  The masking chain
    (two iotas + compares + selects) is pure VPU work that at d=64
    rivals the tile's MXU time — interior tiles skip it entirely; only
    diagonal/edge tiles pay (the fwd/dq kernels run one of two bodies
    under complementary ``pl.when`` predicates)."""
    interior = ik * bk + bk <= s_real
    if check_rows:
        interior &= iq * bq + bq <= s_real
    if causal:
        # strictly below the diagonal: max col <= min row
        interior &= ik * bk + bk - 1 <= iq * bq
    if window is not None:
        # max (row - col) inside the window
        interior &= (iq * bq + bq - 1) - ik * bk < window
    return interior


def _fwd_kernel_blocked(q_ref, k_ref, v_ref, o_ref, *rest,
                        sm_scale, causal, bq, bk, s_real, window=None):
    if len(rest) == 4:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref, (m_scr, l_scr, acc_scr) = None, rest
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def compute(masked):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = _scores(q, k, sm_scale)
        if masked:
            valid = _block_mask(bq, bk, iq * bq, ik * bk, s_real, causal,
                                window=window)
            s = jnp.where(valid, s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if masked:
            # fully-masked block rows: m_new stays NEG_INF, so exp(s-m_new)
            # would be exp(0)=1 on the masked entries — kill them explicitly
            p = jnp.where(valid, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    pred = _tile_alive(iq, ik, bq, bk, causal, window)
    interior = _tile_interior(iq, ik, bq, bk, s_real, causal, window)
    live = interior if pred is None else jnp.logical_and(pred, interior)
    pl.when(live)(lambda: compute(False))
    edge = jnp.logical_not(interior) if pred is None \
        else jnp.logical_and(pred, jnp.logical_not(interior))
    pl.when(edge)(lambda: compute(True))

    @pl.when(ik == nk - 1)
    def _():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, 0:1] + jnp.log(safe_l),
                                             lse_ref.shape[2:])


def _dq_kernel_blocked(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, dq_scr, *, sm_scale, causal, bq, bk, s_real,
                       window=None):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def compute(masked):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = _scores(q, k, sm_scale)
        if masked:
            valid = _block_mask(bq, bk, iq * bq, ik * bk, s_real, causal,
                                window=window)
            s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                           (((1,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)

    pred = _tile_alive(iq, ik, bq, bk, causal, window)
    interior = _tile_interior(iq, ik, bq, bk, s_real, causal, window)
    live = interior if pred is None else jnp.logical_and(pred, interior)
    pl.when(live)(lambda: compute(False))
    edge = jnp.logical_not(interior) if pred is None \
        else jnp.logical_and(pred, jnp.logical_not(interior))
    pl.when(edge)(lambda: compute(True))

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel_blocked(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dk_ref, dv_ref, dk_scr, dv_scr, *,
                        sm_scale, causal, bq, bk, s_real, group,
                        window=None):
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def compute(masked):
        k = k_ref[0, 0]                                     # [bk, d]
        v = v_ref[0, 0]
        for g in range(group):                              # static loop
            q = q_ref[0, g]                                 # [bq, d]
            do = do_ref[0, g]
            lse = lse_ref[0, g][:, 0:1]
            delta = delta_ref[0, g][:, 0:1]
            s = _scores(q, k, sm_scale)                     # [bq, bk]
            if masked:
                valid = _block_mask(bq, bk, iq * bq, ik * bk, s_real,
                                    causal, with_rows=True, window=window)
                s = jnp.where(valid, s, NEG_INF)
            p = jnp.exp(s - lse)
            if masked:
                # pad query rows carry garbage lse; kill them with the mask
                p = jnp.where(valid, p, 0.0)
            dv_scr[...] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * sm_scale
            dk_scr[...] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    pred = _tile_alive(iq, ik, bq, bk, causal, window)
    interior = _tile_interior(iq, ik, bq, bk, s_real, causal, window,
                              check_rows=True)
    live = interior if pred is None else jnp.logical_and(pred, interior)
    pl.when(live)(lambda: compute(False))
    edge = jnp.logical_not(interior) if pred is None \
        else jnp.logical_and(pred, jnp.logical_not(interior))
    pl.when(edge)(lambda: compute(True))

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


# ----------------------------------------------------------------------
# Ring-hop carry kernel: one fused flash pass over a visiting K/V block
# with an ONLINE-SOFTMAX CARRY (m, l, acc) threaded in and out, so ring
# attention (sequence/ring.py) runs each ppermute hop as a single kernel
# launch instead of materialized fp32 [S_l, S_l] score blocks.
#
# Positions are decoupled from array indices: the hop's query/key blocks
# live at *global* positions ``off + stride * i`` (contiguous placement:
# stride 1, off = shard * S_l; striped placement: stride sp, off =
# shard index).  The offsets are TRACED scalars (they derive from
# lax.axis_index inside shard_map) and ride in SMEM; strides are static.
# Causally-dead tiles are skipped at the grid level via ``pl.when`` on
# the offset arithmetic — under striped placement every hop is ~half
# dead, which is exactly the ring causal-load-balancing win.
# ----------------------------------------------------------------------
def wire_dequant_rows(payload, scale_col):
    """The flash kernels' wire-dequant epilogue: int8 payload rows ×
    their per-row fp32 scale.  Exactly the arithmetic of
    ``comm/quantized.wire_decode_rows``'s int8 branch (one fp32 multiply
    per element after an int8→fp32 convert), shared here so the Pallas
    and XLA wire codecs can never drift — pinned bitwise by the
    codec-parity test.  ``payload [rows, d]`` int8, ``scale_col
    [rows, 1]`` fp32 → fp32 ``[rows, d]``."""
    return payload.astype(jnp.float32) * scale_col


def _carry_kernel(info_ref, *refs,
                  sm_scale, causal, window, bq, bk, q_stride, k_stride,
                  s_real, quantized=False):
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, mi_ref, li_ref, acci_ref,
         mo_ref, lo_ref, acco_ref, m_scr, l_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, mi_ref, li_ref, acci_ref,
         mo_ref, lo_ref, acco_ref, m_scr, l_scr, acc_scr) = refs
        ks_ref = vs_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)
    q_off = info_ref[0]
    k_off = info_ref[1]

    @pl.when(ik == 0)
    def _():
        m_scr[...] = mi_ref[0, 0]
        l_scr[...] = li_ref[0, 0]
        acc_scr[...] = acci_ref[0, 0]

    # tile liveness/interiority from the hop's global position ranges —
    # the same shared predicates the backward kernels use, so the
    # forward and backward masks cannot drift
    live, interior = _ring_tile_liveness(
        iq, ik, q_off, k_off, bq=bq, bk=bk, q_stride=q_stride,
        k_stride=k_stride, s_real=s_real, causal=causal, window=window)

    def compute(masked):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        if ks_ref is not None:
            # wire-dequant epilogue: the visiting K/V block traveled the
            # ring as int8 payload + per-row fp32 scales; dequantize in
            # VMEM and promote the whole tile to fp32 (the XLA fallback
            # computes from the same decoded fp32 values)
            k = wire_dequant_rows(k, ks_ref[0, 0][:, 0:1])
            v = wire_dequant_rows(v, vs_ref[0, 0][:, 0:1])
            q = q.astype(jnp.float32)
        s = _scores(q, k, sm_scale)
        if masked:
            valid = _ring_tile_mask(
                iq, ik, q_off, k_off, bq=bq, bk=bk, q_stride=q_stride,
                k_stride=k_stride, s_real=s_real, causal=causal,
                window=window)
            s = jnp.where(valid, s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if masked:
            # fully-masked rows keep m_new = NEG_INF; exp(s - m_new) would
            # be 1 on the masked entries — kill them explicitly
            p = jnp.where(valid, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    pl.when(jnp.logical_and(live, interior))(lambda: compute(False))
    pl.when(jnp.logical_and(live, jnp.logical_not(interior)))(
        lambda: compute(True))

    @pl.when(ik == nk - 1)
    def _():
        mo_ref[0, 0] = m_scr[...]
        lo_ref[0, 0] = l_scr[...]
        acco_ref[0, 0] = acc_scr[...]


# q/k block edge for the carry kernel (per-hop S_l blocks). 512 keeps the
# per-program footprint (q + k/v + carry in/out + one [bq, bk] score tile,
# double-buffered) well inside scoped VMEM at d=128; override for sweeps.
_RING_BLK = 512


def ring_carry_pad(s_l: int) -> int:
    """Padded per-shard length the carry kernel runs at: lane-aligned and
    a whole number of `_RING_BLK` blocks once past one block."""
    s_pad = -(-s_l // 128) * 128
    if s_pad > _RING_BLK:
        s_pad = -(-s_pad // _RING_BLK) * _RING_BLK
    return s_pad


def flash_carry_block(q, k, v, m, l, acc, q_off, k_off, *, q_stride=1,
                      k_stride=1, s_real=None, sm_scale=None, causal=True,
                      window=None, k_scale=None, v_scale=None):
    """One ring hop: online-softmax update of ``(m, l, acc)`` against the
    visiting K/V block, fused in a single Pallas pass (no materialized
    score matrix in HBM).

    ``q [B, Hq, S_pad, D]``; ``k/v [B, Hkv, S_pad, D]`` (GQA folded in the
    index map, KV never repeated); ``m/l [B, Hq, S_pad, 128]`` fp32
    lane-replicated running max / normalizer; ``acc [B, Hq, S_pad, D]``
    fp32 running numerator.  ``q_off/k_off``: traced int32 global position
    offsets of the two blocks; ``q_stride/k_stride``: static position
    strides (1 = contiguous shards, sp = striped placement).  S_pad must
    be ``ring_carry_pad(s_real)``.  Returns updated ``(m, l, acc)``.

    ``k_scale/v_scale`` (both or neither): quantized ring wire — ``k/v``
    are then the int8 payloads that traveled the ring and the scales are
    the per-row fp32 block scales, lane-replicated ``[B, Hkv, S_pad,
    128]``; dequant happens in the kernel epilogue
    (:func:`wire_dequant_rows`), so no fp32 K/V copy ever exists in HBM.
    """
    b, hq, s_pad, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    s_real = s_pad if s_real is None else s_real
    sm_scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale
    bq = bk = min(_RING_BLK, s_pad)
    if s_pad % bq:
        raise ValueError(f"S_pad={s_pad} not a multiple of the ring block "
                         f"({bq}); pad with ring_carry_pad")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("flash_carry_block: k_scale and v_scale must be "
                         "passed together")
    info = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    grid = (b, hq, s_pad // bq, s_pad // bk)
    q_spec = pl.BlockSpec((1, 1, bq, d),
                          lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d),
                           lambda ib, ih, iq, ik: (ib, ih // group, ik, 0))
    kv_lane_spec = pl.BlockSpec((1, 1, bk, 128),
                                lambda ib, ih, iq, ik: (ib, ih // group,
                                                        ik, 0))
    lane_spec = pl.BlockSpec((1, 1, bq, 128),
                             lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    scale_args = (k_scale, v_scale) if quantized else ()
    scale_specs = [kv_lane_spec, kv_lane_spec] if quantized else []
    carry0 = 4 + len(scale_args)   # (info, q, k, v, *scales, m, l, acc)
    return pl.pallas_call(
        functools.partial(_carry_kernel, sm_scale=sm_scale, causal=causal,
                          window=window, bq=bq, bk=bk, q_stride=q_stride,
                          k_stride=k_stride, s_real=s_real,
                          quantized=quantized),
        grid=grid,
        interpret=INTERPRET,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            q_spec, kv_spec, kv_spec, *scale_specs,
            lane_spec, lane_spec, q_spec,
        ],
        out_specs=[lane_spec, lane_spec, q_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, s_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, s_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, s_pad, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # m
            pltpu.VMEM((bq, 128), jnp.float32),   # l
            pltpu.VMEM((bq, d), jnp.float32),     # acc
        ],
        # the carry is read once (ik == 0) and rewritten in place — alias
        # it through so the per-hop scan never copies the running state
        input_output_aliases={carry0: 0, carry0 + 1: 1, carry0 + 2: 2},
    )(info, q, k, v, *scale_args, m, l, acc)


# ----------------------------------------------------------------------
# Ring-hop BACKWARD kernels: offset-aware dq / dkv flash passes.
#
# The ring backward (sequence/ring.py _ring_bwd_rule) reuses the saved
# (o, lse) residuals, so each hop only needs p = exp(s - lse) — no
# online-softmax carry.  What it does need, exactly like the forward's
# flash_carry_block, is position-decoupled masking: the hop's q/k blocks
# live at global positions ``off + stride·i`` with TRACED offsets riding
# in SMEM (they derive from lax.axis_index inside shard_map) and static
# strides (1 = contiguous shards, sp = striped placement).
#
# Both kernels ACCUMULATE: the running dq (resp. the traveling dk/dv)
# ride in as fp32 HBM buffers aliased onto the outputs — scratch is
# seeded from the incoming grad at the first sequential step and written
# back at the last, so a hop updates the accumulators in place with no
# copy and no score-shaped transient ever reaching HBM (the whole point:
# the XLA fallback materializes four fp32 [S_l, S_l] blocks per hop).
# Tiles fully excluded by the causal triangle / sliding window skip all
# compute at grid level via ``pl.when`` on the offset arithmetic; unlike
# the local backward, their DMA cannot be clamped away because liveness
# depends on the traced offsets, which BlockSpec index maps never see.
# ----------------------------------------------------------------------
def _ring_tile_liveness(iq, ik, q_off, k_off, *, bq, bk, q_stride,
                        k_stride, s_real, causal, window):
    """(live, interior) predicates of a (iq, ik) tile from the hop's
    global position ranges (strides are positive, so the block corners
    bound every position in the tile)."""
    q_lo = q_off + q_stride * (iq * bq)
    q_hi = q_off + q_stride * (iq * bq + bq - 1)
    k_lo = k_off + k_stride * (ik * bk)
    k_hi = k_off + k_stride * (ik * bk + bk - 1)
    live = jnp.bool_(True)
    interior = (ik * bk + bk <= s_real) & (iq * bq + bq <= s_real)
    if causal:
        live &= k_lo <= q_hi
        interior &= k_hi <= q_lo
    if window is not None:
        live &= q_lo - k_hi < window
        interior &= q_hi - k_lo < window
    return live, interior


def _ring_tile_mask(iq, ik, q_off, k_off, *, bq, bk, q_stride, k_stride,
                    s_real, causal, window):
    """Elementwise validity of an edge tile (offset-aware analogue of
    _block_mask, rows always range-checked — pad query rows carry lse = 0
    garbage and must never contribute)."""
    rows = lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    valid = (iq * bq + rows < s_real) & (ik * bk + cols < s_real)
    rpos = q_off + q_stride * (iq * bq + rows)
    cpos = k_off + k_stride * (ik * bk + cols)
    if causal:
        valid &= cpos <= rpos
    if window is not None:
        valid &= rpos - cpos < window
    return valid


def _ring_dq_kernel(info_ref, *refs, sm_scale,
                    causal, window, bq, bk, q_stride, k_stride, s_real,
                    quantized=False):
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, do_ref, lse_ref,
         delta_ref, dqi_ref, dqo_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dqi_ref, dqo_ref, dq_scr) = refs
        ks_ref = vs_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)
    q_off = info_ref[0]
    k_off = info_ref[1]

    @pl.when(ik == 0)
    def _():
        dq_scr[...] = dqi_ref[0, 0]

    live, interior = _ring_tile_liveness(
        iq, ik, q_off, k_off, bq=bq, bk=bk, q_stride=q_stride,
        k_stride=k_stride, s_real=s_real, causal=causal, window=window)

    def compute(masked):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        if ks_ref is not None:
            # wire-dequant epilogue (see _carry_kernel): int8 payload +
            # per-row scales in, fp32 tiles out
            k = wire_dequant_rows(k, ks_ref[0, 0][:, 0:1])
            v = wire_dequant_rows(v, vs_ref[0, 0][:, 0:1])
            q = q.astype(jnp.float32)
            do = do.astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = _scores(q, k, sm_scale)
        if masked:
            valid = _ring_tile_mask(
                iq, ik, q_off, k_off, bq=bq, bk=bk, q_stride=q_stride,
                k_stride=k_stride, s_real=s_real, causal=causal,
                window=window)
            s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)
        if masked:
            # pad query rows carry lse = 0: exp(s - 0) on a pad row is
            # garbage unless the mask kills it first
            p = jnp.where(valid, p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    pl.when(jnp.logical_and(live, interior))(lambda: compute(False))
    pl.when(jnp.logical_and(live, jnp.logical_not(interior)))(
        lambda: compute(True))

    @pl.when(ik == nk - 1)
    def _():
        dqo_ref[0, 0] = dq_scr[...]


def _ring_dkv_kernel(info_ref, *refs, sm_scale, causal, window, bq, bk,
                     q_stride, k_stride, s_real, group, quantized=False):
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, do_ref, lse_ref,
         delta_ref, dki_ref, dvi_ref, dko_ref, dvo_ref,
         dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dki_ref, dvi_ref, dko_ref, dvo_ref,
         dk_scr, dv_scr) = refs
        ks_ref = vs_ref = None
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    nq = pl.num_programs(3)
    q_off = info_ref[0]
    k_off = info_ref[1]

    @pl.when(iq == 0)
    def _():
        dk_scr[...] = dki_ref[0, 0]
        dv_scr[...] = dvi_ref[0, 0]

    live, interior = _ring_tile_liveness(
        iq, ik, q_off, k_off, bq=bq, bk=bk, q_stride=q_stride,
        k_stride=k_stride, s_real=s_real, causal=causal, window=window)

    def compute(masked):
        k = k_ref[0, 0]                                     # [bk, d]
        v = v_ref[0, 0]
        if ks_ref is not None:
            # wire-dequant epilogue (see _carry_kernel)
            k = wire_dequant_rows(k, ks_ref[0, 0][:, 0:1])
            v = wire_dequant_rows(v, vs_ref[0, 0][:, 0:1])
        if masked:
            valid = _ring_tile_mask(
                iq, ik, q_off, k_off, bq=bq, bk=bk, q_stride=q_stride,
                k_stride=k_stride, s_real=s_real, causal=causal,
                window=window)
        for g in range(group):                              # static loop
            q = q_ref[0, g]                                 # [bq, d]
            do = do_ref[0, g]
            if ks_ref is not None:
                q = q.astype(jnp.float32)
                do = do.astype(jnp.float32)
            lse = lse_ref[0, g][:, 0:1]
            delta = delta_ref[0, g][:, 0:1]
            s = _scores(q, k, sm_scale)                     # [bq, bk]
            if masked:
                s = jnp.where(valid, s, NEG_INF)
            p = jnp.exp(s - lse)
            if masked:
                # pad query rows carry garbage lse; kill them
                p = jnp.where(valid, p, 0.0)
            dv_scr[...] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * sm_scale
            dk_scr[...] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    pl.when(jnp.logical_and(live, interior))(lambda: compute(False))
    pl.when(jnp.logical_and(live, jnp.logical_not(interior)))(
        lambda: compute(True))

    @pl.when(iq == nq - 1)
    def _():
        dko_ref[0, 0] = dk_scr[...]
        dvo_ref[0, 0] = dv_scr[...]


def _ring_bwd_blocks(s_pad: int, group: int):
    """Block edges for the ring backward: the dq kernel tiles at the
    forward carry's `_RING_BLK`; the grouped dkv kernel halves its q-edge
    under GQA (it holds the whole [group, bq] q-side — q/do plus the
    128-lane fp32 lse/delta — per program; same VMEM reasoning as
    _choose_blocks).  Both divide ring_carry_pad(s_l) by construction:
    s_pad ≤ _RING_BLK is returned whole, larger s_pad is a multiple of
    _RING_BLK and the halved edge divides the power-of-two block."""
    bk = min(_RING_BLK, s_pad)
    bq = bk if group == 1 else max(128, bk // 2)
    return bq, bk


def flash_ring_dq_block(q, k, v, do, lse, delta, dq, q_off, k_off, *,
                        q_stride=1, k_stride=1, s_real=None, sm_scale=None,
                        causal=True, window=None, k_scale=None,
                        v_scale=None):
    """One ring backward hop, dq side: accumulate this hop's dq
    contribution against the visiting K/V block into ``dq`` in place.

    ``q/do [B, Hq, S_pad, D]``; ``k/v [B, Hkv, S_pad, D]`` (GQA folded in
    the index map); ``lse/delta [B, Hq, S_pad, 128]`` fp32 lane-replicated
    (see :func:`bwd_lane_residuals`); ``dq [B, Hq, S_pad, D]`` fp32
    running accumulator, aliased through.  ``q_off/k_off`` traced int32
    global position offsets, ``q_stride/k_stride`` static strides — the
    same contract as :func:`flash_carry_block`, including the
    ``k_scale/v_scale`` quantized-wire operands (int8 payload K/V +
    lane-replicated per-row fp32 scales; dequant in the kernel).
    S_pad must be ``ring_carry_pad(s_real)``.  Returns the updated
    ``dq``."""
    b, hq, s_pad, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    s_real = s_pad if s_real is None else s_real
    sm_scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale
    bq = bk = min(_RING_BLK, s_pad)
    if s_pad % bq:
        raise ValueError(f"S_pad={s_pad} not a multiple of the ring block "
                         f"({bq}); pad with ring_carry_pad")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("flash_ring_dq_block: k_scale and v_scale must "
                         "be passed together")
    info = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    grid = (b, hq, s_pad // bq, s_pad // bk)
    q_spec = pl.BlockSpec((1, 1, bq, d),
                          lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d),
                           lambda ib, ih, iq, ik: (ib, ih // group, ik, 0))
    kv_lane_spec = pl.BlockSpec((1, 1, bk, 128),
                                lambda ib, ih, iq, ik: (ib, ih // group,
                                                        ik, 0))
    lane_spec = pl.BlockSpec((1, 1, bq, 128),
                             lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    scale_args = (k_scale, v_scale) if quantized else ()
    scale_specs = [kv_lane_spec, kv_lane_spec] if quantized else []
    dq_idx = 7 + len(scale_args)
    return pl.pallas_call(
        functools.partial(_ring_dq_kernel, sm_scale=sm_scale, causal=causal,
                          window=window, bq=bq, bk=bk, q_stride=q_stride,
                          k_stride=k_stride, s_real=s_real,
                          quantized=quantized),
        grid=grid,
        interpret=INTERPRET,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            q_spec, kv_spec, kv_spec, *scale_specs,
            q_spec, lane_spec, lane_spec, q_spec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, s_pad, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        # dq is read once (ik == 0) and rewritten in place — the per-hop
        # scan never copies the accumulator
        input_output_aliases={dq_idx: 0},
    )(info, q, k, v, *scale_args, do, lse, delta, dq)


def flash_ring_dkv_block(q, k, v, do, lse, delta, dk, dv, q_off, k_off, *,
                         q_stride=1, k_stride=1, s_real=None, sm_scale=None,
                         causal=True, window=None, k_scale=None,
                         v_scale=None):
    """One ring backward hop, dk/dv side: accumulate this hop's grads for
    the VISITING K/V block into the traveling ``dk/dv`` buffers in place
    (they rotate with their block; sequence/ring.py delivers them home).
    Same layout/offset/quantized-wire contract as
    :func:`flash_ring_dq_block`; ``dk/dv [B, Hkv, S_pad, D]`` fp32,
    aliased through.  Returns the updated ``(dk, dv)``."""
    b, hq, s_pad, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    s_real = s_pad if s_real is None else s_real
    sm_scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale
    bq, bk = _ring_bwd_blocks(s_pad, group)
    if s_pad % bq or s_pad % bk:
        raise ValueError(f"S_pad={s_pad} not a multiple of the ring "
                         f"backward blocks ({bq}, {bk}); pad with "
                         "ring_carry_pad")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("flash_ring_dkv_block: k_scale and v_scale must "
                         "be passed together")
    info = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    grid = (b, hkv, s_pad // bk, s_pad // bq)   # iq innermost-sequential
    grp_spec = pl.BlockSpec((1, group, bq, d),
                            lambda ib, ihkv, ik, iq: (ib, ihkv, iq, 0))
    grp_lane_spec = pl.BlockSpec((1, group, bq, 128),
                                 lambda ib, ihkv, ik, iq: (ib, ihkv, iq, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d),
                           lambda ib, ihkv, ik, iq: (ib, ihkv, ik, 0))
    kv_lane_spec = pl.BlockSpec((1, 1, bk, 128),
                                lambda ib, ihkv, ik, iq: (ib, ihkv, ik, 0))
    scale_args = (k_scale, v_scale) if quantized else ()
    scale_specs = [kv_lane_spec, kv_lane_spec] if quantized else []
    dk_idx = 7 + len(scale_args)
    return pl.pallas_call(
        functools.partial(_ring_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, window=window, bq=bq, bk=bk,
                          q_stride=q_stride, k_stride=k_stride,
                          s_real=s_real, group=group, quantized=quantized),
        grid=grid,
        interpret=INTERPRET,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            grp_spec, kv_spec, kv_spec, *scale_specs, grp_spec,
            grp_lane_spec, grp_lane_spec, kv_spec, kv_spec,
        ],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        input_output_aliases={dk_idx: 0, dk_idx + 1: 1},
    )(info, q, k, v, *scale_args, do, lse, delta, dk, dv)


# ----------------------------------------------------------------------
# pallas_call plumbing
# ----------------------------------------------------------------------
def _pad_seq(x, s_pad):
    s = x.shape[2]
    if s == s_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))


def _fwd(q, k, v, causal, sm_scale, need_lse=True, window=None):
    b, hq, s_real, d = q.shape
    if not _supports_resident(s_real, d):
        if not supports(s_real, d):
            raise ValueError(
                f"flash_mha: S={s_real}, D={d} exceeds the KV-blocked "
                f"ceiling (S_pad*D <= {_MAX_BLOCKED_ELEMS}); shard the "
                "sequence (Ulysses/FPDT) before attention")
        return _fwd_blocked(q, k, v, causal, sm_scale, need_lse=need_lse,
                            window=window)
    hkv = k.shape[1]
    group = hq // hkv
    s_pad = -(-s_real // 128) * 128
    bq = _choose_bq(s_pad)
    s_pad = -(-s_real // bq) * bq  # pad to a whole number of q blocks
    qp, kp, vp = _pad_seq(q, s_pad), _pad_seq(k, s_pad), _pad_seq(v, s_pad)
    grid = (b, hq, s_pad // bq)

    kv_spec = pl.BlockSpec((1, 1, s_pad, d),
                           lambda ib, ih, iq: (ib, ih // group, 0, 0))
    q_blk = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0))
    lse_blk = pl.BlockSpec((1, 1, bq, 128), lambda ib, ih, iq: (ib, ih, iq, 0))
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, s_pad=s_pad, s_real=s_real, window=window),
        grid=grid,
        interpret=INTERPRET,
        in_specs=[q_blk, kv_spec, kv_spec],
        out_specs=[q_blk] + ([lse_blk] if need_lse else []),
        out_shape=[jax.ShapeDtypeStruct((b, hq, s_pad, d), q.dtype)]
        + ([jax.ShapeDtypeStruct((b, hq, s_pad, 128), jnp.float32)]
           if need_lse else []),
    )(qp, kp, vp)
    if not need_lse:
        return out[0][:, :, :s_real], None
    o, lse = out
    return o[:, :, :s_real], lse[:, :, :s_real, 0]


def _clamped_kv_index(group, causal, window=None, bq=None, bk=None):
    """K/V block index for grid (ib, ih, iq, ik). Under causal masking,
    blocks with ik > iq are fully dead: clamp their index to the last live
    block so the Pallas pipeline sees an unchanged index and skips the
    DMA — dead blocks cost neither compute (pl.when) nor bandwidth.  A
    sliding window additionally kills leading blocks (keys older than the
    window): clamp those up to the first live one."""
    if causal and window is not None:
        def idx(ib, ih, iq, ik):
            lo = jnp.maximum((iq * bq - (window - 1)) // bk, 0)
            hi = (iq * bq + bq - 1) // bk  # last k block on the diagonal
            return (ib, ih // group, jnp.clip(ik, lo, hi), 0)

        return idx
    if causal:
        return lambda ib, ih, iq, ik: (
            ib, ih // group, jnp.minimum(ik, (iq * bq + bq - 1) // bk), 0)
    return lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)


def _fwd_blocked(q, k, v, causal, sm_scale, need_lse=True, window=None):
    b, hq, s_real, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    bq, bk = _choose_blocks(group)
    step = max(bq, bk)  # powers of two: lcm == max
    s_pad = -(-s_real // step) * step
    qp, kp, vp = _pad_seq(q, s_pad), _pad_seq(k, s_pad), _pad_seq(v, s_pad)
    grid = (b, hq, s_pad // bq, s_pad // bk)

    kv_idx = _clamped_kv_index(group, causal, window=window, bq=bq, bk=bk)
    q_blk = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    lse_blk = pl.BlockSpec((1, 1, bq, 128),
                           lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    out = pl.pallas_call(
        functools.partial(_fwd_kernel_blocked, sm_scale=sm_scale,
                          causal=causal, bq=bq, bk=bk, s_real=s_real,
                          window=window),
        grid=grid,
        interpret=INTERPRET,
        in_specs=[
            q_blk,
            pl.BlockSpec((1, 1, bk, d), kv_idx),
            pl.BlockSpec((1, 1, bk, d), kv_idx),
        ],
        out_specs=[q_blk] + ([lse_blk] if need_lse else []),
        out_shape=[jax.ShapeDtypeStruct((b, hq, s_pad, d), q.dtype)]
        + ([jax.ShapeDtypeStruct((b, hq, s_pad, 128), jnp.float32)]
           if need_lse else []),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # m
            pltpu.VMEM((bq, 128), jnp.float32),   # l
            pltpu.VMEM((bq, d), jnp.float32),     # acc
        ],
    )(qp, kp, vp)
    if not need_lse:
        return out[0][:, :, :s_real], None
    o, lse = out
    return o[:, :, :s_real], lse[:, :, :s_real, 0]


def _lanes(x, s_pad):  # [B, H, S] -> [B, H, s_pad, 128] lane-broadcast
    if x.shape[2] != s_pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - x.shape[2])))
    return jnp.broadcast_to(x[..., None], x.shape + (128,))


def attn_delta(o, do):
    """``delta = sum(do·o)`` per query row in fp32 — the shared softmax-
    backward correction term of EVERY flash backward (local resident,
    local KV-blocked, and the ring's fused and XLA paths), computed once
    per shard from the saved output."""
    return jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)


def bwd_lane_residuals(o, do, lse, s_pad):
    """Shared backward-residual prep for the flash dq/dkv kernels:
    ``o/do [B, H, S, D]``, ``lse [B, H, S]`` fp32 → lane-replicated,
    tail-padded ``(lse, delta) [B, H, s_pad, 128]`` fp32.  One helper so
    the local backward and the ring backward (sequence/ring.py) cannot
    drift in how they reshape the saved residuals."""
    return _lanes(lse, s_pad), _lanes(attn_delta(o, do), s_pad)


def _bwd_blocked(q, k, v, o, lse, g, causal, sm_scale, window=None):
    b, hq, s_real, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    bq, bk = _choose_blocks(group)
    step = max(bq, bk)
    s_pad = -(-s_real // step) * step

    qp, kp, vp = _pad_seq(q, s_pad), _pad_seq(k, s_pad), _pad_seq(v, s_pad)
    gp = _pad_seq(g, s_pad)
    lsep, deltap = bwd_lane_residuals(o, g, lse, s_pad)

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d),
                           _clamped_kv_index(group, causal, window=window,
                                             bq=bq, bk=bk))
    lane_spec = pl.BlockSpec((1, 1, bq, 128),
                             lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_blocked, sm_scale=sm_scale,
                          causal=causal, bq=bq, bk=bk, s_real=s_real,
                          window=window),
        grid=(b, hq, s_pad // bq, s_pad // bk),
        interpret=INTERPRET,
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, lane_spec, lane_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, s_pad, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
    )(qp, kp, vp, gp, lsep, deltap)

    # dead (iq < ik) steps clamp the q-side index to the diagonal so their
    # DMA is the first live step's prefetch rather than a wasted fetch; a
    # sliding window also kills trailing q blocks (queries past the
    # window) — clamp those down to the last live one
    if causal and window is not None:
        def q_idx(ib, ihkv, ik, iq):
            lo = (ik * bk) // bq  # first q block the diagonal touches
            hi = (ik * bk + bk - 1 + window - 1) // bq
            return (ib, ihkv, jnp.clip(iq, lo, hi), 0)
    elif causal:
        def q_idx(ib, ihkv, ik, iq):
            return (ib, ihkv, jnp.maximum(iq, (ik * bk) // bq), 0)
    else:
        def q_idx(ib, ihkv, ik, iq):
            return (ib, ihkv, iq, 0)
    grp_spec = pl.BlockSpec((1, group, bq, d), q_idx)
    grp_lane_spec = pl.BlockSpec((1, group, bq, 128), q_idx)
    kv_own_spec = pl.BlockSpec((1, 1, bk, d),
                               lambda ib, ihkv, ik, iq: (ib, ihkv, ik, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_blocked, sm_scale=sm_scale,
                          causal=causal, bq=bq, bk=bk, s_real=s_real,
                          group=group, window=window),
        grid=(b, hkv, s_pad // bk, s_pad // bq),
        interpret=INTERPRET,
        in_specs=[grp_spec, kv_own_spec, kv_own_spec, grp_spec,
                  grp_lane_spec, grp_lane_spec],
        out_specs=[kv_own_spec, kv_own_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
    )(qp, kp, vp, gp, lsep, deltap)
    return dq[:, :, :s_real], dk[:, :, :s_real], dv[:, :, :s_real]


def _resident_bwd_fits(s_pad: int, d: int, group: int, bq: int) -> bool:
    """Whether the grouped resident dkv kernel fits scoped VMEM (16 MB).

    It holds the whole [group, s_pad] q-side per program — q and do in
    bf16 plus the 128-lane-replicated fp32 lse/delta — double-buffered by
    the Pallas pipeline, with ~3 live [s_pad, bq] fp32 score
    intermediates.  GQA multiplies the q-side by `group`, so e.g.
    group=4, S=1024, D=128 (Llama-3 geometry) overruns the limit even
    though S·D is within the resident budget; fall back to the
    KV-blocked backward there."""
    blocks = group * s_pad * (2 * d * 2 + 2 * 128 * 4)  # q+do, lse+delta
    interm = 3 * s_pad * bq * 4
    return 2 * blocks + interm <= 12 * (1 << 20)


def _bwd_impl(q, k, v, o, lse, g, causal, sm_scale, window=None):
    b, hq, s_real, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    s_pad128 = -(-s_real // 128) * 128
    if not _supports_resident(s_real, d) or not _resident_bwd_fits(
            s_pad128, d, group, _choose_bq(s_pad128)):
        return _bwd_blocked(q, k, v, o, lse, g, causal, sm_scale,
                            window=window)
    s_pad = s_pad128
    bq = _choose_bq(s_pad)
    s_pad = -(-s_real // bq) * bq

    qp, kp, vp = _pad_seq(q, s_pad), _pad_seq(k, s_pad), _pad_seq(v, s_pad)
    gp = _pad_seq(g, s_pad)
    lsep, deltap = bwd_lane_residuals(o, g, lse, s_pad)

    kv_spec = pl.BlockSpec((1, 1, s_pad, d),
                           lambda ib, ih, iq: (ib, ih // group, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, s_pad=s_pad, s_real=s_real, window=window),
        grid=(b, hq, s_pad // bq),
        interpret=INTERPRET,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            kv_spec,
            kv_spec,
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda ib, ih, iq: (ib, ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, s_pad, d), q.dtype),
    )(qp, kp, vp, gp, lsep, deltap)

    bk = bq
    grp_spec = pl.BlockSpec((1, group, s_pad, d),
                            lambda ib, ihkv, ik: (ib, ihkv, 0, 0))
    grp_lane_spec = pl.BlockSpec((1, group, s_pad, 128),
                                 lambda ib, ihkv, ik: (ib, ihkv, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          bk=bk, s_pad=s_pad, s_real=s_real, group=group,
                          window=window),
        grid=(b, hkv, s_pad // bk),
        interpret=INTERPRET,
        in_specs=[
            grp_spec,
            pl.BlockSpec((1, 1, bk, d), lambda ib, ihkv, ik: (ib, ihkv, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ihkv, ik: (ib, ihkv, ik, 0)),
            grp_spec,
            grp_lane_spec,
            grp_lane_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda ib, ihkv, ik: (ib, ihkv, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ihkv, ik: (ib, ihkv, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), v.dtype),
        ],
    )(qp, kp, vp, gp, lsep, deltap)
    return dq[:, :, :s_real], dk[:, :, :s_real], dv[:, :, :s_real]


# ----------------------------------------------------------------------
# custom_vjp wrapper
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_mha(q, k, v, causal: bool = True, sm_scale: float | None = None,
              window: int | None = None):
    """Flash attention over ``q [B, Hq, S, D]``, ``k/v [B, Hkv, S, D]``
    (Hq a multiple of Hkv — GQA handled in the kernel's index maps).
    ``window``: Mistral sliding-window width (key visible iff
    ``qpos - kpos < window``, on top of causal); tiles fully outside the
    window are skipped at the grid level.  Returns ``o [B, Hq, S, D]``."""
    o, _ = _fwd(q, k, v, causal, _resolve_scale(sm_scale, q),
                need_lse=False, window=window)
    return o


def _resolve_scale(sm_scale, q):
    return 1.0 / math.sqrt(q.shape[-1]) if sm_scale is None else sm_scale


def _flash_fwd_rule(q, k, v, causal, sm_scale, window):
    scale = _resolve_scale(sm_scale, q)
    o, lse = _fwd(q, k, v, causal, scale, window=window)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, sm_scale, window, res, g):
    q, k, v, o, lse = res
    scale = _resolve_scale(sm_scale, q)
    dq, dk, dv = _bwd_impl(q, k, v, o, lse, g, causal, scale,
                           window=window)
    return dq, dk, dv


flash_mha.defvjp(_flash_fwd_rule, _flash_bwd_rule)
