"""Repo-owned Pallas flash attention for TPU training.

TPU replacement for the reference's fused attention CUDA kernels
(``csrc/transformer/inference/csrc/softmax.cu``,
``deepspeed/ops/transformer`` FlashAttention paths) — written from scratch
for the TPU memory hierarchy rather than ported:

* **Full KV resident in VMEM** per (batch, kv-head) program. At training
  sequence lengths (S·D ≤ ~512K elements, e.g. 8K × 64) K and V fit on-chip,
  so each q-block does a single-shot softmax over one [bq, S] score matrix —
  two big MXU matmuls — instead of the chunked online-softmax loop a GPU
  kernel needs. Beyond the VMEM budget the caller falls back to XLA.
* **GQA-native**: the kernel grid runs over query heads and the K/V
  BlockSpec index map folds ``h → h // group`` — KV is never repeated in
  HBM (the reference repeats KV to full MHA; VERDICT round-1 flagged the
  8× KV-bandwidth waste for Llama-3-70B-class models).
* **Any length**: the wrapper pads S up to a lane-aligned block multiple.
  Tail-padding is masked in-kernel (pad keys never attended, pad query rows
  sliced off), so there is no silent O(S²) XLA fallback for S % 128 != 0.
* **Saved-residual backward**: a custom VJP saves (q, k, v, o, lse) and the
  outputs are tagged with ``checkpoint_name`` ("flash_out"/"flash_lse"), so
  the engine's remat policy can keep them and the backward never re-runs the
  forward kernel (the upstream library kernel always recomputes under
  remat).

Layout contract: q is ``[B, Hq, S, D]``, k/v are ``[B, Hkv, S, D]``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl

NEG_INF = -1e30
# K + V resident per program: S * D * 2 bytes * 2 tensors ≤ ~4 MB
_MAX_KV_ELEMS = 1 << 20  # S * D

# Set True (tests/conftest or CI) to run the kernels through the Pallas
# interpreter so numerics are checkable on the CPU mesh.
INTERPRET = False


def _choose_bq(s_pad: int, scores_budget: int = 1 << 20) -> int:
    """Largest q-block in {512, 384, 256, 128} dividing s_pad with a
    [bq, s_pad] fp32 score matrix within budget (≤ 4 MB)."""
    for bq in (512, 384, 256, 128):
        if s_pad % bq == 0 and bq * s_pad <= scores_budget:
            return bq
    return 128


def supports(s: int, d: int) -> bool:
    """Whether the kernel's VMEM-resident strategy applies: K+V resident
    within budget AND a q-block exists whose score matrix fits (so
    _choose_bq's fallback can never exceed the documented bound)."""
    s_pad = -(-s // 128) * 128
    return s_pad * d <= _MAX_KV_ELEMS and 128 * s_pad <= (1 << 20)


# ----------------------------------------------------------------------
# Kernels
# ----------------------------------------------------------------------
def _scores(q, k, sm_scale):
    """[bq, d] x [s, d] -> scaled fp32 scores [bq, s] (MXU)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return s * sm_scale


def _mask(scores, q0, bq, s_pad, s_real, causal):
    rows = lax.broadcasted_iota(jnp.int32, (bq, s_pad), 0) + q0
    cols = lax.broadcasted_iota(jnp.int32, (bq, s_pad), 1)
    valid = cols < s_real
    if causal:
        valid &= cols <= rows
    return jnp.where(valid, scores, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale, causal, bq, s_pad, s_real):
    iq = pl.program_id(2)
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = _scores(q, k, sm_scale)
    s = _mask(s, iq * bq, bq, s_pad, s_real, causal)
    m = jnp.max(s, axis=1, keepdims=True)                      # [bq, 1]
    p = jnp.exp(s - m)                                          # fp32
    l = jnp.sum(p, axis=1, keepdims=True)
    o = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0] = (o / l).astype(o_ref.dtype)
    # [bq, 1] broadcast over a 128-lane minor dim. Mosaic requires the
    # minor block dim to be 128-aligned, so a rank-3 [B,H,S] lse output is
    # not expressible; the upstream library kernel uses this same
    # 128-lane-replicated layout. The 3D residual handed to the remat
    # policy is the lane-0 slice, so only the transient HBM write pays
    # the 128x.
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (s.shape[0], 128))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               sm_scale, causal, bq, s_pad, s_real):
    iq = pl.program_id(2)
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, :, 0:1]                                 # [bq, 1]
    delta = delta_ref[0, 0, :, 0:1]
    s = _scores(q, k, sm_scale)
    s = _mask(s, iq * bq, bq, s_pad, s_real, causal)
    p = jnp.exp(s - lse)                                        # [bq, s]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * sm_scale
    dq = jax.lax.dot_general(ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, sm_scale, causal, bk, s_pad, s_real, group):
    ik = pl.program_id(2)
    k = k_ref[0, 0]                                             # [bk, d]
    v = v_ref[0, 0]
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    k0 = ik * bk
    for g in range(group):                                      # static loop
        q = q_ref[0, g]                                         # [s, d]
        do = do_ref[0, g]
        lse = lse_ref[0, g, :, 0:1]                             # [s, 1]
        delta = delta_ref[0, g, :, 0:1]
        s = _scores(q, k, sm_scale)                             # [s, bk]
        rows = lax.broadcasted_iota(jnp.int32, (s_pad, bk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (s_pad, bk), 1) + k0
        valid = (cols < s_real) & (rows < s_real)
        if causal:
            valid &= cols <= rows
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)                                    # [s, bk]
        # pad query rows have lse = 0 from masked fwd rows; kill them
        p = jnp.where(valid, p, 0.0)
        pT = p.astype(do.dtype)
        dv += jax.lax.dot_general(pT, do, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale                        # [s, bk]
        dk += jax.lax.dot_general(ds.astype(q.dtype), q,
                                  (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# ----------------------------------------------------------------------
# pallas_call plumbing
# ----------------------------------------------------------------------
def _pad_seq(x, s_pad):
    s = x.shape[2]
    if s == s_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))


def _fwd(q, k, v, causal, sm_scale):
    b, hq, s_real, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    s_pad = -(-s_real // 128) * 128
    bq = _choose_bq(s_pad)
    s_pad = -(-s_real // bq) * bq  # pad to a whole number of q blocks
    qp, kp, vp = _pad_seq(q, s_pad), _pad_seq(k, s_pad), _pad_seq(v, s_pad)
    grid = (b, hq, s_pad // bq)

    kv_spec = pl.BlockSpec((1, 1, s_pad, d),
                           lambda ib, ih, iq: (ib, ih // group, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, s_pad=s_pad, s_real=s_real),
        grid=grid,
        interpret=INTERPRET,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, s_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, s_pad, 128), jnp.float32),
        ],
    )(qp, kp, vp)
    return o[:, :, :s_real], lse[:, :, :s_real, 0]


def _bwd_impl(q, k, v, o, lse, g, causal, sm_scale):
    b, hq, s_real, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    s_pad = -(-s_real // 128) * 128
    bq = _choose_bq(s_pad)
    s_pad = -(-s_real // bq) * bq
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    def lanes(x):  # [B, H, S] -> [B, H, s_pad, 128] lane-broadcast
        if x.shape[2] != s_pad:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - x.shape[2])))
        return jnp.broadcast_to(x[..., None], x.shape + (128,))

    qp, kp, vp = _pad_seq(q, s_pad), _pad_seq(k, s_pad), _pad_seq(v, s_pad)
    gp = _pad_seq(g, s_pad)
    lsep, deltap = lanes(lse), lanes(delta)

    kv_spec = pl.BlockSpec((1, 1, s_pad, d),
                           lambda ib, ih, iq: (ib, ih // group, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, s_pad=s_pad, s_real=s_real),
        grid=(b, hq, s_pad // bq),
        interpret=INTERPRET,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            kv_spec,
            kv_spec,
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda ib, ih, iq: (ib, ih, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda ib, ih, iq: (ib, ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, s_pad, d), q.dtype),
    )(qp, kp, vp, gp, lsep, deltap)

    bk = bq
    grp_spec = pl.BlockSpec((1, group, s_pad, d),
                            lambda ib, ihkv, ik: (ib, ihkv, 0, 0))
    grp_lane_spec = pl.BlockSpec((1, group, s_pad, 128),
                                 lambda ib, ihkv, ik: (ib, ihkv, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          bk=bk, s_pad=s_pad, s_real=s_real, group=group),
        grid=(b, hkv, s_pad // bk),
        interpret=INTERPRET,
        in_specs=[
            grp_spec,
            pl.BlockSpec((1, 1, bk, d), lambda ib, ihkv, ik: (ib, ihkv, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ihkv, ik: (ib, ihkv, ik, 0)),
            grp_spec,
            grp_lane_spec,
            grp_lane_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda ib, ihkv, ik: (ib, ihkv, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ihkv, ik: (ib, ihkv, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, s_pad, d), v.dtype),
        ],
    )(qp, kp, vp, gp, lsep, deltap)
    return dq[:, :, :s_real], dk[:, :, :s_real], dv[:, :, :s_real]


# ----------------------------------------------------------------------
# custom_vjp wrapper
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_mha(q, k, v, causal: bool = True, sm_scale: float | None = None):
    """Flash attention over ``q [B, Hq, S, D]``, ``k/v [B, Hkv, S, D]``
    (Hq a multiple of Hkv — GQA handled in the kernel's index maps).
    Returns ``o [B, Hq, S, D]``."""
    o, _ = _fwd(q, k, v, causal, _resolve_scale(sm_scale, q))
    return o


def _resolve_scale(sm_scale, q):
    return 1.0 / math.sqrt(q.shape[-1]) if sm_scale is None else sm_scale


def _flash_fwd_rule(q, k, v, causal, sm_scale):
    scale = _resolve_scale(sm_scale, q)
    o, lse = _fwd(q, k, v, causal, scale)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, sm_scale, res, g):
    q, k, v, o, lse = res
    scale = _resolve_scale(sm_scale, q)
    dq, dk, dv = _bwd_impl(q, k, v, o, lse, g, causal, scale)
    return dq, dk, dv


flash_mha.defvjp(_flash_fwd_rule, _flash_bwd_rule)
