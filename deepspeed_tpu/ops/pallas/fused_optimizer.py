"""Fused multi-tensor optimizer steps as Pallas TPU kernels.

Device-kernel analog of the reference's fused optimizers
(``csrc/adam/multi_tensor_adam.cu``, ``csrc/lamb/fused_lamb_cuda_kernel.cu``,
``csrc/lion`` — SURVEY §2.4 [NATIVE]).  On CUDA the multi-tensor apply
exists to amortise kernel-launch overhead across hundreds of small
tensors; XLA has no launch-per-op cost and already fuses the optax
elementwise chain into one HBM pass per tensor, so the win to chase here
is different: these kernels *pin* the one-pass guarantee (4 reads p/g/m/v,
3 writes p/m/v — the bandwidth floor) independent of XLA's fusion
heuristics.  Measured r04 (v5e, 328M fp32 params, in-jit scan via
tools/bench_kernels.py): 16.5 ms/step at 556 GB/s effective vs the optax
chain's 17.0 ms at 541 GB/s — a hair past XLA, both near the HBM bound.
The optax path stays the default because GSPMD partitions it under
sharded meshes (a pallas_call does not partition).

Numerics are bit-identical to the optax chain used by
``runtime/optimizers.build_optimizer`` (scale_by_adam → add_decayed_weights
→ -lr scaling; scale_by_lion likewise), so the two paths are
interchangeable mid-training.

Sharding: a pallas_call does not partition under GSPMD, so the fused path
serves unsharded/replicated leaves (single-chip, or ZeRO-0 meshes); the
engine's sharded updates keep the optax chain, which GSPMD partitions
perfectly.  Callers route per-leaf via :func:`supports`.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INTERPRET = False

_LANES = 128


def supports(shape: Tuple[int, ...]) -> bool:
    """A leaf is servable when its LAST dim is a whole number of 128-lane
    vectors (the kernels collapse leading dims — a free view — and tile
    the natural [M, N]; flattening into [size/128, 128] instead would
    force a retiling copy per tensor that costs more than the fused step
    saves — measured r04: 234 vs 502 GB/s)."""
    if not shape:
        return False
    n = 1
    for d in shape:
        n *= d
    return shape[-1] % _LANES == 0 and n >= 8 * _LANES


def _view_rows(x: jnp.ndarray) -> jnp.ndarray:
    return x.reshape(-1, x.shape[-1])


def _block_shape(m: int, n: int) -> Tuple[int, int]:
    """Tile edges bounded so the AdamW kernel's 7 fp32 operand blocks,
    double-buffered, stay within scoped VMEM: area ≤ 128·1024 elements
    → 7 · 0.5 MB · 2 = 7 MB (the 256·1024 version measured 16.79 MB
    against the 16 MB limit on v5e)."""
    bn = n
    for cand in (1024, 512, 256, 128):
        if n % cand == 0:
            bn = cand
            break
    bm = max(8, (128 * 1024) // bn)
    while bm > m and bm > 8:
        bm //= 2
    return bm, bn


def _adamw_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref,
                  po_ref, mo_ref, vo_ref, *,
                  b1: float, b2: float, eps: float, wd: float):
    lr = sc_ref[0]
    bc1 = sc_ref[1]   # 1 - b1**t
    bc2 = sc_ref[2]   # 1 - b2**t
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if wd:
        u = u + wd * p
    po_ref[...] = (p - lr * u).astype(po_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


def fused_adamw_leaf(p: jnp.ndarray, g: jnp.ndarray, m: jnp.ndarray,
                     v: jnp.ndarray, lr, count,
                     b1: float = 0.9, b2: float = 0.999,
                     eps: float = 1e-8, wd: float = 0.01):
    """One AdamW step for one tensor: returns ``(p', m', v')``.

    ``lr``/``count`` may be traced scalars (count is the optax step
    counter BEFORE increment, i.e. this step uses ``t = count + 1``).
    """
    t = (count + 1).astype(jnp.float32) if hasattr(count, "astype") \
        else float(count + 1)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        1.0 - jnp.asarray(b1, jnp.float32) ** t,
        1.0 - jnp.asarray(b2, jnp.float32) ** t,
    ])
    p2, g2 = _view_rows(p), _view_rows(g)
    m2, v2 = _view_rows(m), _view_rows(v)
    rows, n = p2.shape
    bm, bn = _block_shape(rows, n)
    grid = (pl.cdiv(rows, bm), n // bn)
    tile = pl.BlockSpec((bm, bn), lambda i, j: (i, j))
    po, mo, vo = pl.pallas_call(
        functools.partial(_adamw_kernel, b1=float(b1), b2=float(b2),
                          eps=float(eps), wd=float(wd)),
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  tile, tile, tile, tile],
        out_specs=[tile, tile, tile],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p.dtype),
                   jax.ShapeDtypeStruct(m2.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v2.shape, jnp.float32)],
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=INTERPRET,
    )(scalars, p2, g2, m2, v2)
    return po.reshape(p.shape), mo.reshape(m.shape), vo.reshape(v.shape)


def _lion_kernel(sc_ref, p_ref, g_ref, m_ref, po_ref, mo_ref, *,
                 b1: float, b2: float, wd: float):
    lr = sc_ref[0]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...]
    u = jnp.sign(b1 * m + (1.0 - b1) * g)
    if wd:
        u = u + wd * p
    po_ref[...] = (p - lr * u).astype(po_ref.dtype)
    mo_ref[...] = b2 * m + (1.0 - b2) * g


def fused_lion_leaf(p: jnp.ndarray, g: jnp.ndarray, m: jnp.ndarray, lr,
                    b1: float = 0.9, b2: float = 0.99, wd: float = 0.0):
    """One Lion step for one tensor: returns ``(p', m')``."""
    scalars = jnp.asarray(lr, jnp.float32).reshape(1)
    p2, g2, m2 = _view_rows(p), _view_rows(g), _view_rows(m)
    rows, n = p2.shape
    bm, bn = _block_shape(rows, n)
    grid = (pl.cdiv(rows, bm), n // bn)
    tile = pl.BlockSpec((bm, bn), lambda i, j: (i, j))
    po, mo = pl.pallas_call(
        functools.partial(_lion_kernel, b1=float(b1), b2=float(b2),
                          wd=float(wd)),
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), tile, tile, tile],
        out_specs=[tile, tile],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p.dtype),
                   jax.ShapeDtypeStruct(m2.shape, jnp.float32)],
        input_output_aliases={1: 0, 3: 1},
        interpret=INTERPRET,
    )(scalars, p2, g2, m2)
    return po.reshape(p.shape), mo.reshape(m.shape)
