"""Fused ZeRO-3 gather-matmul: the matmul whose epilogue region issues
the NEXT matmul's parameter all-gather.

The T3 move (arXiv:2401.16677) applied to the stage-3 forward: instead
of leaving the per-use parameter all-gathers to GSPMD's scheduling
(which the ``step_schedule.zero3_prefetch`` arm can only *hoist* by
widening the layer-scan unroll window), the layer MLP runs inside an
explicit ``shard_map`` over the ZeRO axes where

* every weight shard is gathered by an EXPLICIT ``lax.all_gather``
  issued at the top of the fused region — the SECOND matmul's gather
  (and the swiglu gate branch's) is emitted before the first matmul
  runs, so it is dataflow-independent of that matmul and the
  latency-hiding scheduler overlaps transfer with MXU work; and
* the matmuls themselves run as ONE blocked Pallas kernel each
  (``matmul_block``) on TPU — a single opaque custom call the compiler
  cannot split or re-order around the in-flight gather, which pins the
  overlap window the fusion creates (off-TPU the same contraction runs
  as a jnp dot behind the same gate, so CPU parity tests cover the
  wiring).

Composition: ``step_schedule.gather_prefetch_depth`` still unrolls the
layer scan, so consecutive unrolled layer bodies expose *their* fused
regions' gathers to each other — layer i+1's gather issues under layer
i's matmuls.  The overlap scheduler's decision table picks this fused
arm vs the scheduled (unroll-only) arm from the same probe evidence
(``fused_gather_matmul`` decision, docs/AUTOTUNING.md).

The engine enables the path only after verifying the MLP weights carry
the expected fsdp sharding pattern (wi/wg sharded on the embed dim 0,
wo on the embed dim 1) — see ``runtime/engine.py``; anything else
warn-falls back to GSPMD scheduling.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.parallel.topology import BATCH_AXES
from deepspeed_tpu.utils.jax_compat import get_abstract_mesh, shard_map

# Set True (tests) to run the matmul kernel through the Pallas
# interpreter so the fused path is checkable on the CPU mesh.
INTERPRET = False

# Block edges: [bm, bk] x [bk, bn] fp32 accumulation in VMEM scratch.
# 256³ keeps the per-program footprint (two input tiles + fp32 acc,
# double-buffered) well inside scoped VMEM for bf16/f32 operands.
_BLK_M = 256
_BLK_N = 256
_BLK_K = 256


def _kernel_enabled() -> bool:
    """Run the Pallas matmul: on TPU, or under the interpreter flag (CPU
    parity tests) — the same gate shape as sequence/ring.py's."""
    if INTERPRET:
        return True
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover - no backend at trace time
        return False


def _matmul_kernel(x_ref, w_ref, o_ref, acc_scr):
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    acc_scr[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _():
        o_ref[...] = acc_scr[...].astype(o_ref.dtype)


def _pad_to(x, m, axis):
    s = x.shape[axis]
    if s % m == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, m - s % m)
    return jnp.pad(x, pad)


@jax.custom_vjp
def pallas_matmul(x, w):
    """Blocked Pallas matmul ``[M, K] @ [K, N] -> [M, N]`` (fp32 VMEM
    accumulation, zero-padding to block multiples, result in ``x``'s
    dtype).  Falls back to ``jnp.dot`` when the kernel gate is off.
    Differentiable: the hand-written VJP runs the transposed
    contractions through the same kernel (``pallas_call`` has no AD
    rule of its own)."""
    return _matmul_impl(x, w)


def _mm_fwd(x, w):
    return _matmul_impl(x, w), (x, w)


def _mm_bwd(res, g):
    x, w = res
    dx = _matmul_impl(g, w.T)            # [M, N] @ [N, K]
    dw = _matmul_impl(x.T, g)            # [K, M] @ [M, N]
    return dx.astype(x.dtype), dw.astype(w.dtype)


def _matmul_impl(x, w):
    if not _kernel_enabled():
        return jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
    m, k = x.shape
    n = w.shape[1]
    bm = min(_BLK_M, -(-m // 8) * 8)
    bn = min(_BLK_N, -(-n // 128) * 128)
    bk = min(_BLK_K, -(-k // 128) * 128)
    xp = _pad_to(_pad_to(x, bm, 0), bk, 1)
    wp = _pad_to(_pad_to(w, bk, 0), bn, 1)
    mp, kp = xp.shape
    np_ = wp.shape[1]
    out = pl.pallas_call(
        _matmul_kernel,
        grid=(mp // bm, np_ // bn, kp // bk),
        interpret=INTERPRET,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda im, jn, ik: (im, ik)),
            pl.BlockSpec((bk, bn), lambda im, jn, ik: (ik, jn)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda im, jn, ik: (im, jn)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
    )(xp, wp)
    return out[:m, :n]


pallas_matmul.defvjp(_mm_fwd, _mm_bwd)


def gather_matmul(x, w_shard, axes, shard_dim, *, prefetch=()):
    """One fused gather-matmul INSIDE a manual (shard_map) region:
    all-gather ``w_shard`` over ``axes`` (tiled on ``shard_dim``), run
    the Pallas matmul against the gathered weight, and ALSO issue the
    all-gathers for every ``(shard, dim)`` in ``prefetch`` FIRST — those
    are the following matmuls' parameters, emitted in this matmul's
    epilogue region so their transfer overlaps this matmul's compute.

    Returns ``(y, gathered_prefetch_tuple)``."""
    nexts = tuple(lax.all_gather(s, axes, axis=d, tiled=True)
                  for s, d in prefetch)
    w = lax.all_gather(w_shard, axes, axis=shard_dim, tiled=True)
    lead = x.shape[:-1]
    y = pallas_matmul(x.reshape(-1, x.shape[-1]), w)
    return y.reshape(lead + (w.shape[1],)), nexts


def fused_gather_mlp(x, p, cfg):
    """The transformer MLP on the fused gather-matmul path
    (``step_schedule.fused_gather_matmul``; called from
    models/transformer.py ``_mlp_block`` when the engine enabled the
    flag).  ``x [B, S, H]`` batch-sharded, ``p`` the layer's mlp params
    with wi/wg sharded on dim 0 and wo on dim 1 over
    ``cfg.fused_gather_axes``.  Biases (when present) stay outside the
    manual region — they are small and GSPMD's implicit gather of them
    is already declared intent."""
    axes = tuple(cfg.fused_gather_axes)
    ctx = get_abstract_mesh()
    if ctx.empty:  # pragma: no cover - engine always jits under the mesh
        from deepspeed_tpu.parallel.topology import get_topology

        mesh = get_topology().mesh
    else:
        mesh = ctx
    swiglu = cfg.activation == "swiglu"
    P = jax.sharding.PartitionSpec
    dt = x.dtype
    ax = axes if len(axes) > 1 else axes[0]
    bi = p.get("bi")
    has_bi = bi is not None and not swiglu

    def local(x_l, wi_l, wo_l, wg_l, bi_l):
        # the SECOND matmul's gather (and the gate branch's, and the
        # tiny pre-activation bias') issues before the first matmul runs
        # — dataflow-independent, so the scheduler overlaps the
        # transfers with the MXU work below
        pre = ((wo_l, 1), (wg_l, 0), (bi_l, 0))
        h, (wo_full, wg_full, bi_full) = gather_matmul(
            x_l, wi_l, axes, 0, prefetch=pre)
        if has_bi:
            h = h + bi_full
        if swiglu:
            lead = x_l.shape[:-1]
            gate = pallas_matmul(x_l.reshape(-1, x_l.shape[-1]), wg_full)
            h = jax.nn.silu(gate.reshape(lead + (wg_full.shape[1],))) * h
        else:
            h = jax.nn.relu(h) if cfg.activation == "relu" \
                else jax.nn.gelu(h, approximate=cfg.activation != "gelu_exact")
        y = pallas_matmul(h.reshape(-1, h.shape[-1]), wo_full)
        return y.reshape(x_l.shape[:-1] + (wo_full.shape[1],))

    xspec = P(BATCH_AXES, None, None)
    wi_spec = P(ax, None)
    wo_spec = P(None, ax)
    wg = p.get("wg") if swiglu else None
    if wg is None:
        # keep the shard_map arity fixed: zero-size dummies ride the
        # unused slots (never touched in the body)
        wg = jnp.zeros((p["wi"].shape[0], 0), dt)
    bi_in = bi if has_bi else jnp.zeros((0,), dt)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(xspec, wi_spec, wo_spec, wi_spec, P(ax)),
                   out_specs=xspec,
                   axis_names={*BATCH_AXES, *axes}, check_vma=False)
    return fn(x, p["wi"].astype(dt), p["wo"].astype(dt), wg.astype(dt),
              bi_in.astype(dt))
