"""Block-sparse flash attention with true block skipping (Pallas, TPU).

TPU-native analog of the reference's Triton block-sparse kernels
(``deepspeed/ops/sparse_attention/matmul.py`` SDD/DSD + ``softmax.py`` +
``sparse_self_attention.py``): a DeepSpeed ``SparsityConfig`` block layout
``[H, nb, nb]`` gates, per kernel tile,

* the MXU compute — ``pl.when`` on the tile's layout slab, so dead tiles
  cost no FLOPs (generalising flash_mha's causal skip to arbitrary
  layouts), and
* the K/V DMAs — the host-side liveness table clamps the k-block index of
  dead tiles to the most recent live one, and the Pallas pipeline does not
  re-fetch an unchanged index (the same trick ``_clamped_kv_index`` plays
  for the causal triangle).

Within a live tile the (coarser) layout slab expands to a token mask via
two tiny 0/1 expansion matmuls (MXU-friendly — no gathers or lane-dim
reshapes).  Numerics match the dense-masked reference implementation
(``ops/sparse_attention.sparse_attention``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import importlib

# the package re-exports the flash_mha *function* over the submodule name;
# import the module itself (shared helpers + INTERPRET flag)
_fm = importlib.import_module("deepspeed_tpu.ops.pallas.flash_mha")

NEG_INF = -1e30


def _kernel_block(lb: int) -> int:
    """Kernel tile edge: 128 (fine skip granularity, full MXU tiles) or
    the layout block itself when that is coarser."""
    return lb if lb > 128 else 128


def _pad_layout(layout: np.ndarray, nb_pad: int) -> np.ndarray:
    h, nbq, nbk = layout.shape
    out = np.zeros((h, nb_pad, nb_pad), layout.dtype)
    out[:, :nbq, :nbk] = layout
    return out


def _tile_live(layout: np.ndarray, bq: int, bk: int, lb: int,
               causal: bool) -> np.ndarray:
    """Host-side per-kernel-tile liveness [H, nq, nk] — the exact
    predicate the kernel's ``pl.when`` evaluates (tests call this to
    assert compute scales with layout density)."""
    h, nb, _ = layout.shape
    tq, tk = max(1, bq // lb), max(1, bk // lb)
    nq, nk = nb // tq, nb // tk
    live = layout.reshape(h, nq, tq, nk, tk).max((2, 4)) > 0
    if causal:
        iq = np.arange(nq)[:, None] * bq + bq - 1
        ik = np.arange(nk)[None, :] * bk
        live = live & (ik <= iq)[None]
    return live


def _kv_pick(live: np.ndarray, inner_is_k: bool) -> np.ndarray:
    """Clamp table for the non-owned operand's block index: dead steps
    reuse the most recent live index (no re-fetch), leading dead steps
    borrow the first upcoming live one (acts as prefetch).  Vectorised —
    this runs per trace (32k/64 layouts are ~4M entries)."""
    rows = live if inner_is_k else live.swapaxes(1, 2)  # [H, outer, inner]
    ni = rows.shape[2]
    idx = np.arange(ni, dtype=np.int32)
    # last live index at-or-before i (−1 where none yet)
    last = np.maximum.accumulate(np.where(rows, idx, -1), axis=2)
    # first live index anywhere (fallback for the leading dead run)
    any_live = rows.any(axis=2, keepdims=True)
    first = np.where(any_live, rows.argmax(axis=2, keepdims=True), 0)
    return np.where(last >= 0, last,
                    np.broadcast_to(first, last.shape)).astype(np.int32)


def _expand_mask(lt, bq: int, bk: int, lb: int):
    """[tq, tk] layout slab → [bq, bk] bool token mask via two 0/1
    expansion matmuls (no gather, no lane-dim reshape)."""
    tq, tk = lt.shape
    if tq == 1 and tk == 1:
        return jnp.broadcast_to(lt > 0, (bq, bk))
    er = (lax.broadcasted_iota(jnp.int32, (bq, tq), 0) // lb
          == lax.broadcasted_iota(jnp.int32, (bq, tq), 1)
          ).astype(jnp.float32)
    ec = (lax.broadcasted_iota(jnp.int32, (tk, bk), 0)
          == lax.broadcasted_iota(jnp.int32, (tk, bk), 1) // lb
          ).astype(jnp.float32)
    m = jax.lax.dot_general(er, lt.astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    m = jax.lax.dot_general(m, ec, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return m > 0.5


def _alive(lt, causal, iq, ik, bq, bk):
    pred = jnp.max(lt) > 0
    if causal:
        pred = jnp.logical_and(pred, ik * bk <= iq * bq + bq - 1)
    return pred


# ----------------------------------------------------------------------
# Kernels (structure mirrors flash_mha's KV-blocked kernels)
# ----------------------------------------------------------------------
def _fwd_kernel(pick_ref, q_ref, k_ref, v_ref, lt_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal, bq, bk, lb,
                s_real):
    del pick_ref  # consumed by the index maps (scalar prefetch)
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)
    lt = lt_ref[0]

    @pl.when(ik == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = _fm._scores(q, k, sm_scale)
        valid = _fm._block_mask(bq, bk, iq * bq, ik * bk, s_real, causal)
        valid = jnp.logical_and(valid, _expand_mask(lt, bq, bk, lb))
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    pl.when(_alive(lt, causal, iq, ik, bq, bk))(compute)

    @pl.when(ik == nk - 1)
    def _():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l > 0, l, 1.0)
        # fully-masked rows (no visible key anywhere) emit zeros, matching
        # the dense-masked reference's uniform-zero convention
        has = m_scr[:, 0:1] > NEG_INF / 2
        o_ref[0, 0] = jnp.where(has, acc_scr[...] / safe_l,
                                0.0).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, 0:1] + jnp.log(safe_l),
                                         lse_ref.shape[2:])


def _dq_kernel(pick_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               lt_ref, dq_ref, dq_scr, *, sm_scale, causal, bq, bk, lb,
               s_real):
    del pick_ref
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)
    lt = lt_ref[0]

    @pl.when(ik == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = _fm._scores(q, k, sm_scale)
        valid = _fm._block_mask(bq, bk, iq * bq, ik * bk, s_real, causal)
        valid = jnp.logical_and(valid, _expand_mask(lt, bq, bk, lb))
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)
        p = jnp.where(valid, p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                           (((1,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)

    pl.when(_alive(lt, causal, iq, ik, bq, bk))(compute)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(pick_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                lt_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale,
                causal, bq, bk, lb, s_real, group):
    del pick_ref
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    lt_all = lt_ref[...]  # [group, tq, tk]

    def compute():
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        for g in range(group):
            lt = lt_all[g]
            q = q_ref[0, g]
            do = do_ref[0, g]
            lse = lse_ref[0, g][:, 0:1]
            delta = delta_ref[0, g][:, 0:1]
            s = _fm._scores(q, k, sm_scale)
            valid = _fm._block_mask(bq, bk, iq * bq, ik * bk, s_real,
                                    causal, with_rows=True)
            valid = jnp.logical_and(valid, _expand_mask(lt, bq, bk, lb))
            s = jnp.where(valid, s, NEG_INF)
            p = jnp.exp(s - lse)
            p = jnp.where(valid, p, 0.0)
            dv_scr[...] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * sm_scale
            dk_scr[...] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    pred = jnp.max(lt_all) > 0
    if causal:
        pred = jnp.logical_and(pred, iq * bq + bq - 1 >= ik * bk)
    pl.when(pred)(compute)

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


# ----------------------------------------------------------------------
# pallas_call plumbing
# ----------------------------------------------------------------------
def _prep(q, layout, lb):
    b, hq, s_real, d = q.shape
    bq = bk = _kernel_block(lb)
    s_pad = -(-s_real // bq) * bq
    nb_pad = s_pad // lb
    lay = _pad_layout(np.asarray(layout), nb_pad)
    tq, tk = max(1, bq // lb), max(1, bk // lb)
    return bq, bk, s_pad, lay, tq, tk


def _fwd_impl(q, k, v, layout, lb, causal, sm_scale):
    b, hq, s_real, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    bq, bk, s_pad, lay, tq, tk = _prep(q, layout, lb)
    qp = _fm._pad_seq(q, s_pad)
    kp = _fm._pad_seq(k, s_pad)
    vp = _fm._pad_seq(v, s_pad)
    nq, nk = s_pad // bq, s_pad // bk
    live = _tile_live(lay, bq, bk, lb, causal)
    pick = jnp.asarray(_kv_pick(live, inner_is_k=True))
    lay_j = jnp.asarray(lay)

    grid = (b, hq, nq, nk)
    q_blk = pl.BlockSpec((1, 1, bq, d),
                         lambda ib, ih, iq, ik, pick_ref: (ib, ih, iq, 0))
    kv_blk = pl.BlockSpec(
        (1, 1, bk, d),
        lambda ib, ih, iq, ik, pick_ref: (ib, ih // group,
                                          pick_ref[ih, iq, ik], 0))
    lt_blk = pl.BlockSpec((1, tq, tk),
                          lambda ib, ih, iq, ik, pick_ref: (ih, iq, ik))
    lse_blk = pl.BlockSpec((1, 1, bq, 128),
                           lambda ib, ih, iq, ik, pick_ref: (ib, ih, iq, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, bk=bk, lb=lb, s_real=s_real),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[q_blk, kv_blk, kv_blk, lt_blk],
            out_specs=[q_blk, lse_blk],
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, d), jnp.float32),
            ]),
        interpret=_fm.INTERPRET,
        out_shape=[jax.ShapeDtypeStruct((b, hq, s_pad, d), q.dtype),
                   jax.ShapeDtypeStruct((b, hq, s_pad, 128), jnp.float32)],
    )(pick, qp, kp, vp, lay_j)
    return o[:, :, :s_real], lse[:, :, :s_real, 0]


def _bwd_impl(q, k, v, o, lse, g, layout, lb, causal, sm_scale):
    b, hq, s_real, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    bq, bk, s_pad, lay, tq, tk = _prep(q, layout, lb)
    nq, nk = s_pad // bq, s_pad // bk
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    qp = _fm._pad_seq(q, s_pad)
    kp = _fm._pad_seq(k, s_pad)
    vp = _fm._pad_seq(v, s_pad)
    gp = _fm._pad_seq(g, s_pad)
    lsep = _fm._lanes(lse, s_pad)
    deltap = _fm._lanes(delta, s_pad)
    live = _tile_live(lay, bq, bk, lb, causal)
    pick_k = jnp.asarray(_kv_pick(live, inner_is_k=True))
    lay_j = jnp.asarray(lay)

    q_blk = pl.BlockSpec((1, 1, bq, d),
                         lambda ib, ih, iq, ik, pref: (ib, ih, iq, 0))
    kv_blk = pl.BlockSpec(
        (1, 1, bk, d),
        lambda ib, ih, iq, ik, pref: (ib, ih // group,
                                      pref[ih, iq, ik], 0))
    lt_blk = pl.BlockSpec((1, tq, tk),
                          lambda ib, ih, iq, ik, pref: (ih, iq, ik))
    lane_blk = pl.BlockSpec((1, 1, bq, 128),
                            lambda ib, ih, iq, ik, pref: (ib, ih, iq, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, bk=bk, lb=lb, s_real=s_real),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hq, nq, nk),
            in_specs=[q_blk, kv_blk, kv_blk, q_blk, lane_blk, lane_blk,
                      lt_blk],
            out_specs=q_blk,
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)]),
        interpret=_fm.INTERPRET,
        out_shape=jax.ShapeDtypeStruct((b, hq, s_pad, d), q.dtype),
    )(pick_k, qp, kp, vp, gp, lsep, deltap, lay_j)

    # dkv: grid (b, hkv, nk, nq), q-side clamped by the any-over-group
    # liveness (transposed walk); the (group, ...)-sized blocks cover the
    # kv-head's query heads directly on the head axis
    live_any = live.reshape(hkv, group, nq, nk).max(1) > 0
    pick_q = jnp.asarray(_kv_pick(live_any, inner_is_k=False))

    def q_idx(ib, ihkv, ik, iq, pref):
        return (ib, ihkv, pref[ihkv, ik, iq], 0)

    grp_blk = pl.BlockSpec((1, group, bq, d), q_idx)
    grp_lane = pl.BlockSpec((1, group, bq, 128), q_idx)
    kv_own = pl.BlockSpec((1, 1, bk, d),
                          lambda ib, ihkv, ik, iq, pref: (ib, ihkv, ik, 0))
    # the layout tile MUST use the true (unclamped) q index: the skip
    # predicate reads it, and a clamped-to-live tile here would re-run a
    # live tile's compute on a dead step (double counting)
    lt_grp = pl.BlockSpec(
        (group, tq, tk),
        lambda ib, ihkv, ik, iq, pref: (ihkv, iq, ik))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, bk=bk, lb=lb, s_real=s_real, group=group),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv, nk, nq),
            in_specs=[grp_blk, kv_own, kv_own, grp_blk, grp_lane, grp_lane,
                      lt_grp],
            out_specs=[kv_own, kv_own],
            scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                            pltpu.VMEM((bk, d), jnp.float32)]),
        interpret=_fm.INTERPRET,
        out_shape=[jax.ShapeDtypeStruct((b, hkv, s_pad, d), k.dtype),
                   jax.ShapeDtypeStruct((b, hkv, s_pad, d), v.dtype)],
    )(pick_q, qp, kp, vp, gp, lsep, deltap, lay_j)
    return dq[:, :, :s_real], dk[:, :, :s_real], dv[:, :, :s_real]


def block_sparse_mha(q, k, v, layout, block: int, causal: bool = False,
                     sm_scale=None):
    """Block-sparse attention over ``q [B, Hq, S, D]``, ``k/v [B, Hkv, S,
    D]`` with a DeepSpeed block ``layout [Hq, S/block, S/block]``.
    Differentiable (custom VJP mirroring flash_mha's saved-residual
    backward); dead layout tiles cost neither FLOPs nor K/V DMA."""
    layout = np.asarray(layout)
    if layout.shape[0] != q.shape[1]:
        raise ValueError(
            f"layout has {layout.shape[0]} heads but q has {q.shape[1]} — "
            "a mismatched layout would silently clamp head indices on TPU")
    scale = 1.0 / math.sqrt(q.shape[-1]) if sm_scale is None else sm_scale
    lb = int(block)

    @jax.custom_vjp
    def f(q, k, v):
        o, _ = _fwd_impl(q, k, v, layout, lb, causal, scale)
        return o

    def f_fwd(q, k, v):
        o, lse = _fwd_impl(q, k, v, layout, lb, causal, scale)
        return o, (q, k, v, o, lse)

    def f_bwd(res, g):
        q, k, v, o, lse = res
        return _bwd_impl(q, k, v, o, lse, g, layout, lb, causal, scale)

    f.defvjp(f_fwd, f_bwd)
    return f(q, k, v)


def supports(s: int, d: int, block: int, num_heads: int,
             layout_heads: int | None = None) -> bool:
    """Applicability: layout blocks must tile the kernel blocks, the score
    tile must fit the documented VMEM budget, and (when given) the layout's
    head count must match the query heads (a mismatch would clamp head
    indices silently on TPU)."""
    if layout_heads is not None and layout_heads != num_heads:
        return False
    bq = _kernel_block(block)
    if block <= 128 and 128 % block != 0:
        return False
    return bq * bq * 4 <= (1 << 22) and d <= 256
