"""Blockwise int8/int4 quantization as Pallas TPU kernels.

Device-kernel analog of the reference's quantization kernel set
(``csrc/quantization/quantize.cu``, ``dequantize.cu``,
``fake_quantizer.cu``, ``swizzled_quantize.cu`` — SURVEY §2.6).  The jnp
path (``ops/quantizer.py``) is numerically identical; these kernels pin
the one-HBM-pass guarantee and measurably beat XLA's fusion of the jnp
form — r04 on v5e (8192² bf16, in-jit scan, tools/bench_kernels.py):
quant+dequant 2.94 ms vs 5.0 ms (138 vs 82 GB/s effective), QAT
fake-quantize 3.3 ms vs 6.2 ms — because XLA materialises the
absmax/scale intermediates between its loop fusions while the kernel
keeps them in VMEM:

* ``quantize``: reads the float tensor ONCE, writes int8 payload + fp32
  scales — no intermediate absmax/scale round-trip can be materialised.
* ``dequantize``: reads int8+scales once, writes float once.
* ``fake_quantize``: QAT round-trip without ever materialising the int8
  payload in HBM.

Layout: the tensor is viewed as [M, N] rows with the last axis split into
``group_size``-wide groups.  The Pallas grid tiles rows × groups, so every
kernel block is ``[block_m, group_size]`` — each *row* of a block is one
quantization group, absmax reduces over lanes, and no in-kernel reshapes
are needed (lane-dim reshapes are the thing Mosaic dislikes).  Scales come
out as ``[M, n_groups]``; their block spans the full group axis with an
index map that ignores the group step (Mosaic requires the minor block
dim be 128-divisible or the whole axis — a [bm, 1] block is rejected on
hardware), so the block persists across the inner grid steps and each
step writes only its own column.

Constraints (callers fall back to the jnp path otherwise — see
``ops.quantizer.quantize_blockwise(backend=...)``): last dim divisible by
``group_size``, ``group_size`` a multiple of 128, symmetric mode.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# flipped by tests to run kernels on the CPU interpreter
INTERPRET = False


def supports(shape: Tuple[int, ...], group_size: int, symmetric: bool,
             num_bits: int) -> bool:
    """Whether the Pallas path can serve this call."""
    if not symmetric or num_bits not in (4, 8):
        return False
    if len(shape) == 0 or group_size <= 0:  # <=0 means whole-tensor group
        return False
    n = shape[-1]
    return n >= group_size and n % group_size == 0 and group_size % 128 == 0


def _view_2d(x: jnp.ndarray) -> Tuple[jnp.ndarray, Tuple[int, ...]]:
    shape = x.shape
    m = 1
    for d in shape[:-1]:
        m *= d
    return x.reshape(m, shape[-1]), shape


def _block_m(m: int, itemdtype) -> int:
    # int8 tiles want >=32 sublanes; cap block height so a block stays
    # well under VMEM (block_m * group_size * 4B, group_size <= 1024)
    bm = 256
    while bm > m and bm > 8:
        bm //= 2
    return max(bm, 8)


def _quant_kernel(x_ref, q_ref, s_ref, *, qmax: float):
    # the scales block spans all groups and persists across the inner (j)
    # grid steps; a width-1 dynamic lane store does not lower on TPU, so
    # each step folds its column in via a one-hot select (VMEM-local)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        s_ref[...] = jnp.zeros_like(s_ref)

    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = absmax / qmax
    scale = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(x / scale), -qmax - 1.0, qmax)
    q_ref[...] = q.astype(jnp.int8)
    lane = jax.lax.broadcasted_iota(jnp.int32, s_ref.shape, 1)
    s_ref[...] += jnp.where(lane == j, scale, 0.0)


def quantize(x: jnp.ndarray, num_bits: int = 8,
             group_size: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric blockwise quantize; returns ``(q_int8, scales)`` with
    ``scales.shape == x.shape[:-1] + (n // group_size,)``."""
    x2, shape = _view_2d(x)
    m, n = x2.shape
    ng = n // group_size
    bm = _block_m(m, x2.dtype)
    qmax = float(2 ** (num_bits - 1) - 1)
    grid = (pl.cdiv(m, bm), ng)
    q, s = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=qmax),
        grid=grid,
        in_specs=[pl.BlockSpec((bm, group_size), lambda i, j: (i, j))],
        out_specs=[
            pl.BlockSpec((bm, group_size), lambda i, j: (i, j)),
            pl.BlockSpec((bm, ng), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), jnp.int8),
            jax.ShapeDtypeStruct((m, ng), jnp.float32),
        ],
        interpret=INTERPRET,
    )(x2)
    return q.reshape(shape), s.reshape(shape[:-1] + (ng,))


def _dequant_kernel(q_ref, s_ref, o_ref, *, dtype):
    j = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)
    s = s_ref[...]
    lane = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    scale = jnp.sum(jnp.where(lane == j, s, 0.0), axis=1, keepdims=True)
    o_ref[...] = (q * scale).astype(dtype)


def dequantize(q: jnp.ndarray, scales: jnp.ndarray,
               dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize`."""
    q2, shape = _view_2d(q)
    m, n = q2.shape
    ng = scales.shape[-1]
    group_size = n // ng
    s2 = scales.reshape(m, ng)
    bm = _block_m(m, q2.dtype)
    grid = (pl.cdiv(m, bm), ng)
    out = pl.pallas_call(
        functools.partial(_dequant_kernel, dtype=dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, group_size), lambda i, j: (i, j)),
            pl.BlockSpec((bm, ng), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bm, group_size), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), dtype),
        interpret=INTERPRET,
    )(q2, s2)
    return out.reshape(shape)


def _fake_quant_kernel(x_ref, o_ref, *, qmax: float):
    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = absmax / qmax
    scale = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(x / scale), -qmax - 1.0, qmax)
    o_ref[...] = (q * scale).astype(o_ref.dtype)


def fake_quantize(x: jnp.ndarray, num_bits: int = 8,
                  group_size: int = 256) -> jnp.ndarray:
    """Quantize-dequantize round-trip (QAT) in one HBM pass — the int8
    payload never leaves VMEM (ref fake_quantizer.cu)."""
    x2, shape = _view_2d(x)
    m, n = x2.shape
    bm = _block_m(m, x2.dtype)
    qmax = float(2 ** (num_bits - 1) - 1)
    grid = (pl.cdiv(m, bm), n // group_size)
    out = pl.pallas_call(
        functools.partial(_fake_quant_kernel, qmax=qmax),
        grid=grid,
        in_specs=[pl.BlockSpec((bm, group_size), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((bm, group_size), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=INTERPRET,
    )(x2)
    return out.reshape(shape)
