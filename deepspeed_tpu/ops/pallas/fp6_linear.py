"""FP6 (e3m2) packed-weight linear: real 6-bit storage + a Pallas GEMM
that unpacks in VMEM.

TPU-native analog of the reference's FP6-LLM weight-only path
(``inference/v2/kernels/core_ops/cuda_linear/cuda_linear.py:167`` — packed
6-bit storage + split-K GEMM): weights live in HBM as 0.75 bytes/value
(plus one fp32 scale per output column), and the matmul kernel reads ONLY
the packed bytes, decoding e3m2 → bf16 inside VMEM right before the MXU
dot.  Serving is weight-bandwidth-bound, so reading 6 bits instead of 16
is both the memory saving at rest AND the bandwidth win per step — the
property the quant-dequant emulation in ``ops/fp_quantizer.py`` cannot
provide.

Layout: the [K, N] weight's K dim is viewed in groups of 4 values
v0..v3 (6 bits each = 3 bytes), stored as three byte PLANES
``packed[3, K/4, N]``:

    B0 = v0<<2 | v1>>4;  B1 = (v1&15)<<4 | v2>>2;  B2 = (v2&3)<<6 | v3

Plane-major packing means the kernel never interleaves along sublanes:
the activation is pre-split into 4 K-strided planes ``x4[4, M, K/4]``
(``x[:, p::4]``), and the tile dot is the sum of 4 plane dots — the
split-K structure of the reference GEMM, with K-grid accumulation in an
f32 VMEM scratch.

e3m2: 1 sign, 3 exponent (bias 3, full range — no inf/nan codes),
2 mantissa; max normal 28.0, subnormal step 2^-4.  Encoding snaps to the
nearest representable value (host-side, at weight-load time).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.utils.jax_compat import tpu_compiler_params
from deepspeed_tpu.utils.logging import logger

INTERPRET = False

# One-time flag: the dequantize-then-dot fallback silently reads 16-bit
# weights (the whole point of fp6 is the 6-bit wire/HBM read), so losing
# the bandwidth win must be visible in logs exactly once per process.
_warned_fallback = False

_BIAS = 3
_MAX_VAL = 28.0  # (2 - 2^-2) * 2^(7-3): full exponent range, no inf/nan


def _decode_table() -> np.ndarray:
    """All 64 e3m2 code values (index = 6-bit code)."""
    codes = np.arange(64)
    s = codes >> 5
    e = (codes >> 2) & 7
    m = (codes & 3).astype(np.float64)
    mag = np.where(e == 0, m * 2.0 ** (1 - _BIAS - 2),
                   (1.0 + m * 0.25) * 2.0 ** (e - _BIAS))
    return np.where(s == 1, -mag, mag).astype(np.float32)


DECODE_TABLE = _decode_table()


def fp6_quantize(w) -> tuple:
    """[K, N] weight → (packed uint8 [3, K/4, N], scale fp32 [N]).

    Per-output-column absmax scaling (the reference's per-channel
    quantization), nearest-representable e3m2 encoding, plane packing.
    Host-side numpy — runs once at weight-load time."""
    w = np.asarray(w, np.float32)
    k, n = w.shape
    if k % 4:
        raise ValueError(f"K={k} must be divisible by 4 for fp6 packing")
    scale = np.maximum(np.abs(w).max(axis=0), 1e-12) / _MAX_VAL   # [N]
    ws = w / scale[None, :]
    # nearest representable value via searchsorted on the sorted table
    order = np.argsort(DECODE_TABLE, kind="stable")
    tbl = DECODE_TABLE[order]
    pos = np.searchsorted(tbl, ws).clip(1, 63)
    lo, hi = tbl[pos - 1], tbl[np.minimum(pos, 63)]
    pick_hi = (ws - lo) > (hi - ws)
    codes = order[np.where(pick_hi, np.minimum(pos, 63), pos - 1)]
    codes = codes.astype(np.uint8)                                # [K, N]
    v = codes.reshape(k // 4, 4, n)
    v0, v1, v2, v3 = v[:, 0], v[:, 1], v[:, 2], v[:, 3]
    packed = np.stack([
        (v0 << 2) | (v1 >> 4),
        ((v1 & 15) << 4) | (v2 >> 2),
        ((v2 & 3) << 6) | v3,
    ]).astype(np.uint8)                                           # [3,K/4,N]
    return jnp.asarray(packed), jnp.asarray(scale, jnp.float32)


def _unpack_codes(packed):
    """[3, K/4, N] planes → 4 code planes v0..v3 (int32 [K/4, N])."""
    b0 = packed[0].astype(jnp.int32)
    b1 = packed[1].astype(jnp.int32)
    b2 = packed[2].astype(jnp.int32)
    v0 = b0 >> 2
    v1 = ((b0 & 3) << 4) | (b1 >> 4)
    v2 = ((b1 & 15) << 2) | (b2 >> 6)
    v3 = b2 & 63
    return v0, v1, v2, v3


def _decode(v):
    """e3m2 code plane (int32) → f32 values, arithmetically (no table
    gather — VPU-friendly)."""
    s = v >> 5
    e = (v >> 2) & 7
    m = (v & 3).astype(jnp.float32)
    mag = jnp.where(e == 0, m * 2.0 ** (1 - _BIAS - 2),
                    (1.0 + m * 0.25) * jnp.exp2((e - _BIAS)
                                                .astype(jnp.float32)))
    return jnp.where(s == 1, -mag, mag)


def fp6_dequantize(packed, scale, dtype=jnp.bfloat16):
    """Full dequantized [K, N] weight (XLA fallback / tests)."""
    k4 = packed.shape[1]
    n = packed.shape[2]
    planes = [_decode(v) for v in _unpack_codes(packed)]
    w = jnp.stack(planes, axis=1).reshape(k4 * 4, n)
    return (w * scale[None, :]).astype(dtype)


def _mm_kernel(x_ref, p_ref, sc_ref, o_ref, acc, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    v0, v1, v2, v3 = _unpack_codes(p_ref[...])
    part = jnp.zeros_like(acc)
    for p, v in enumerate((v0, v1, v2, v3)):
        part += jax.lax.dot_general(
            x_ref[p], _decode(v).astype(x_ref.dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    acc[...] += part

    @pl.when(k == nk - 1)
    def _():
        o_ref[...] = (acc[...] * sc_ref[0][None, :]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "block_k4"))
def fp6_matmul(x, packed, scale, block_m: int = 256, block_n: int = 256,
               block_k4: int = 128):
    """``x [M, K] @ fp6_weight [K, N]`` reading only packed bytes.

    The kernel consumes the activation as 4 K-strided planes and sums 4
    plane dots per tile (split-K over the plane structure), accumulating
    across the K grid in f32 scratch.  Falls back to the XLA
    dequantize-then-dot form off-TPU unless INTERPRET."""
    lead = x.shape[:-1]
    if x.ndim != 2:
        # [..., K] activations (e.g. [B, S, H]) flatten to rows
        x = x.reshape(-1, x.shape[-1])
    m, k = x.shape
    _, k4, n = packed.shape
    if k4 * 4 != k:
        raise ValueError(f"packed K {k4 * 4} != x K {k}")
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        on_tpu = False
    # Awkward M (prime, 2·prime, …) would degenerate the largest-divisor
    # tile into 1-2 rows; pad M up to a multiple of 8 (sublane) instead —
    # a few zero rows beat either tiny tiles or falling back to reading
    # the full dequantized weight on this weight-bandwidth-bound path.
    m_pad = -(-m // 8) * 8
    if m_pad != m:
        x = jnp.concatenate(
            [x, jnp.zeros((m_pad - m, k), x.dtype)], axis=0)
    bm = next((c for c in range(min(block_m, m_pad), 7, -1)
               if m_pad % c == 0), 8)
    bn = min(block_n, n)
    bk4 = min(block_k4, k4)
    servable = (n % bn == 0 and k4 % bk4 == 0
                and bn % 128 == 0 and bk4 % 8 == 0)
    if not servable or not (on_tpu or INTERPRET):
        global _warned_fallback
        if not _warned_fallback:
            reason = (f"unservable tile shape (K={k}, N={n} vs blocks "
                      f"bn={bn}, bk4={bk4})" if (on_tpu or INTERPRET)
                      else "not running on TPU")
            logger.warning(
                "fp6_matmul: %s — falling back to dequantize-then-dot; the "
                "packed 6-bit HBM/bandwidth win is lost for these calls "
                "(weights are expanded to %s before the MXU dot)",
                reason, jnp.dtype(x.dtype).name)
            _warned_fallback = True
        out = x[:m] @ fp6_dequantize(packed, scale, x.dtype)
        return out.reshape(lead + (n,))
    m = m_pad

    x4 = x.reshape(m, k4, 4).swapaxes(0, 2).swapaxes(1, 2)  # [4, M, K/4]
    nk = k4 // bk4
    out = pl.pallas_call(
        functools.partial(_mm_kernel, nk=nk),
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((4, bm, bk4), lambda i, j, k_: (0, i, k_)),
            pl.BlockSpec((3, bk4, bn), lambda i, j, k_: (0, k_, j)),
            pl.BlockSpec((1, bn), lambda i, j, k_: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k_: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=INTERPRET,
    )(x4, packed, scale.reshape(1, n))
    rows = int(np.prod(lead, dtype=np.int64)) if lead else 1
    return out[:rows].reshape(lead + (n,))
