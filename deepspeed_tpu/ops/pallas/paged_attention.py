"""Repo-owned Pallas paged (block-table) attention for inference v2 decode.

TPU replacement for the reference's ragged blocked-flash CUDA kernels
(``/root/reference/deepspeed/inference/v2/kernels/ragged_ops/`` — blocked
flash over a KV block table). Design:

* **Grid (T, nkv, NB)**: one query token × one KV head per outer step, one
  KV-cache page per inner step. The page's row index comes from the block
  table via **scalar prefetch** — Pallas's pipeline DMAs page
  ``tables[t, j+1]`` into VMEM while page ``tables[t, j]`` is being
  processed, which is exactly the manual prefetch loop the reference's CUDA
  kernel implements by hand.
* **Online softmax** accumulators (m, l, acc) live in VMEM scratch and
  persist across the sequential page steps; output is written on the last
  page.
* **GQA-native**: the q block for KV head ``h`` is its ``group`` query
  heads ``[group, d]``, matmul'd against the page block ``[bs, d]`` — KV
  heads are never repeated, and every contraction is a plain rank-2 matmul
  (Mosaic-friendly; no in-kernel reshapes).
* No [T, C, nkv, d] gather is ever materialised in HBM (the XLA fallback's
  cost, and the reason decode throughput was gather-bound in round 1).

Cache layout contract: k_pages/v_pages are ``[nkv, P, d]`` where P = number
of pages × block_size rows; ``pages[t, j]`` gives page ids (row-blocks of
``block_size``). Positions ``c = j*block_size + r`` are masked against the
token's causal position and its sequence's context length.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

INTERPRET = False


def supports(block_size: int, d: int) -> bool:
    """Kernel applicability: page rows must be sublane-aligned."""
    return block_size >= 8 and block_size % 8 == 0


def _kernel(pages_ref, pos_ref, clen_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, *, bs, group, sm_scale, window=None):
    t = pl.program_id(0)
    j = pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    pos = pos_ref[t]
    clen = clen_ref[t]

    # Pages beyond the causal frontier — or wholly before the sliding
    # window — contribute nothing; skip their math (their DMA already
    # happened: it is the pipeline's prefetch slot).
    alive = j * bs <= pos
    if window is not None:
        alive = jnp.logical_and(alive, pos - (j * bs + bs - 1) < window)

    @pl.when(alive)
    def _():
        q = q_ref[0, 0]                                  # [group, d]
        k = k_ref[0]                                     # [bs, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [group, bs]
        s = s * sm_scale
        c = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * bs
        valid = (c <= pos) & (c < clen)
        if window is not None:
            valid &= pos - c < window
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[:, 0:1]                           # [group, 1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                           # [group, bs]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [group, d]
        acc_scr[...] = acc_scr[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nb - 1)
    def _():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_size", "sm_scale",
                                             "window"))
def paged_decode_attention(q, k_pages, v_pages, pages, token_pos,
                           token_ctx_len, block_size: int, sm_scale: float,
                           window: int | None = None):
    """q: [T, nh, d]; k_pages/v_pages: [nkv, P, d]; pages: [T, NB] page ids
    per token; token_pos/token_ctx_len: [T]; ``window``: Mistral sliding
    window (key visible iff qpos - kpos < window).  Returns [T, nh, d]."""
    t, nh, d = q.shape
    nkv = k_pages.shape[0]
    group = nh // nkv
    nb = pages.shape[1]
    bs = block_size

    kv_spec = pl.BlockSpec(
        (1, bs, d),
        lambda t_, h, j, pages_r, pos_r, clen_r: (h, pages_r[t_, j], 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t, nkv, nb),
        in_specs=[
            # q reshaped to [T, nkv, group, d] outside: one KV head's query
            # group per block, full trailing dims (Mosaic block constraint)
            pl.BlockSpec((1, 1, group, d),
                         lambda t_, h, j, *refs: (t_, h, 0, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda t_, h, j, *refs: (t_, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),   # m
            pltpu.VMEM((group, 128), jnp.float32),   # l
            pltpu.VMEM((group, d), jnp.float32),     # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, bs=bs, group=group, sm_scale=sm_scale,
                          window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, nkv, group, d), q.dtype),
        interpret=INTERPRET,
    )(pages.astype(jnp.int32), token_pos.astype(jnp.int32),
      token_ctx_len.astype(jnp.int32), q.reshape(t, nkv, group, d),
      k_pages, v_pages)
    return out.reshape(t, nh, d)
