"""Repo-owned Pallas paged (block-table) attention for inference v2 decode.

TPU replacement for the reference's ragged blocked-flash CUDA kernels
(``/root/reference/deepspeed/inference/v2/kernels/ragged_ops/`` — blocked
flash over a KV block table). Design:

* **Grid (T, nkv)**: ONE program per (query token, KV head) walks that
  token's live pages in an in-kernel ``fori_loop`` with double-buffered
  manual DMA (``pltpu.make_async_copy``) out of the HBM-resident page
  pool — page ``tables[t, j+1]``'s copy is in flight while page
  ``tables[t, j]`` is being processed, the same prefetch loop the
  reference's CUDA kernel implements by hand.  (Putting the page walk on
  the grid instead costs T·nkv·NB invocations whose fixed per-step
  overhead dominated decode — measured r04: 7.3 → 2.3 ms/call at T=32,
  NB=128.)
* **Online softmax** state (m, l, acc) rides the loop carry; dead pages
  (beyond the causal frontier, or before the sliding window) are never
  visited at all.
* **GQA-native**: the q block for KV head ``h`` is its ``group`` query
  heads ``[group, d]``, matmul'd against the page block ``[bs, d]`` — KV
  heads are never repeated, and every contraction is a plain rank-2 matmul
  (Mosaic-friendly; no in-kernel reshapes).
* No [T, C, nkv, d] gather is ever materialised in HBM (the XLA fallback's
  cost, and the reason decode throughput was gather-bound in round 1).

Cache layout contract: k_pages/v_pages are ``[nkv, P, d]`` where P = number
of pages × block_size rows; ``pages[t, j]`` gives page ids (row-blocks of
``block_size``). Positions ``c = j*block_size + r`` are masked against the
token's causal position and its sequence's context length.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

INTERPRET = False


def supports(block_size: int, d: int) -> bool:
    """Kernel applicability: page rows must be sublane-aligned."""
    return block_size >= 8 and block_size % 8 == 0


def _kernel(pages_ref, pos_ref, clen_ref, q_ref, k_hbm, v_hbm, o_ref,
            k_buf, v_buf, sem_k, sem_v, *, bs, group, sm_scale,
            window=None):
    """Grid (T, nkv): ONE program per (token, KV head) walks that token's
    live pages in an in-kernel fori_loop with double-buffered manual DMA
    from the HBM-resident page pool.  The previous design put the page
    walk on the grid — T·nkv·NB invocations whose fixed per-step cost
    (~0.6 µs on v5e) dominated decode (measured r04: 7.3 ms/call at
    T=32, NB=128 vs 0.35 ms for this form, with identical math)."""
    t = pl.program_id(0)
    h = pl.program_id(1)
    pos = pos_ref[t]
    clen = clen_ref[t]
    j_lo = jnp.int32(0)
    if window is not None:
        j_lo = jnp.maximum((pos - (window - 1)) // bs, 0)
    j_hi = pos // bs + 1  # one past the causal frontier page

    def page_copy(j, slot):
        page = pages_ref[t, j]
        ck = pltpu.make_async_copy(
            k_hbm.at[h, pl.dslice(page * bs, bs)], k_buf.at[slot],
            sem_k.at[slot])
        cv = pltpu.make_async_copy(
            v_hbm.at[h, pl.dslice(page * bs, bs)], v_buf.at[slot],
            sem_v.at[slot])
        ck.start()
        cv.start()

    page_copy(j_lo, 0)
    q = q_ref[0, 0]                                      # [group, d]

    def body(j, carry):
        m_prev, l_prev, acc = carry
        slot = lax.rem(j - j_lo, 2)

        @pl.when(j + 1 < j_hi)
        def _():
            page_copy(j + 1, 1 - slot)

        # wait() only consumes (sem, dst-bytes) — the src slice need not
        # match the one the copy was started with, so a fixed slice
        # reconstructs an equivalent descriptor for the decrement
        pltpu.make_async_copy(k_hbm.at[h, pl.dslice(0, bs)],
                              k_buf.at[slot], sem_k.at[slot]).wait()
        pltpu.make_async_copy(v_hbm.at[h, pl.dslice(0, bs)],
                              v_buf.at[slot], sem_v.at[slot]).wait()
        k = k_buf[slot]                                  # [bs, d]
        v = v_buf[slot]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [group, bs]
        s = s * sm_scale
        c = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * bs
        valid = (c <= pos) & (c < clen)
        if window is not None:
            valid &= pos - c < window
        s = jnp.where(valid, s, NEG_INF)

        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                           # [group, bs]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [group, d]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((group, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, 1), jnp.float32)
    a0 = jnp.zeros((group, q.shape[-1]), jnp.float32)
    m, l, acc = lax.fori_loop(j_lo, j_hi, body, (m0, l0, a0))
    safe_l = jnp.where(l > 0, l, 1.0)
    o_ref[0, 0] = (acc / safe_l).astype(o_ref.dtype)


def _kernel_quant(pages_ref, pos_ref, clen_ref, q_ref, ksc_ref, vsc_ref,
                  k_hbm, v_hbm, o_ref, k_buf, v_buf, sem_k, sem_v, *,
                  bs, group, sm_scale, window=None):
    """Int8-KV variant of :func:`_kernel`: the page payloads are int8 with
    one fp32 scale per (head, row).  Only the d-wide payload rides the
    manual double-buffered DMA (half the bytes of the bf16 cache — the
    decode bandwidth win); the [P]-long per-head scale rows are small and
    arrive whole through an ordinary VMEM BlockSpec, sliced per page.
    Scales fold into existing vectors: the k scale multiplies score
    COLUMNS after the q·k matmul, the v scale multiplies the softmax
    probabilities before p·v — no [bs, d] dequantized buffer ever
    materialises."""
    t = pl.program_id(0)
    h = pl.program_id(1)
    pos = pos_ref[t]
    clen = clen_ref[t]
    j_lo = jnp.int32(0)
    if window is not None:
        j_lo = jnp.maximum((pos - (window - 1)) // bs, 0)
    j_hi = pos // bs + 1

    def page_copy(j, slot):
        page = pages_ref[t, j]
        pltpu.make_async_copy(
            k_hbm.at[h, pl.dslice(page * bs, bs)], k_buf.at[slot],
            sem_k.at[slot]).start()
        pltpu.make_async_copy(
            v_hbm.at[h, pl.dslice(page * bs, bs)], v_buf.at[slot],
            sem_v.at[slot]).start()

    page_copy(j_lo, 0)
    q = q_ref[0, 0]                                      # [group, d]

    def body(j, carry):
        m_prev, l_prev, acc = carry
        slot = lax.rem(j - j_lo, 2)

        @pl.when(j + 1 < j_hi)
        def _():
            page_copy(j + 1, 1 - slot)

        pltpu.make_async_copy(k_hbm.at[h, pl.dslice(0, bs)],
                              k_buf.at[slot], sem_k.at[slot]).wait()
        pltpu.make_async_copy(v_hbm.at[h, pl.dslice(0, bs)],
                              v_buf.at[slot], sem_v.at[slot]).wait()
        page = pages_ref[t, j]
        ks = ksc_ref[0, pl.dslice(page * bs, bs)]        # [bs] f32
        vs = vsc_ref[0, pl.dslice(page * bs, bs)]
        k = k_buf[slot].astype(jnp.float32)              # int8 rows exact
        v = v_buf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [group, bs]
        s = s * (sm_scale * ks)[None, :]
        c = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * bs
        valid = (c <= pos) & (c < clen)
        if window is not None:
            valid &= pos - c < window
        s = jnp.where(valid, s, NEG_INF)

        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        e = jnp.exp(s - m_new)                           # [group, bs]
        l_new = l_prev * alpha + jnp.sum(e, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            e * vs[None, :], v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [group, d]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((group, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, 1), jnp.float32)
    a0 = jnp.zeros((group, q.shape[-1]), jnp.float32)
    m, l, acc = lax.fori_loop(j_lo, j_hi, body, (m0, l0, a0))
    safe_l = jnp.where(l > 0, l, 1.0)
    o_ref[0, 0] = (acc / safe_l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_size", "sm_scale",
                                             "window"))
def paged_decode_attention(q, k_pages, v_pages, pages, token_pos,
                           token_ctx_len, block_size: int, sm_scale: float,
                           window: int | None = None,
                           k_scales=None, v_scales=None):
    """q: [T, nh, d]; k_pages/v_pages: [nkv, P, d]; pages: [T, NB] page ids
    per token; token_pos/token_ctx_len: [T]; ``window``: Mistral sliding
    window (key visible iff qpos - kpos < window).  With
    ``k_scales``/``v_scales`` [nkv, P] the page payloads are int8 rows
    scaled per (head, row) — ref KV-block layout
    inference/v2/ragged/kv_cache.py:40.  Returns [T, nh, d]."""
    t, nh, d = q.shape
    nkv, p_rows = k_pages.shape[0], k_pages.shape[1]
    group = nh // nkv
    bs = block_size
    quant = k_scales is not None

    in_specs = [
        # q reshaped to [T, nkv, group, d] outside: one KV head's query
        # group per block, full trailing dims (Mosaic block constraint)
        pl.BlockSpec((1, 1, group, d), lambda t_, h, *refs: (t_, h, 0, 0)),
    ]
    extra = ()
    if quant:
        # whole per-head scale rows live in VMEM via the normal pipeline
        in_specs += [pl.BlockSpec((1, p_rows), lambda t_, h, *refs: (h, 0)),
                     pl.BlockSpec((1, p_rows), lambda t_, h, *refs: (h, 0))]
        extra = (k_scales.astype(jnp.float32), v_scales.astype(jnp.float32))
    in_specs += [
        # the page pools stay in HBM; the kernel DMAs live pages into
        # its double buffer itself
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t, nkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda t_, h, *refs: (t_, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, bs, d), k_pages.dtype),   # k double buffer
            pltpu.VMEM((2, bs, d), v_pages.dtype),   # v double buffer
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kern = _kernel_quant if quant else _kernel
    out = pl.pallas_call(
        functools.partial(kern, bs=bs, group=group, sm_scale=sm_scale,
                          window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, nkv, group, d), q.dtype),
        interpret=INTERPRET,
    )(pages.astype(jnp.int32), token_pos.astype(jnp.int32),
      token_ctx_len.astype(jnp.int32), q.reshape(t, nkv, group, d),
      *extra, k_pages, v_pages)
    return out.reshape(t, nh, d)
