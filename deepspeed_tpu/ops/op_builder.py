"""Native op builder: JIT-compiles C++ host ops and loads them via ctypes.

Analog of the reference's ``op_builder`` system (OpBuilder.jit_load,
op_builder/builder.py:544): sources live in ``csrc/``, are compiled with
g++ on first use into a cache directory, and reloaded from cache afterwards
(hash of source → .so name).  No torch cpp_extension / pybind11 — plain C
ABIs consumed with ctypes.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)


def _find_csrc() -> str:
    """Source tree location: repo root (dev/editable install) or inside the
    installed package (wheels ship deepspeed_tpu/csrc — see pyproject)."""
    for cand in (os.path.join(_REPO_ROOT, "csrc"),
                 os.path.join(_PKG_ROOT, "csrc")):
        if os.path.isdir(cand):
            return cand
    return os.path.join(_REPO_ROOT, "csrc")  # best-effort for error messages


CSRC_DIR = _find_csrc()
CACHE_DIR = os.environ.get("DSTPU_OPS_CACHE",
                           os.path.expanduser("~/.cache/deepspeed_tpu/ops"))


class OpBuilderError(RuntimeError):
    pass


def _source_hash(paths: List[str], extra: str = "") -> str:
    h = hashlib.sha256(extra.encode())
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def build_op(name: str, sources: List[str],
             extra_flags: Optional[List[str]] = None) -> ctypes.CDLL:
    """Compile ``sources`` (relative to csrc/) into lib<name>.so and dlopen it."""
    srcs = [os.path.join(CSRC_DIR, s) for s in sources]
    for s in srcs:
        if not os.path.exists(s):
            raise OpBuilderError(f"missing source {s}")
    flags = ["-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
             "-march=native"] + (extra_flags or [])
    tag = _source_hash(srcs, " ".join(flags))
    os.makedirs(CACHE_DIR, exist_ok=True)
    so_path = os.path.join(CACHE_DIR, f"lib{name}-{tag}.so")
    if not os.path.exists(so_path):
        # library flags (-lrt etc.) must FOLLOW the objects that need
        # their symbols, or the linker discards them as unused
        libs = [f for f in flags if f.startswith("-l")]
        cmd = (["g++"] + [f for f in flags if not f.startswith("-l")]
               + srcs + libs + ["-o", so_path])
        logger.info(f"building native op '{name}': {' '.join(cmd)}")
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise OpBuilderError(f"g++ failed for {name}:\n{proc.stderr}")
    return ctypes.CDLL(so_path)


_LOADED = {}


def load_op(name: str, sources: List[str],
            extra_flags: Optional[List[str]] = None) -> ctypes.CDLL:
    if name not in _LOADED:
        _LOADED[name] = build_op(name, sources, extra_flags)
    return _LOADED[name]
