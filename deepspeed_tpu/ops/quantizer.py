"""Blockwise integer quantization kernels.

TPU-native analog of the reference's quantization kernel set
(``csrc/quantization/``: quantize.cu, dequantize.cu, fake_quantizer.cu,
swizzled_quantize.cu, quant_reduce.cu — SURVEY §2.6).  On CUDA these are
hand-written warp kernels; on TPU the same math is plain jittable jnp that
XLA fuses into neighbouring ops (gather/scatter/reduce), so there is no
separate "kernel launch" — the quantize fuses into the collective's
producer and the dequantize into its consumer.

Swizzled layouts (swizzled_quantize.cu) exist on CUDA to coalesce the
subsequent NCCL transfer; XLA's layout assignment owns tiling on TPU, so no
swizzle variant is needed — noted here for parity auditing.  The claim
that the TRANSPORT really moves int8 (the whole point of qwZ/qgZ) is
pinned at the compiled-HLO level by tests/test_quant_transport.py: the
ZeRO++ all-gather and both qgZ all-to-all hops carry s8 payloads with no
full-size float collective remaining.

All functions are symmetric-by-default blockwise: the last axis is grouped
into ``group_size`` blocks, each with its own scale (and zero-point when
asymmetric).  int4 packs two nibbles per int8 byte for wire/memory savings.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _pallas_ok(x: jnp.ndarray, num_bits: int, group_size: int,
               symmetric: bool, backend: str) -> bool:
    """Route to the Pallas kernels (ops/pallas/quantize.py) when requested
    and servable: 'pallas' forces them, 'auto' uses them on TPU only (the
    CPU interpreter is test-grade), 'jnp' never."""
    if backend == "jnp":
        return False
    from deepspeed_tpu.ops.pallas import quantize as pq

    if not pq.supports(x.shape, group_size, symmetric, num_bits):
        return False
    if backend == "pallas" or pq.INTERPRET:
        return True
    return jax.default_backend() not in ("cpu",)


def _group(x: jnp.ndarray, group_size: int) -> Tuple[jnp.ndarray, int]:
    n = x.shape[-1]
    if group_size <= 0 or group_size > n:
        group_size = n
    if n % group_size != 0:
        raise ValueError(f"last dim {n} not divisible by group_size {group_size}")
    return x.reshape(x.shape[:-1] + (n // group_size, group_size)), group_size


def quantize_blockwise(x: jnp.ndarray, num_bits: int = 8, group_size: int = 256,
                       symmetric: bool = True,
                       backend: str = "auto") -> Tuple[jnp.ndarray, jnp.ndarray,
                                                       Optional[jnp.ndarray]]:
    """Quantize to ``num_bits`` integers with per-group scales.

    Returns ``(q, scale, zero_point)``; ``zero_point`` is None when
    symmetric.  q is int8 (int4 values occupy the low nibble range).
    ``backend``: 'auto' (Pallas on TPU when servable, else jnp),
    'pallas', or 'jnp'.
    Ref: csrc/quantization/quantize.cu / pt_binding quantize.
    """
    if _pallas_ok(x, num_bits, group_size, symmetric, backend):
        from deepspeed_tpu.ops.pallas import quantize as pq

        q, s = pq.quantize(x, num_bits, group_size)
        return q, s, None
    g, group_size = _group(x.astype(jnp.float32), group_size)
    qmax = float(2 ** (num_bits - 1) - 1)
    if symmetric:
        absmax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
        scale = absmax / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(g / scale), -qmax - 1, qmax).astype(jnp.int8)
        return q.reshape(x.shape), scale.squeeze(-1), None
    lo = jnp.min(g, axis=-1, keepdims=True)
    hi = jnp.max(g, axis=-1, keepdims=True)
    scale = (hi - lo) / (2 ** num_bits - 1)
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round((g - lo) / scale), 0, 2 ** num_bits - 1)
    # store centred so int8 holds uint range for 8-bit too
    q = (q - 2 ** (num_bits - 1)).astype(jnp.int8)
    return q.reshape(x.shape), scale.squeeze(-1), lo.squeeze(-1)


def dequantize_blockwise(q: jnp.ndarray, scale: jnp.ndarray,
                         zero_point: Optional[jnp.ndarray] = None,
                         num_bits: int = 8,
                         dtype=jnp.float32,
                         backend: str = "auto") -> jnp.ndarray:
    """Inverse of :func:`quantize_blockwise` (ref dequantize.cu)."""
    if zero_point is None and _pallas_ok(
            q, num_bits, q.shape[-1] // scale.shape[-1], True, backend):
        from deepspeed_tpu.ops.pallas import quantize as pq

        return pq.dequantize(q, scale, dtype=dtype)
    shape = q.shape
    group_size = shape[-1] // scale.shape[-1]
    g = q.astype(jnp.float32).reshape(shape[:-1] + (scale.shape[-1], group_size))
    if zero_point is None:
        out = g * scale[..., None]
    else:
        out = (g + 2 ** (num_bits - 1)) * scale[..., None] + zero_point[..., None]
    return out.reshape(shape).astype(dtype)


def fake_quantize(x: jnp.ndarray, num_bits: int = 8, group_size: int = 256,
                  symmetric: bool = True, backend: str = "auto") -> jnp.ndarray:
    """Quantize-dequantize roundtrip for QAT (ref fake_quantizer.cu).  The
    Pallas route does it in one HBM pass (payload stays in VMEM)."""
    if _pallas_ok(x, num_bits, group_size, symmetric, backend):
        from deepspeed_tpu.ops.pallas import quantize as pq

        return pq.fake_quantize(x, num_bits, group_size)
    q, s, z = quantize_blockwise(x, num_bits, group_size, symmetric,
                                 backend="jnp")
    return dequantize_blockwise(q, s, z, num_bits, dtype=x.dtype,
                                backend="jnp")


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Pack int4 values (stored in int8) into one byte per pair — halves
    wire/HBM footprint for quantized collectives (ref quant_reduce.cu uses
    4-bit lanes)."""
    if q.shape[-1] % 2 != 0:
        raise ValueError("last dim must be even to pack int4")
    lo = q[..., 0::2].astype(jnp.uint8) & 0xF
    hi = (q[..., 1::2].astype(jnp.uint8) & 0xF) << 4
    return (lo | hi).astype(jnp.uint8)


def unpack_int4(packed: jnp.ndarray) -> jnp.ndarray:
    lo = (packed & 0xF).astype(jnp.int8)
    hi = ((packed >> 4) & 0xF).astype(jnp.int8)
    # sign-extend nibbles
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(packed.shape[:-1] + (packed.shape[-1] * 2,))


def stochastic_round(x: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Stochastic rounding helper (ref sr_fused kernels): round up with
    probability equal to the fractional part."""
    floor = jnp.floor(x)
    frac = x - floor
    return floor + (jax.random.uniform(key, x.shape) < frac).astype(x.dtype)
