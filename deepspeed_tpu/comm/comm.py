"""Collectives façade.

TPU-native analog of ``deepspeed/comm/comm.py``: the same module-level API
(``init_distributed``, ``get_rank``, ``get_world_size``, ``all_reduce``,
``all_gather``, ``reduce_scatter``, ``all_to_all``, ``broadcast``,
``barrier``) but the backend is XLA collectives over mesh axes rather than
torch.distributed/NCCL.

Two modes:

* **In-jit** (the hot path): the ``all_reduce``-style functions take an
  ``axis_name`` (or use the default ZeRO axes) and lower to
  ``lax.psum/all_gather/psum_scatter/all_to_all``.  They must be called from
  inside ``shard_map``/``pjit`` tracing — the idiomatic TPU replacement for
  the reference's eager NCCL ops (SURVEY §2.2 note).
* **Eager** (setup/debug): ``all_reduce_eager`` etc. wrap the op in a
  one-shot ``shard_map`` over the global topology's mesh, so tests and setup
  code can reduce concrete arrays.

Per-op timing/logging mirrors ``timed_op``/``CommsLogger``
(ref comm/comm.py:102, utils/comms_logging.py:67).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DATA_AXIS, EXPERT_AXIS, MESH_AXES, SEQ_AXIS,
                                             TENSOR_AXIS, ZERO_AXES, MeshTopology,
                                             get_topology, set_topology)
from deepspeed_tpu.utils.comms_logging import get_comms_logger
from deepspeed_tpu.utils.logging import logger

AxisName = Union[str, Sequence[str]]

# Reduce ops, mirroring deepspeed.comm.ReduceOp
class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


_INITIALIZED = False


def init_distributed(dist_backend: str = "xla",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     mesh_sizes: Optional[dict] = None,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     rank: int = -1,
                     world_size: int = -1,
                     **kwargs) -> MeshTopology:
    """Initialize multi-process JAX (if needed) and the global mesh topology.

    Ref: ``init_distributed`` (comm/comm.py:788).  On TPU pods each host
    calls ``jax.distributed.initialize``; env vars
    ``DSTPU_COORDINATOR/DSTPU_NUM_PROCS/DSTPU_PROC_ID`` (set by the
    launcher, analog of MASTER_ADDR/WORLD_SIZE/RANK) are used when arguments
    are absent.  Single-process use skips distributed init entirely.
    """
    global _INITIALIZED
    coordinator_address = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    if num_processes is None and os.environ.get("DSTPU_NUM_PROCS"):
        num_processes = int(os.environ["DSTPU_NUM_PROCS"])
    if process_id is None and os.environ.get("DSTPU_PROC_ID"):
        process_id = int(os.environ["DSTPU_PROC_ID"])

    if coordinator_address and num_processes and num_processes > 1 and not _INITIALIZED:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        logger.info(f"jax.distributed initialized: process {jax.process_index()}"
                    f"/{jax.process_count()} @ {coordinator_address}")
    _INITIALIZED = True

    topo = get_topology()
    if topo is None or mesh_sizes is not None:
        topo = MeshTopology(mesh_sizes)
        set_topology(topo)
    return topo


def is_initialized() -> bool:
    return _INITIALIZED


def _require_topology() -> MeshTopology:
    topo = get_topology()
    if topo is None:
        topo = init_distributed()
    return topo


# ----------------------------------------------------------------------
# Rank / world queries (ref comm.py get_rank/get_world_size)
# ----------------------------------------------------------------------
def get_world_size(group: Optional[AxisName] = None) -> int:
    topo = _require_topology()
    if group is None:
        return topo.world_size
    if isinstance(group, str):
        return topo.axis_size(group)
    size = 1
    for ax in group:
        size *= topo.axis_size(ax)
    return size


def get_rank(group: Optional[AxisName] = None) -> int:
    """Process rank (host-level). With ``group`` given, the rank is this
    process's coordinate along those mesh axes (row-major over the group),
    mirroring ``dist.get_rank(group=...)`` (ref comm/comm.py:636). Per-device
    coordinates inside jit come from ``lax.axis_index`` instead."""
    if group is None:
        return jax.process_index()
    import numpy as np

    topo = _require_topology()
    dev = jax.local_devices()[0]
    coords = np.argwhere(topo.mesh.devices == dev)
    if coords.size == 0:  # device not in mesh (e.g. probe backends)
        return jax.process_index()
    coord = dict(zip(topo.mesh.axis_names, coords[0]))
    axes = (group,) if isinstance(group, str) else tuple(group)
    rank = 0
    for ax in axes:
        rank = rank * topo.axis_size(ax) + int(coord[ax])
    return rank


def get_local_rank() -> int:
    """Rank within this host (ref dist.get_local_rank / LOCAL_RANK env).

    One process per host is the TPU norm (→ 0), but per-chip process
    layouts launched by the runner (hostfile slots, --num_procs_per_host)
    export LOCAL_RANK / DSTPU_LOCAL_RANK — honor them when present."""
    for var in ("DSTPU_LOCAL_RANK", "LOCAL_RANK"):
        v = os.environ.get(var)
        if v is not None:
            return int(v)
    return 0


# ----------------------------------------------------------------------
# In-jit collectives (call inside shard_map/pjit)
# ----------------------------------------------------------------------
def _log_op(name: str, x, axis: AxisName) -> None:
    cl = get_comms_logger()
    if cl.enabled:
        cl.record(name, x, axis)


def all_reduce(x, op: str = ReduceOp.SUM, group: AxisName = ZERO_AXES):
    """lax.psum/pmax/pmin over mesh axis(es). Ref: dist.all_reduce (comm.py:504)."""
    _log_op("all_reduce", x, group)
    if op == ReduceOp.SUM:
        return lax.psum(x, group)
    if op == ReduceOp.AVG:
        return lax.pmean(x, group)
    if op == ReduceOp.MAX:
        return lax.pmax(x, group)
    if op == ReduceOp.MIN:
        return lax.pmin(x, group)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, group: AxisName = ZERO_AXES, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis``. Ref: all_gather_into_tensor (comm.py:305)."""
    _log_op("all_gather", x, group)
    return lax.all_gather(x, group, axis=axis, tiled=tiled)


def reduce_scatter(x, group: AxisName = ZERO_AXES, axis: int = 0, op: str = ReduceOp.SUM):
    """Reduce then keep this rank's shard. Ref: reduce_scatter_tensor (comm.py:257)."""
    _log_op("reduce_scatter", x, group)
    out = lax.psum_scatter(x, group, scatter_dimension=axis, tiled=True)
    if op == ReduceOp.AVG:
        out = out / get_world_size(group)
    return out


def all_to_all(x, group: AxisName, split_axis: int, concat_axis: int, tiled: bool = True):
    """Ref: all_to_all_single (comm.py:380); Ulysses building block."""
    _log_op("all_to_all", x, group)
    return lax.all_to_all(x, group, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def broadcast(x, src: int = 0, group: AxisName = ZERO_AXES):
    """Everyone takes rank-``src``'s value (ref dist.broadcast, comm.py:224).

    Implemented as mask-and-psum: every rank except ``src`` contributes
    zeros, so the result is src's value everywhere. O(1) memory per rank —
    unlike an all_gather-and-index, which materialises world_size copies
    (the round-1 implementation; flagged in VERDICT)."""
    _log_op("broadcast", x, group)
    axes = (group,) if isinstance(group, str) else tuple(group)
    idx = lax.axis_index(axes[0] if len(axes) == 1 else axes)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, group)


def ppermute(x, perm, group: AxisName):
    """Point-to-point ring shift; the TPU-native replacement for the pipeline
    engine's P2P send/recv (ref runtime/pipe/p2p.py)."""
    _log_op("ppermute", x, group)
    return lax.ppermute(x, group, perm)


def axis_index(group: AxisName):
    return lax.axis_index(group)


# ----------------------------------------------------------------------
# Eager wrappers (setup / tests): run a collective on concrete arrays
# ----------------------------------------------------------------------
def _eager(fn, x, spec_in, spec_out):
    topo = _require_topology()
    mapped = jax.shard_map(fn, mesh=topo.mesh, in_specs=spec_in, out_specs=spec_out,
                           check_vma=False)
    return mapped(x)


def all_reduce_eager(x, op: str = ReduceOp.SUM, group: str = DATA_AXIS, shard_dim: int = 0):
    """Eager allreduce of an array sharded along ``shard_dim`` over ``group``."""
    spec = [None] * x.ndim
    spec[shard_dim] = group
    fn = functools.partial(all_reduce, op=op, group=group)
    return _eager(fn, x, P(*spec), P(*spec))


def barrier(group: Optional[AxisName] = None) -> None:
    """Host-level barrier. Ref: dist.barrier (comm.py:623)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("dstpu_barrier")


# DeepSpeed exposes these at package level; re-export-friendly aliases.
allreduce = all_reduce
allgather = all_gather
