"""Collectives façade.

TPU-native analog of ``deepspeed/comm/comm.py``: the same module-level API
(``init_distributed``, ``get_rank``, ``get_world_size``, ``all_reduce``,
``all_gather``, ``reduce_scatter``, ``all_to_all``, ``broadcast``,
``barrier``) but the backend is XLA collectives over mesh axes rather than
torch.distributed/NCCL.

Two modes:

* **In-jit** (the hot path): the ``all_reduce``-style functions take an
  ``axis_name`` (or use the default ZeRO axes) and lower to
  ``lax.psum/all_gather/psum_scatter/all_to_all``.  They must be called from
  inside ``shard_map``/``pjit`` tracing — the idiomatic TPU replacement for
  the reference's eager NCCL ops (SURVEY §2.2 note).
* **Eager** (setup/debug): ``all_reduce_eager`` etc. wrap the op in a
  one-shot ``shard_map`` over the global topology's mesh, so tests and setup
  code can reduce concrete arrays.

Per-op timing/logging mirrors ``timed_op``/``CommsLogger``
(ref comm/comm.py:102, utils/comms_logging.py:67).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DATA_AXIS, EXPERT_AXIS, MESH_AXES, SEQ_AXIS,
                                             TENSOR_AXIS, ZERO_AXES, MeshTopology,
                                             get_topology, set_topology)
from deepspeed_tpu.utils.comms_logging import get_comms_logger
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.jax_compat import axis_size, shard_map

AxisName = Union[str, Sequence[str]]

# Reduce ops, mirroring deepspeed.comm.ReduceOp
class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


_INITIALIZED = False


def init_distributed(dist_backend: str = "xla",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     mesh_sizes: Optional[dict] = None,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     rank: int = -1,
                     world_size: int = -1,
                     **kwargs) -> MeshTopology:
    """Initialize multi-process JAX (if needed) and the global mesh topology.

    Ref: ``init_distributed`` (comm/comm.py:788).  On TPU pods each host
    calls ``jax.distributed.initialize``; env vars
    ``DSTPU_COORDINATOR/DSTPU_NUM_PROCS/DSTPU_PROC_ID`` (set by the
    launcher, analog of MASTER_ADDR/WORLD_SIZE/RANK) are used when arguments
    are absent.  Single-process use skips distributed init entirely.
    """
    global _INITIALIZED
    coordinator_address = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    if num_processes is None and os.environ.get("DSTPU_NUM_PROCS"):
        num_processes = int(os.environ["DSTPU_NUM_PROCS"])
    if process_id is None and os.environ.get("DSTPU_PROC_ID"):
        process_id = int(os.environ["DSTPU_PROC_ID"])

    if coordinator_address and num_processes and num_processes > 1 and not _INITIALIZED:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        logger.info(f"jax.distributed initialized: process {jax.process_index()}"
                    f"/{jax.process_count()} @ {coordinator_address}")
    _INITIALIZED = True

    topo = get_topology()
    if topo is None or mesh_sizes is not None:
        topo = MeshTopology(mesh_sizes)
        set_topology(topo)
    return topo


def is_initialized() -> bool:
    return _INITIALIZED


def _require_topology() -> MeshTopology:
    topo = get_topology()
    if topo is None:
        topo = init_distributed()
    return topo


# ----------------------------------------------------------------------
# Rank / world queries (ref comm.py get_rank/get_world_size)
# ----------------------------------------------------------------------
def get_world_size(group: Optional[AxisName] = None) -> int:
    topo = _require_topology()
    if group is None:
        return topo.world_size
    if isinstance(group, str):
        return topo.axis_size(group)
    size = 1
    for ax in group:
        size *= topo.axis_size(ax)
    return size


def get_rank(group: Optional[AxisName] = None) -> int:
    """Process rank (host-level). With ``group`` given, the rank is this
    process's coordinate along those mesh axes (row-major over the group),
    mirroring ``dist.get_rank(group=...)`` (ref comm/comm.py:636). Per-device
    coordinates inside jit come from ``lax.axis_index`` instead."""
    if group is None:
        return jax.process_index()
    import numpy as np

    topo = _require_topology()
    dev = jax.local_devices()[0]
    coords = np.argwhere(topo.mesh.devices == dev)
    if coords.size == 0:  # device not in mesh (e.g. probe backends)
        return jax.process_index()
    coord = dict(zip(topo.mesh.axis_names, coords[0]))
    axes = (group,) if isinstance(group, str) else tuple(group)
    rank = 0
    for ax in axes:
        rank = rank * topo.axis_size(ax) + int(coord[ax])
    return rank


def get_local_rank() -> int:
    """Rank within this host (ref dist.get_local_rank / LOCAL_RANK env).

    One process per host is the TPU norm (→ 0), but per-chip process
    layouts launched by the runner (hostfile slots, --num_procs_per_host)
    export LOCAL_RANK / DSTPU_LOCAL_RANK — honor them when present."""
    for var in ("DSTPU_LOCAL_RANK", "LOCAL_RANK"):
        v = os.environ.get(var)
        if v is not None:
            return int(v)
    return 0


# ----------------------------------------------------------------------
# In-jit collectives (call inside shard_map/pjit)
# ----------------------------------------------------------------------
def _log_op(name: str, x, axis: AxisName) -> None:
    cl = get_comms_logger()
    if cl.enabled:
        cl.record(name, x, axis)


def all_reduce(x, op: str = ReduceOp.SUM, group: AxisName = ZERO_AXES):
    """lax.psum/pmax/pmin over mesh axis(es). Ref: dist.all_reduce (comm.py:504)."""
    _log_op("all_reduce", x, group)
    if op == ReduceOp.SUM:
        return lax.psum(x, group)
    if op == ReduceOp.AVG:
        return lax.pmean(x, group)
    if op == ReduceOp.MAX:
        return lax.pmax(x, group)
    if op == ReduceOp.MIN:
        return lax.pmin(x, group)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, group: AxisName = ZERO_AXES, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis``. Ref: all_gather_into_tensor (comm.py:305)."""
    _log_op("all_gather", x, group)
    return lax.all_gather(x, group, axis=axis, tiled=tiled)


def reduce_scatter(x, group: AxisName = ZERO_AXES, axis: int = 0, op: str = ReduceOp.SUM):
    """Reduce then keep this rank's shard. Ref: reduce_scatter_tensor (comm.py:257)."""
    _log_op("reduce_scatter", x, group)
    out = lax.psum_scatter(x, group, scatter_dimension=axis, tiled=True)
    if op == ReduceOp.AVG:
        out = out / get_world_size(group)
    return out


def all_to_all(x, group: AxisName, split_axis: int, concat_axis: int, tiled: bool = True):
    """Ref: all_to_all_single (comm.py:380); Ulysses building block."""
    _log_op("all_to_all", x, group)
    return lax.all_to_all(x, group, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def broadcast(x, src: int = 0, group: AxisName = ZERO_AXES):
    """Everyone takes rank-``src``'s value (ref dist.broadcast, comm.py:224).

    Implemented as mask-and-psum: every rank except ``src`` contributes
    zeros, so the result is src's value everywhere. O(1) memory per rank —
    unlike an all_gather-and-index, which materialises world_size copies
    (the round-1 implementation; flagged in VERDICT)."""
    _log_op("broadcast", x, group)
    axes = (group,) if isinstance(group, str) else tuple(group)
    idx = lax.axis_index(axes[0] if len(axes) == 1 else axes)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, group)


def ppermute(x, perm, group: AxisName):
    """Point-to-point ring shift; the TPU-native replacement for the pipeline
    engine's P2P send/recv (ref runtime/pipe/p2p.py)."""
    _log_op("ppermute", x, group)
    return lax.ppermute(x, group, perm)


def axis_index(group: AxisName):
    return lax.axis_index(group)


def send_recv(x, src: int, dst: int, group: AxisName):
    """One p2p edge src→dst (ref pipe p2p send/recv pair,
    runtime/pipe/p2p.py:46/67): rank ``dst`` returns rank ``src``'s value,
    everyone else zeros.  Under SPMD the reference's rank-local
    ``send``/``recv`` pair collapses into ONE collective permute whose
    edge set must be static — both endpoints are parameters."""
    _log_op("send_recv", x, group)
    return lax.ppermute(x, group, [(src, dst)])


def send(x, dst: int, group: AxisName, src: int = 0):
    """Reference-parity wrapper over :func:`send_recv` (ref dist.send,
    comm.py:369).  SPMD note: the matching receiver is part of the same
    compiled collective, so the source rank must be named too."""
    return send_recv(x, src, dst, group)


def recv(x, src: int, group: AxisName, dst: Optional[int] = None):
    """Reference-parity wrapper over :func:`send_recv` (ref dist.recv,
    comm.py:375); ``dst`` defaults to the next rank after ``src``."""
    if dst is None:
        dst = (src + 1) % get_world_size(group)
    return send_recv(x, src, dst, group)


def reduce(x, dst: int = 0, op: str = ReduceOp.SUM,
           group: AxisName = ZERO_AXES):
    """Reduce-to-root (ref dist.reduce, comm.py:591).  SPMD note: the
    reduction is an all-reduce — every rank holds the result, which is a
    superset of the reference's root-only contract."""
    return all_reduce(x, op=op, group=group)


def gather(x, dst: int = 0, group: AxisName = ZERO_AXES, axis: int = 0):
    """Gather-to-root (ref dist.gather, comm.py:393).  SPMD note: lowers
    to all-gather — every rank holds the concatenation."""
    return all_gather(x, group=group, axis=axis)


def scatter(x, src: int = 0, group: AxisName = ZERO_AXES, axis: int = 0):
    """Scatter from root (ref dist.scatter, comm.py:406): rank i takes
    slice i of rank-``src``'s tensor along ``axis``."""
    _log_op("scatter", x, group)
    full = broadcast(x, src=src, group=group)
    n = axis_size(group)
    if full.shape[axis] % n != 0:
        raise ValueError(
            f"scatter: axis {axis} (size {full.shape[axis]}) must divide "
            f"evenly over the {n}-rank group (ref dist.scatter requires "
            "equal chunks)")
    i = lax.axis_index(group)
    size = full.shape[axis] // n
    return lax.dynamic_slice_in_dim(full, i * size, size, axis=axis)


# ----------------------------------------------------------------------
# Eager wrappers (setup / tests): run a collective on concrete arrays
# ----------------------------------------------------------------------
def _eager(fn, x, spec_in, spec_out):
    topo = _require_topology()
    mapped = shard_map(fn, mesh=topo.mesh, in_specs=spec_in, out_specs=spec_out,
                           check_vma=False)
    return mapped(x)


def all_reduce_eager(x, op: str = ReduceOp.SUM, group: str = DATA_AXIS, shard_dim: int = 0):
    """Eager allreduce of an array sharded along ``shard_dim`` over ``group``."""
    spec = [None] * x.ndim
    spec[shard_dim] = group
    fn = functools.partial(all_reduce, op=op, group=group)
    return _eager(fn, x, P(*spec), P(*spec))


def barrier(group: Optional[AxisName] = None) -> None:
    """Host-level barrier. Ref: dist.barrier (comm.py:623)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("dstpu_barrier")


def monitored_barrier(group: Optional[AxisName] = None,
                      timeout: Optional[float] = None,
                      wait_all_ranks: bool = False) -> None:
    """Barrier that logs when the wait exceeds ``timeout`` seconds (ref
    dist.monitored_barrier, comm.py:425 — there it raises on straggler
    detection; the DCN sync here cannot attribute blame to a rank, so a
    breach is logged with this process's identity instead)."""
    import time as _time

    t0 = _time.perf_counter()
    barrier(group)
    waited = _time.perf_counter() - t0
    if timeout is not None and waited > timeout:
        from deepspeed_tpu.utils.logging import logger

        logger.warning(
            f"monitored_barrier: process {jax.process_index()} waited "
            f"{waited:.1f}s (> timeout {timeout:.1f}s) — straggler among "
            f"the other {jax.process_count() - 1} process(es)")


def broadcast_object_list(object_list: list, src: int = 0,
                          group=None, device=None) -> None:
    """In-place host-object broadcast across processes (ref
    dist.broadcast_object_list, comm.py:229): every process's
    ``object_list`` is overwritten with ``src``'s.  Rides the DCN via
    :func:`all_gather_object` — every process must call (see its
    transport note).  ``src`` is a GLOBAL rank, matching the reference:
    with ``group`` set it must be a member of the group and is mapped to
    its position in the group's rank tuple.  Single-process runs are the
    identity."""
    if jax.process_count() <= 1:
        return
    if group is not None:
        ranks = tuple(group)
        if src not in ranks:
            raise ValueError(
                f"broadcast_object_list: src={src} is a global rank and is "
                f"not a member of group {ranks}")
        src = ranks.index(src)
    object_list[:] = all_gather_object(list(object_list), group=group)[src]


def all_gather_object(obj, group=None) -> list:
    """Gather arbitrary picklable objects from every process (ref
    dist.all_gather_object, comm.py:247).  Pickle → padded uint8 rows →
    process_allgather → unpickle.

    TRANSPORT IS GLOBAL: every process must call (the DCN gather is a
    whole-job collective; an in-group-only call would hang).  ``group``
    (a :func:`new_group` rank tuple) only selects whose values are
    returned, in group-rank order."""
    if jax.process_count() <= 1:
        return [obj]
    import pickle

    import numpy as _np
    from jax.experimental import multihost_utils

    payload = _np.frombuffer(pickle.dumps(obj), dtype=_np.uint8)
    sizes = _np.asarray(multihost_utils.process_allgather(
        _np.asarray([payload.size], _np.int32))).reshape(-1)
    n = int(sizes.max())
    row = _np.zeros((n,), _np.uint8)
    row[:payload.size] = payload
    rows = _np.asarray(multihost_utils.process_allgather(row))
    rows = rows.reshape(jax.process_count(), n)
    members = range(jax.process_count()) if group is None else group
    return [pickle.loads(rows[i, :sizes[i]].tobytes()) for i in members]


def destroy_process_group(group=None) -> None:
    """Tear down distributed state (ref dist.destroy_process_group,
    comm.py:177): drop the cached topology and shut down jax.distributed
    when it was initialized."""
    from deepspeed_tpu.parallel import topology as _topo

    _topo._GLOBAL_TOPOLOGY = None
    try:
        jax.distributed.shutdown()
    except Exception:
        pass  # not initialized (single-process) — nothing to tear down


def new_group(ranks):
    """Ref dist.new_group (comm.py:182).  In-jit groups are mesh axes —
    construct the topology with the factorization you need and pass the
    axis name as ``group`` to the collectives.  The returned rank tuple is
    accepted by the host-object collectives as a RESULT FILTER only:
    their transport stays whole-job (every process must still call), and
    ``src`` arguments are GLOBAL ranks that must be group members
    (reference semantics — see :func:`broadcast_object_list`)."""
    return tuple(sorted(int(r) for r in ranks))


# DeepSpeed exposes these at package level; re-export-friendly aliases.
allreduce = all_reduce
allgather = all_gather
