"""Shared-memory host collectives (co-located launcher processes).

Python binding for ``csrc/shm_comm`` — the analog of the reference's
``CCLBackend`` SHM path (``deepspeed/comm/ccl.py`` → csrc/cpu/comm/shm.cpp):
host-side allreduce/broadcast/allgather/barrier between processes on one
machine without touching the network.  Used by the launcher/elasticity for
host coordination; device collectives stay XLA/ICI.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import OpBuilderError, load_op
from deepspeed_tpu.utils.logging import logger

_LIB = None
_LIB_FAILED = False


def _lib():
    global _LIB, _LIB_FAILED
    if _LIB is None and not _LIB_FAILED:
        try:
            # -lrt: on glibc < 2.34 shm_open lives in librt, and without
            # the explicit link the .so only dlopens when some OTHER
            # module already pulled librt in globally (order-dependent
            # test failures); glibc >= 2.34 keeps librt as a stub, so the
            # flag is harmless there
            lib = load_op("ds_shm_comm", ["shm_comm/shm_comm.cpp"],
                          extra_flags=["-lrt"])
            lib.ds_shm_create.restype = ctypes.c_void_p
            lib.ds_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_int64,
                                          ctypes.c_uint64, ctypes.c_int64]
            f32 = ctypes.POINTER(ctypes.c_float)
            lib.ds_shm_allreduce.restype = ctypes.c_int
            lib.ds_shm_allreduce.argtypes = [ctypes.c_void_p, f32,
                                             ctypes.c_int64]
            lib.ds_shm_broadcast.restype = ctypes.c_int
            lib.ds_shm_broadcast.argtypes = [ctypes.c_void_p, f32,
                                             ctypes.c_int64, ctypes.c_int]
            lib.ds_shm_allgather.restype = ctypes.c_int
            lib.ds_shm_allgather.argtypes = [ctypes.c_void_p, f32,
                                             ctypes.c_int64, f32]
            lib.ds_shm_barrier.argtypes = [ctypes.c_void_p]
            lib.ds_shm_destroy.argtypes = [ctypes.c_void_p, ctypes.c_int]
            _LIB = lib
        except OpBuilderError as e:
            logger.warning(f"shm comm unavailable: {e}")
            _LIB_FAILED = True
    return _LIB


def shm_available() -> bool:
    return _lib() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class ShmComm:
    """Process group over POSIX shared memory (same-host ranks)."""

    def __init__(self, name: str, rank: int, world: int,
                 max_elems: int = 1 << 20, nonce: Optional[int] = None,
                 timeout_s: float = 60.0):
        lib = _lib()
        if lib is None:
            raise RuntimeError("shm comm native op unavailable")
        self._lib = lib
        self.rank = rank
        self.world = world
        # namespace per user+name so stale regions don't collide
        shm_name = f"/dstpu_{os.environ.get('USER', 'u')}_{name}"
        # all ranks of one run must agree on the nonce, and it must differ
        # from a crashed previous run's: the launcher exports one per job.
        # Fallback for co-spawned workers: parent pid mixed with the
        # parent's start time (stable across ranks, differs when the parent
        # pid is recycled).  Caveat: a supervisor that respawns an
        # identical job keeps the same parent — such setups must provide
        # DSTPU_SHM_NONCE (or nonce=) for full stale-region safety.
        if nonce is None:
            env = os.environ.get("DSTPU_SHM_NONCE")
            if env is not None:
                nonce = int(env)
            else:
                nonce = os.getppid()
                try:
                    with open(f"/proc/{nonce}/stat", "rb") as f:
                        starttime = int(f.read().rsplit(b") ", 1)[1].split()[19])
                    nonce = (starttime << 22) | nonce
                except (OSError, IndexError, ValueError):
                    pass
        self.nonce = nonce & 0xFFFFFFFFFFFFFFFF
        if self.nonce == 0:
            self.nonce = 1  # 0 is the in-progress-init sentinel
        self._h = lib.ds_shm_create(shm_name.encode(), rank, world,
                                    max_elems * 4, self.nonce,
                                    int(timeout_s * 1e6))
        if not self._h:
            if rank == 0:
                raise RuntimeError(
                    f"shm init failed for {shm_name}: could not create/map "
                    f"the shared-memory region (is /dev/shm writable and "
                    f"large enough?)")
            raise RuntimeError(
                f"shm init failed for {shm_name} (rank {rank}/{world}): "
                f"rank 0 never published nonce {self.nonce} — if ranks are "
                f"spawned from different parents, set DSTPU_SHM_NONCE to a "
                f"shared per-job value")

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        arr = np.ascontiguousarray(arr, np.float32)
        if self._lib.ds_shm_allreduce(self._h, _ptr(arr), arr.size) != 0:
            raise ValueError("payload exceeds shm slot size")
        return arr

    def broadcast(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        arr = np.ascontiguousarray(arr, np.float32)
        if self._lib.ds_shm_broadcast(self._h, _ptr(arr), arr.size, root) != 0:
            raise ValueError("payload exceeds shm slot size")
        return arr

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        arr = np.ascontiguousarray(arr, np.float32)
        out = np.empty((self.world,) + arr.shape, np.float32)
        if self._lib.ds_shm_allgather(self._h, _ptr(arr), arr.size,
                                      _ptr(out)) != 0:
            raise ValueError("payload exceeds shm slot size")
        return out

    def barrier(self) -> None:
        self._lib.ds_shm_barrier(self._h)

    def close(self, unlink: Optional[bool] = None) -> None:
        if self._h:
            self._lib.ds_shm_destroy(
                self._h, 1 if (unlink if unlink is not None
                               else self.rank == 0) else 0)
            self._h = None
