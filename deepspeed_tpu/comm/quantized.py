"""Quantized ZeRO collectives — block-scaled gradient reduce-scatter /
all-reduce with a selectable wire dtype.

EQuARX-style (arXiv:2506.17615) in-program quantized collectives that a
plain Adam + ZeRO-1/2 data-parallel run can turn on, generalising the
machinery that previously lived only inside the Onebit optimizers
(``comm/compressed.py``) and the qgZ all-to-all
(``comm/coalesced_collectives.py``):

* **reduce-scatter**: chunk the flat gradient buffer into ``world``
  pieces, block-quantize each chunk (fp32 per-block scales), all-to-all
  the quantized payload + scales, dequantize and reduce **in fp32**.
  Wire traffic is the quantized dtype; accumulation never is.
* **all-reduce**: reduce-scatter, then re-quantize the reduced shard and
  all-gather it (the EQuARX two-phase schedule — both phases move the
  quantized payload).
* **error feedback**: optionally carry the first-send quantization
  residual into the next step (LoCo-style; the gather-phase requantize
  error is NOT compensated — same contract as LoCo/qgZ).

Wire dtypes:
  ``fp32``  — no quantization; the *explicit* collective still runs and
              logs its volume, giving an apples-to-apples telemetry
              baseline for the quantized modes.
  ``int8``  — blockwise symmetric int8 (ops/quantizer).
  ``fp8``   — float8_e4m3fn with fp32 per-block scales; the payload is
              bitcast to uint8 for the collective itself so every
              backend (including the CPU test mesh) moves plain bytes.

All functions are **in-jit** collectives over flat fp32 buffers: call
them inside ``shard_map`` (the engine's explicit-reduce path does) with
the relevant mesh axis names.  Comm volume is recorded at trace time in
the process ``CommsLogger`` under the frozen :data:`QUANT_COMM_OPS`
names, so per-collective byte reduction shows up directly in the
telemetry ``StepRecord.comm`` field (docs/QUANTIZED_COMM.md).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.ops.quantizer import dequantize_blockwise, quantize_blockwise
from deepspeed_tpu.utils.comms_logging import get_comms_logger

AxisName = Union[str, Sequence[str]]

# Wire dtypes a comm_quantization config block may select per collective.
WIRE_DTYPES = ("fp32", "int8", "fp8")

# Frozen comm-op vocabulary (linted against docs/QUANTIZED_COMM.md by
# tools/telemetry_check.py, same contract as the StepRecord schema):
# every wire movement of the quantized collectives is recorded under one
# of these names in CommsLogger — payload and scales both.
QUANT_COMM_OPS = ("quant_reduce_scatter", "quant_all_gather")

# float8_e4m3fn: absent on ancient jax builds; gate instead of crashing.
_FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)
_FP8_MAX = 448.0  # e4m3fn finite max


def wire_encode_rows(x, wire_dtype: str):
    """Encode a ``[..., d]`` buffer for the wire with ONE fp32 scale per
    trailing-dim row (the quantization block IS the trailing dim — the
    layout the ring rotation and the flash dequant epilogue share).

    Returns ``(payload, scale)``: payload has ``x``'s shape (int8, or fp8
    bitcast to uint8), ``scale`` is fp32 ``x.shape[:-1] + (1,)``; both are
    ``(x, None)`` for fp32.  Always routes the jnp codec so GSPMD/manual
    call sites partition it freely (same reasoning as qwz_weight_gather's
    backend="jnp").
    """
    if wire_dtype == "fp32":
        return x, None
    d = x.shape[-1]
    x2 = x.astype(jnp.float32).reshape(-1, d)
    payload, scale = _wire_encode(x2, wire_dtype, d, backend="jnp")
    return (payload.reshape(x.shape),
            scale.reshape(x.shape[:-1] + (1,)))


def wire_decode_rows(payload, scale, wire_dtype: str):
    """Inverse of :func:`wire_encode_rows`; always returns fp32.  The
    int8 branch is element-for-element the multiply the Pallas flash
    epilogue performs (``ops/pallas/flash_mha.wire_dequant_rows``), so
    the kernel and XLA wire codecs are the same arithmetic — pinned by
    the codec-parity test in tests/test_fused_collectives.py."""
    if wire_dtype == "fp32":
        return payload
    d = payload.shape[-1]
    out = _wire_decode(payload.reshape(-1, d),
                       scale.reshape(-1, 1), wire_dtype, backend="jnp")
    return out.reshape(payload.shape)


def fp8_supported() -> bool:
    return _FP8_DTYPE is not None


def validate_wire_dtype(name: str) -> str:
    if name not in WIRE_DTYPES:
        raise ValueError(
            f"wire dtype {name!r} not in {WIRE_DTYPES}")
    if name == "fp8" and not fp8_supported():
        raise ValueError("wire dtype 'fp8' requires jnp.float8_e4m3fn, "
                         "which this jax build lacks")
    return name


def _log_wire(op: str, payload, scale, axis) -> None:
    """Trace-time comm-volume record of what actually travels the wire
    (payload and, for quantized dtypes, the fp32 scales)."""
    cl = get_comms_logger()
    if not cl.enabled:
        return
    cl.record(op, payload, axis)
    if scale is not None:
        cl.record(op, scale, axis)


def _block(m: int, group_size: int) -> int:
    gs = min(group_size, m) if group_size > 0 else m
    if m % gs:
        gs = m
    return gs


def _wire_encode(x2d: jnp.ndarray, wire_dtype: str, group_size: int,
                 backend: str = "auto", num_bits: int = 8
                 ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Encode last-dim blocks of an fp32 buffer for the wire.

    Returns ``(payload, scales)``; ``scales`` is None for fp32.  The fp8
    payload is bitcast to uint8 so the collective moves plain bytes on
    every backend.  ``backend`` routes the int8 quantizer ("jnp" is
    load-bearing for GSPMD call sites — see qwz_weight_gather);
    ``num_bits`` narrows the integer wire format (int4 values ride the
    int8 payload's low nibble range) and is ignored for fp8/fp32.
    """
    if wire_dtype == "fp32":
        return x2d, None
    m = x2d.shape[-1]
    gs = _block(m, group_size)
    if wire_dtype == "int8":
        q, scale, _ = quantize_blockwise(x2d, num_bits=num_bits,
                                         group_size=gs, backend=backend)
        return q, scale
    if _FP8_DTYPE is None:
        raise ValueError("fp8 wire dtype unavailable on this jax build")
    g = x2d.reshape(x2d.shape[:-1] + (m // gs, gs))
    absmax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    scale = absmax / _FP8_MAX
    scale = jnp.where(scale == 0, 1.0, scale)
    q = (g / scale).astype(_FP8_DTYPE).reshape(x2d.shape)
    return lax.bitcast_convert_type(q, jnp.uint8), scale.squeeze(-1)


def _wire_decode(payload: jnp.ndarray, scale: Optional[jnp.ndarray],
                 wire_dtype: str, backend: str = "auto") -> jnp.ndarray:
    """Inverse of :func:`_wire_encode`; always returns fp32."""
    if wire_dtype == "fp32":
        return payload
    if wire_dtype == "int8":
        return dequantize_blockwise(payload, scale, backend=backend)
    f8 = lax.bitcast_convert_type(payload, _FP8_DTYPE)
    m = f8.shape[-1]
    gs = m // scale.shape[-1]
    g = f8.astype(jnp.float32).reshape(f8.shape[:-1] + (scale.shape[-1], gs))
    return (g * scale[..., None]).reshape(f8.shape)


def quantized_reduce_scatter(x: jnp.ndarray, axis: AxisName, world: int,
                             wire_dtype: str = "int8", group_size: int = 256,
                             residual: Optional[jnp.ndarray] = None,
                             mean: bool = True
                             ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Block-scaled quantized reduce-scatter of flat ``x`` [N] (N divisible
    by ``world``): quantize → all-to-all → fp32 dequant-reduce.

    Rank r returns its [N/world] reduced chunk.  ``residual`` (same shape
    as ``x``) enables error feedback: it is folded into the send and the
    new first-send quantization residual is returned (None when no
    residual was passed).  ``mean`` divides by ``world`` (gradient
    averaging); ``False`` leaves the sum.
    """
    n = x.size
    if n % world:
        raise ValueError(f"buffer size {n} not divisible by world {world}")
    m = n // world
    c = x + residual if residual is not None else x
    chunks = c.reshape(world, m)
    payload, scale = _wire_encode(chunks, wire_dtype, group_size)
    _log_wire("quant_reduce_scatter", payload, scale, axis)
    new_residual = None
    if residual is not None:
        sent = _wire_decode(payload, scale, wire_dtype).reshape(-1)
        new_residual = c - sent
    # rank r receives chunk r from every rank: [world, m], rows = src rank
    p_t = lax.all_to_all(payload, axis, split_axis=0, concat_axis=0,
                         tiled=True)
    s_t = None
    if scale is not None:
        s_t = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0,
                             tiled=True)
    deq = _wire_decode(p_t, s_t, wire_dtype)
    red = jnp.mean(deq, axis=0) if mean else jnp.sum(deq, axis=0)
    return red, new_residual


def quantized_all_reduce(x: jnp.ndarray, axis: AxisName, world: int,
                         wire_dtype: str = "int8", group_size: int = 256,
                         residual: Optional[jnp.ndarray] = None,
                         mean: bool = True
                         ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Two-phase quantized all-reduce (EQuARX schedule): quantized
    reduce-scatter, then re-quantize the reduced shard and all-gather it.
    Both phases move the quantized payload; reduction stays fp32.

    Returns ``(out [N], new_residual or None)``.  Error feedback covers
    the reduce-scatter send only (the gather-phase requantize error is
    uncompensated, like LoCo/qgZ).
    """
    shard, new_residual = quantized_reduce_scatter(
        x, axis, world, wire_dtype=wire_dtype, group_size=group_size,
        residual=residual, mean=mean)
    payload, scale = _wire_encode(shard[None, :], wire_dtype, group_size)
    _log_wire("quant_all_gather", payload, scale, axis)
    g = lax.all_gather(payload[0], axis, axis=0, tiled=True)
    m = shard.size
    if scale is not None:
        s = lax.all_gather(scale[0], axis, axis=0, tiled=True)
        s = s.reshape(world, -1)
    else:
        s = None
    out = _wire_decode(g.reshape(world, m), s, wire_dtype)
    return out.reshape(-1), new_residual
