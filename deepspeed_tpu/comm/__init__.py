"""deepspeed_tpu.comm — collectives façade (ref: deepspeed/comm)."""

from deepspeed_tpu.comm.quantized import (QUANT_COMM_OPS, WIRE_DTYPES,
                                          quantized_all_reduce,
                                          quantized_reduce_scatter)
from deepspeed_tpu.comm.comm import (ReduceOp, all_gather, all_gather_object,
                                     all_reduce, all_to_all, allgather,
                                     allreduce, axis_index, barrier, broadcast,
                                     broadcast_object_list,
                                     destroy_process_group, gather,
                                     get_local_rank, get_rank, get_world_size,
                                     init_distributed, is_initialized,
                                     monitored_barrier, new_group, ppermute,
                                     recv, reduce, reduce_scatter, scatter,
                                     send, send_recv)
