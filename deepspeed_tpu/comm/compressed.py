"""1-bit compressed allreduce with error feedback.

TPU-native analog of the reference's compressed backends
(``runtime/comm/compressed.py`` CompressedBackend:13, ``runtime/comm/nccl.py``
NcclBackend:16, ``runtime/comm/mpi.py``): the error-feedback sign-SGD
compression used by 1-bit Adam / 1-bit LAMB / 0/1-Adam.

Algorithm (ref compressed_allreduce): with per-worker error e and server
error s over a flat buffer c = x + e:

1. chunk c into world pieces; per-chunk scale = mean|chunk|; sign-compress;
   worker error ← c − decompress(sent).
2. all-to-all the compressed chunks (sign bits + scales on the wire — int8
   here; the reference packs to real bits via packbits, 8× vs our 4×... we
   pack signs of 8 elements per byte below for the same 32× total).
3. each rank averages its received chunk, adds server error, compresses
   again; server error ← residual.
4. all-gather the compressed server chunks; decompress → averaged result.

In-jit: call inside ``shard_map`` over the data axis. State (worker/server
error) is per-rank: the engine stores it as arrays with a leading
``[world]`` axis sharded over the same mesh axis.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def pack_signs(sign01: jnp.ndarray) -> jnp.ndarray:
    """Pack {0,1} sign bits, 8 per byte (ref csrc/xpu/packbits analog).

    Lengths not divisible by 8 are zero-padded internally — the true
    length travels with the caller (``_decompress`` slices ``[..., :n]``),
    so arbitrary flat buffers compress.  ``unpack_signs`` returns the
    padded length (a whole number of bytes); callers slice back."""
    n = sign01.shape[-1]
    pad = (-n) % 8
    if pad:
        widths = [(0, 0)] * (sign01.ndim - 1) + [(0, pad)]
        sign01 = jnp.pad(sign01, widths)
        n += pad
    b = sign01.reshape(sign01.shape[:-1] + (n // 8, 8)).astype(jnp.uint8)
    weights = (1 << jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(b * weights, axis=-1).astype(jnp.uint8)


def unpack_signs(packed: jnp.ndarray) -> jnp.ndarray:
    bits = (packed[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return bits.reshape(packed.shape[:-1] + (packed.shape[-1] * 8,))


def _compress(c: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """sign + L1 scale per row; returns (packed bits, scale)."""
    scale = jnp.mean(jnp.abs(c), axis=-1)
    bits = pack_signs((c >= 0).astype(jnp.uint8))
    return bits, scale


def _decompress(bits: jnp.ndarray, scale: jnp.ndarray, n: int) -> jnp.ndarray:
    sign = unpack_signs(bits)[..., :n].astype(jnp.float32) * 2.0 - 1.0
    return sign * scale[..., None]


def compressed_allreduce(x: jnp.ndarray, worker_err: jnp.ndarray,
                         server_err: jnp.ndarray, axis: AxisName,
                         world: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Error-feedback 1-bit mean-allreduce of flat ``x`` (≡ ref
    CompressedBackend.compressed_allreduce, runtime/comm/compressed.py:13).

    ``x`` [N] with N divisible by ``world`` (chunks of any length
    compress — pack_signs pads to whole bytes internally and the true
    length rides through ``_decompress``); ``worker_err`` [N];
    ``server_err`` [N/world].  Returns (avg, new_worker_err, new_server_err).
    """
    n = x.size
    if n % world:
        raise ValueError(f"buffer size {n} not divisible by world {world}")
    m = n // world
    c = x + worker_err

    chunks = c.reshape(world, m)
    bits, scales = _compress(chunks)
    new_worker_err = c - _decompress(bits, scales, m).reshape(-1)

    # exchange compressed chunks: rank r receives chunk r from every rank
    bits_t = lax.all_to_all(bits, axis, split_axis=0, concat_axis=0, tiled=True)
    scales_t = lax.all_to_all(scales, axis, split_axis=0, concat_axis=0, tiled=True)
    recv = _decompress(bits_t.reshape(world, -(-m // 8)),
                       scales_t.reshape(world), m)

    server_chunk = jnp.mean(recv, axis=0) + server_err
    s_bits, s_scale = _compress(server_chunk[None, :])
    new_server_err = server_chunk - _decompress(s_bits, s_scale, m)[0]

    # gather everyone's compressed server chunk
    g_bits = lax.all_gather(s_bits[0], axis, axis=0, tiled=False)
    g_scale = lax.all_gather(s_scale, axis, axis=0, tiled=False)
    out = _decompress(g_bits, g_scale.reshape(world), m).reshape(-1)[:n]
    return out, new_worker_err, new_server_err


class CompressedBackend:
    """Object façade matching the reference's backend classes; holds sizes
    and exposes ``compressed_allreduce`` bound to a mesh axis."""

    def __init__(self, axis: AxisName, world: int):
        self.axis = axis
        self.world = world
        self.size = world

    def compressed_allreduce(self, x, worker_err, server_err):
        return compressed_allreduce(x, worker_err, server_err, self.axis, self.world)
