"""Disaggregated serving: prefill/decode replica tiers + speculation.

The homogeneous :class:`~.router.Router` treats replicas as
interchangeable, but the two phases of a generation live in different
roofline regimes: prefill is compute-bound (one big ragged batch over
the prompt), decode is HBM-bandwidth-bound (one token per sequence per
step, the KV cache streaming past the MXU).  At fleet scale they fight
for the same chips — the reference stack's MII/FastGen layer specializes
the fleet instead, and splitting the pools is a placement decision in
the sense of arXiv:2601.02311: different regimes deserve different
replica shapes, admission policies, and routing scores.

This module turns the replica tier into that fleet:

* **Tiers.**  ``ReplicaSet.build(..., disagg=...)`` splits the set into
  a *prefill tier* and a *decode tier* on disjoint device slices; the
  :class:`DisaggRouter` scores prefill legs by compute queue depth and
  decode legs by evictable KV headroom
  (``AdmissionController.evictable_headroom``), and falls back across
  tiers — a leg that finds no live replica in its tier re-runs on a
  unified (or any surviving) replica.

* **KV-block handoff.**  A prefill replica runs ``prompt → first
  token`` with ``handoff=True``: at completion the serve loop exports
  the sequence's FULL KV pages (``engine.export_kv_chain``) onto the
  stream.  The router then submits ``prompt + first_token`` to a decode
  replica with the payload attached; admission adopts it through the
  refcounted allocator — the same chain-keyed identity the prefix cache
  uses, so when the decode replica's cache already holds the chain the
  handoff is a **zero-copy ref acquire**, and otherwise only the
  uncovered tail moves as an explicit device-to-device block transfer
  (``handoff_ms``/``handoff_bytes`` are measured per request).  Both
  sides share the same-seed weight contract, so the decode continuation
  is bit-identical to a single-replica run — and a replica killed
  mid-handoff degrades to the ordinary fail-over recompute.

* **Speculative decoding.**  A small draft model lives in the decode
  replica's serve loop (:class:`SpeculativeDecoder`): it proposes up to
  ``spec_k`` greedy tokens per sequence, the target verifies the whole
  batch of proposals in ONE ragged verify-k step
  (``engine.verify_step``), and acceptance is **bit-identical to
  greedy** — every emitted token is the target's own argmax after its
  prefix, the draft only decides how many land per dispatch.  Opt-in is
  per request (``SamplingParams(speculative=True)``).

Like the rest of ``serving/``, this module imports no jax at module
scope — engines are built by ``ReplicaSet.build``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from deepspeed_tpu.serving.request import (DeadlineExceeded,
                                           GenerationRequest, ServingError)
from deepspeed_tpu.serving.router import _RETRY, Router, _RoutedRequest
from deepspeed_tpu.utils.logging import log_dist

#: replica tier vocabulary (ServingReplica.tier)
REPLICA_TIERS = ("prefill", "decode", "unified")

#: frozen key set of one RequestTimeline row — the per-request phase
#: breakdown the DisaggRouter stamps onto ``stream.timeline`` at finish
#: and keeps in its bounded ring (``DisaggRouter.timelines()``); linted
#: by tools/telemetry_check.py against docs/OBSERVABILITY.md
REQUEST_TIMELINE_KEYS = ("decode_ms", "failovers", "handoff_bytes",
                        "handoff_ms", "prefill_ms", "total_ms",
                        "trace_id", "uid")

#: RequestTimeline ring bound (oldest dropped)
_TIMELINE_RING = 1024


class SpeculativeConfig:
    """``serving.disagg.speculative`` block, serving-side parser."""

    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        self.enabled = bool(d.get("enabled", False))
        # models.get_model_config name (or a TransformerConfig passed
        # programmatically) for the draft; must share the target's
        # tokenizer/vocab — the proposals are target-vocabulary ids
        self.draft_model = d.get("draft_model", "")
        self.spec_k = int(d.get("spec_k", 4))
        if self.spec_k < 1:
            raise ValueError(f"speculative.spec_k={self.spec_k}: "
                             "must be >= 1")
        if self.enabled and not self.draft_model:
            raise ValueError("speculative.enabled requires a draft_model")


class DisaggConfig:
    """``serving.disagg`` block, serving-side parser (the runtime-config
    twin, ``runtime.config.DisaggServingConfig``, round-trips through
    this class at validation — the PR 9 drift tripwire)."""

    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        self.enabled = bool(d.get("enabled", False))
        self.prefill_replicas = int(d.get("prefill_replicas", 1))
        self.decode_replicas = int(d.get("decode_replicas", 1))
        spec = d.get("speculative", {})
        self.speculative = (spec if isinstance(spec, SpeculativeConfig)
                            else SpeculativeConfig(spec))
        if self.enabled:
            if self.prefill_replicas < 1 or self.decode_replicas < 1:
                raise ValueError(
                    f"disagg tiers need >= 1 replica each, got prefill="
                    f"{self.prefill_replicas} decode={self.decode_replicas}")

    @property
    def n_replicas(self) -> int:
        return self.prefill_replicas + self.decode_replicas

    def tier_of(self, index: int) -> str:
        return "prefill" if index < self.prefill_replicas else "decode"


class SpeculativeDecoder:
    """Draft-propose / target-verify speculation inside one serve loop.

    The draft is a full :class:`InferenceEngineV2` (small model, same
    device slice) whose sequences MIRROR the target's: each round it
    greedily proposes up to ``spec_k`` tokens per sequence, the target
    scores every proposal in one ragged ``verify_step``, and the draft
    is rewound to the accepted stream (its KV rows for rejected
    positions are dead weight that the re-run overwrites — same
    position-addressed contract as the target's own rewind).  The
    mirror is self-healing: a missing or diverged draft sequence is
    flushed and re-admitted (a cheap draft-model re-prefill), so draft
    KV exhaustion, preemption, and fail-over all degrade to plain
    greedy decoding rather than to an error.
    """

    def __init__(self, target: Any, draft: Any, spec_k: int = 4):
        self.target = target
        self.draft = draft
        self.spec_k = int(spec_k)
        self.tracer = None
        self.trace_id = ""
        self.metrics = None

    def bind(self, tracer, trace_id: str, metrics) -> None:
        """Called by the owning server at start(): spans + accept-rate
        counters land in its trace/registry."""
        self.tracer = tracer
        self.trace_id = trace_id
        self.metrics = metrics

    # -- serve-loop API (the engine-owning thread only) -----------------
    def flush(self, uid: int) -> None:
        """Drop a draft mirror (target finished/preempted/failed)."""
        if uid in self.draft.state_manager:
            self.draft.flush(uid)

    def round(self, active: Dict[int, GenerationRequest]
              ) -> Dict[int, List[int]]:
        """One speculative round for the whole active set.

        Every request must be greedy/speculative with exactly one
        pending sampled token (the server's ``_spec_eligible`` gate).
        Returns ``{uid: accepted_tokens}`` (each >= 1 token, the burst
        the serve loop fans out); the target sequences already carry
        them.  Raises ``KVCacheExhausted`` only for TARGET pressure —
        draft pressure degrades to fewer (or zero) proposals.
        """
        tr = self.tracer
        uids = list(active)
        budget = self.target.scheduler.token_budget
        k_cap = max(0, budget // max(1, len(uids)) - 1)
        want = {uid: min(self.spec_k, k_cap,
                         max(0, active[uid].remaining - 1))
                for uid in uids}
        sp = (tr.span("spec.draft", self.trace_id) if tr is not None
              and tr.enabled else None)
        proposals = self._propose(uids, want)
        if sp is not None:
            sp.end(n_seqs=len(uids),
                   proposed=sum(len(p) for p in proposals.values()))
        sp = (tr.span("spec.verify", self.trace_id) if tr is not None
              and tr.enabled else None)
        try:
            accepted = self.target.verify_step(proposals)
        except BaseException:
            # target rolled back to the pre-round state; the draft
            # mirrors consumed proposals the target never saw — drop
            # them and re-admit lazily next round
            for uid in uids:
                self.flush(uid)
            if sp is not None:
                sp.end(kv_exhausted=True)
            raise
        n_prop = sum(len(p) for p in proposals.values())
        n_acc = sum(len(a) - 1 for a in accepted.values())
        if sp is not None:
            sp.end(proposed=n_prop, accepted=n_acc)
            tr.instant("spec.accept", self.trace_id, proposed=n_prop,
                       accepted=n_acc)
        if self.metrics is not None:
            self.metrics.record_spec_round(n_prop, n_acc)
        self._rewind_drafts(uids, proposals, accepted)
        return accepted

    # -- internals ------------------------------------------------------
    def _propose(self, uids: Sequence[int],
                 want: Dict[int, int]) -> Dict[int, List[int]]:
        """Greedy draft proposals, ``want[uid]`` tokens each.  A fresh
        (or diverged) mirror is re-admitted first and catches up through
        the draft's own chunked prefill; its completing step yields its
        first proposal.  Sequences done proposing idle (uncached 0) —
        the scheduler skips them — while slower peers finish."""
        from deepspeed_tpu.inference.v2.ragged import KVCacheExhausted

        mgr = self.draft.state_manager
        for uid in uids:
            seq_t = self.target.state_manager.get(uid)
            if uid in mgr:
                if list(mgr.get(uid).tokens) != list(seq_t.tokens):
                    self.draft.flush(uid)      # diverged: self-heal
            if uid not in mgr:
                try:
                    self.draft.admit(uid, list(seq_t.tokens))
                except (KVCacheExhausted, RuntimeError):
                    continue   # no draft room: propose nothing this round
        proposals: Dict[int, List[int]] = {u: [] for u in uids}
        max_iters = max(list(want.values()) or [0]) + 8
        for _ in range(max_iters):
            if all(len(proposals[u]) >= want[u] or u not in mgr
                   for u in uids):
                break
            try:
                out = self.draft.step(temperature=0.0)
            except KVCacheExhausted:
                # draft pool pressure: free EVERYTHING (mirrors rebuild
                # lazily) and run with the proposals gathered so far
                for uid in list(uids):
                    self.flush(uid)
                log_dist("speculative: draft KV exhausted; degrading to "
                         "plain greedy this round", level="warning")
                break
            if not out and not self.draft.scheduler.has_work:
                break
            for uid, tok in out.items():
                if uid in proposals and len(proposals[uid]) < want[uid]:
                    proposals[uid].append(int(tok))
                    if len(proposals[uid]) < want[uid]:
                        self.draft.extend(uid, int(tok))
        return proposals

    def _rewind_drafts(self, uids, proposals, accepted) -> None:
        """Align every draft mirror with the target's post-verify stream:
        the draft's KV is valid up to the longest common prefix of what
        it consumed (its own proposals) and what the target accepted."""
        mgr = self.draft.state_manager
        for uid in uids:
            if uid not in mgr:
                continue
            acc = accepted.get(uid)
            if acc is None:
                continue
            m = len(acc) - 1           # accepted proposals (sans bonus)
            seq_t = self.target.state_manager.get(uid)
            dseq = mgr.get(uid)
            base = len(seq_t.tokens) - len(acc)   # pre-round stream len
            self.draft.rewind(uid, list(seq_t.tokens),
                              num_cached=min(dseq.num_cached, base + m))


class DisaggRouter(Router):
    """Tier-aware router: prefill leg → KV handoff → decode leg.

    The ``submit()/generate()`` surface is unchanged.  Each request runs
    a **prefill leg** (``max_new_tokens=1`` + ``handoff=True`` on the
    prefill tier — TTFT is paid where the compute is) and, unless one
    token was all it wanted, a **decode leg** on the decode tier whose
    admission adopts the exported KV chain.  Fail-over is per leg and
    tier-local first: a dead prefill replica's leg re-runs on another
    prefill (or any surviving) replica, a dead decode replica's leg
    re-submits prompt+delivered WITH the payload (the chain is still a
    prefix of the stream), and when a tier is empty the other tier's
    replicas serve as unified stand-ins re-running prefill — greedy
    continuations stay bit-identical throughout.
    """

    def __init__(self, replicas, config: Optional[dict] = None,
                 telemetry=None):
        super().__init__(replicas, config, telemetry)
        tiers = {r.tier for r in replicas}
        if "prefill" not in tiers or "decode" not in tiers:
            raise ValueError(
                "DisaggRouter needs at least one prefill-tier and one "
                f"decode-tier replica (got tiers {sorted(tiers)}); build "
                "the ReplicaSet with disagg={'enabled': True, ...}")
        # finished-request phase breakdowns (REQUEST_TIMELINE_KEYS),
        # newest last; appended under self._lock by the pump threads
        self._timelines: deque = deque(maxlen=_TIMELINE_RING)
        # degraded homogeneous mode: True while a whole tier is gone
        # (fleet supervisor actuation) — requests run ONE full leg on
        # any survivor instead of the prefill→handoff→decode split
        self._collapsed = False

    def timelines(self) -> List[Dict[str, Any]]:
        """Recent per-request phase timelines (oldest first) — each row
        carries exactly :data:`REQUEST_TIMELINE_KEYS`."""
        with self._lock:
            return list(self._timelines)

    # -- degraded homogeneous mode --------------------------------------
    @property
    def collapsed(self) -> bool:
        with self._lock:
            return self._collapsed

    def collapse_tiers(self) -> None:
        """Fold the prefill/decode split into homogeneous routing: new
        requests run a single full leg on whichever replicas survive.
        The fleet supervisor calls this when a tier's dispatchable pool
        empties; in-flight two-leg requests finish through the ordinary
        cross-tier fallback.  Greedy outputs are unchanged — a unified
        leg is just prefill+decode on one replica."""
        with self._lock:
            if self._collapsed:
                return
            self._collapsed = True
        log_dist("disagg: tier collapsed — routing homogeneous until "
                 "the fleet heals", level="warning")

    def restore_tiers(self) -> None:
        """Re-enable tiered prefill→decode routing (both tiers have
        dispatchable replicas again)."""
        with self._lock:
            if not self._collapsed:
                return
            self._collapsed = False
        log_dist("disagg: tiers restored — prefill/decode routing back",
                 level="warning")

    # -- tier-aware dispatch --------------------------------------------
    def _candidates(self, tier: Optional[str],
                    exclude: Sequence[int]) -> List[Any]:
        masked = self.masked_indices()
        alive = [r for r in self.replicas.alive if r.index not in exclude]
        clean = [r for r in alive if r.index not in masked]
        if tier is None or self.collapsed:
            # homogeneous: prefer unmasked survivors, but availability
            # beats cleanliness when the mask covers everyone
            return clean or alive
        pool = [r for r in clean if r.tier == tier]
        if pool:
            return pool
        uni = [r for r in clean if r.tier == "unified"]
        if uni:
            return uni
        # last resort: any unmasked survivor serves the leg (a decode
        # leg landing on a prefill replica just re-runs prefill — the
        # recompute contract fail-over already rests on); a fully-masked
        # fleet still dispatches rather than failing the request
        return clean or alive

    def _score(self, rep, tier: Optional[str] = None) -> float:
        if tier == "prefill":
            # prefill is compute-bound: the only thing that matters is
            # how much prompt work is already queued on the replica
            with self._lock:
                inflight = self._inflight.get(rep.index, 0)
            return -float(rep.queue_load + inflight)
        # decode legs (and the unified fallback) score by evictable KV
        # headroom — the base rule
        return super()._score(rep, tier)

    # -- the two-leg pump -----------------------------------------------
    def submit(self, prompt, params=None, priority: int = 0,
               deadline_s: Optional[float] = None,
               session: Optional[str] = None):
        from deepspeed_tpu.serving.request import SamplingParams

        # validate the WHOLE request up front: the prefill leg's 1-token
        # shape would sail past the per-sequence KV cap that the decode
        # leg then hits mid-flight (replicas share one geometry, so any
        # live engine speaks for the fleet)
        params = params or SamplingParams()
        rep = next(iter(self.replicas.alive), None)
        if rep is not None and prompt is not None:
            eng = rep.engine
            need = eng.seq_blocks(len(prompt) + params.max_new_tokens)
            if need > eng.max_seq_blocks:
                raise ValueError(
                    f"prompt+output needs {need} KV blocks but the "
                    f"engines allow {eng.max_seq_blocks} per "
                    "sequence; raise num_blocks/max_context or "
                    "shorten the request")
        return super().submit(prompt, params, priority=priority,
                              deadline_s=deadline_s, session=session,
                              phase=None if self.collapsed else "prefill")

    def _request_complete(self, rr: _RoutedRequest) -> bool:
        eos = rr.params.eos_token_id
        return (len(rr.delivered) >= rr.params.max_new_tokens
                or (eos is not None and rr.delivered
                    and rr.delivered[-1] == eos))

    def _leg_done(self, rr: _RoutedRequest) -> None:
        # bank the leg's wall time under its phase BEFORE releasing the
        # inflight slot; failed-over legs accumulate (the timeline shows
        # total time spent in each phase, retries included)
        phase = rr.phase or "unified"
        rr.legs[phase] = (rr.legs.get(phase, 0.0)
                          + (time.monotonic() - rr.leg_t0) * 1e3)
        super()._leg_done(rr)

    def _pump_loop(self, rr: _RoutedRequest,
                   session: Optional[str]) -> None:
        out = rr.stream
        while True:
            leg = (self.tracer.span("router.leg", rr.trace_id, rr.span)
                   .set(uid=rr.uid, replica=rr.replica.index,
                        tier=rr.phase)
                   if self.tracer.enabled else None)
            try:
                for tok in rr.inner:
                    rr.delivered.append(tok)
                    out._put_token(tok)
                self._leg_done(rr)
                if leg is not None:
                    leg.end(outcome="completed")
                if rr.phase == "prefill" and not self._request_complete(rr):
                    # leg 2: hand the chain to the decode tier.  A lost
                    # payload (export failed, replica died between token
                    # and export) is fine — admission just re-prefills.
                    rr.payload = getattr(rr.inner, "handoff_payload", None)
                    if (rr.deadline is not None
                            and time.monotonic() >= rr.deadline):
                        # deadline died BETWEEN legs: surface the typed
                        # terminal error here rather than burning a
                        # decode admission that would only expire in
                        # queue.  The un-adopted payload is dropped —
                        # its exported chain was released with the
                        # prefill request, so no blocks leak.
                        rr.payload = None
                        self._finish(rr, DeadlineExceeded(
                            f"request {rr.uid}: deadline exceeded after "
                            f"prefill leg ({len(rr.delivered)} tokens out)"))
                        return
                    rr.phase = "decode"
                    try:
                        self._dispatch(rr, session=session)
                    except ServingError as e:
                        self._finish(rr, e)
                        return
                    continue
                self._finish(rr, None)
                return
            except ServingError as e:
                self._leg_done(rr)
                if leg is not None:
                    leg.end(outcome=type(e).__name__)
                err = self._on_leg_error(rr, e, session)
                if err is not _RETRY:
                    self._finish(rr, err)
                    return

    def _finish(self, rr: _RoutedRequest, error) -> None:
        payload = rr.payload
        if payload is not None and "import_ms" in payload:
            # the decode server stamped the import half at admission;
            # export half rode the payload from the prefill server
            ms = payload.get("export_ms", 0.0) + payload["import_ms"]
            nbytes = payload["import_bytes"]
            self.metrics.record_handoff(nbytes, ms / 1e3)
            rr.stream.handoff_ms = round(ms, 3)
            rr.stream.handoff_bytes = int(nbytes)
            rr.payload = None     # exactly-once accounting
        # RequestTimeline: the cross-tier phase breakdown, stamped on the
        # caller's stream AND kept in the ring — terminal errors included
        # (a failed request's phase split is exactly what triage wants)
        tl: Dict[str, Any] = {
            "uid": rr.uid,
            "trace_id": rr.trace_id,
            "prefill_ms": round(rr.legs.get("prefill", 0.0), 3),
            "decode_ms": round(rr.legs.get("decode", 0.0)
                               + rr.legs.get("unified", 0.0), 3),
            "handoff_ms": rr.stream.handoff_ms or 0.0,
            "handoff_bytes": rr.stream.handoff_bytes or 0,
            "failovers": rr.failovers,
            "total_ms": round((time.monotonic() - rr.t_submit) * 1e3, 3),
        }
        rr.stream.timeline = tl
        with self._lock:
            self._timelines.append(tl)
        super()._finish(rr, error)
