"""Admission control: backpressure, KV watermarks, preemption policy.

The robustness layer the bare engine lacks (ref DeepSpeed-MII
``RaggedBatchBase`` request queue + FastGen's watermark'd KV usage):

* **Bounded request queue** — ``submit`` beyond ``max_queue_size`` either
  raises ``QueueFull`` (policy ``"reject"``, the load-shedding default)
  or blocks the submitter (policy ``"block"``).
* **KV watermarks** — a new request is admitted only while, after its
  prompt pages, the pool keeps ``kv_high_watermark`` of its blocks free;
  decode growth may then drain the pool to ``kv_low_watermark`` before
  preemption kicks in.  The hysteresis gap is what lets running requests
  finish instead of thrashing against new arrivals.
* **Preemption policy** — when an engine step raises ``KVCacheExhausted``,
  ``choose_victim`` picks the lowest-priority, youngest-admitted running
  request; its recompute requeue is the graceful-degradation path.

Admission can overcommit on purpose (``reserve_decode=False``, the
throughput default): reserving every request's worst-case output up front
(what ``generate()`` does) caps concurrency at the pessimal bound, while
optimistic admission + preemption tracks the *actual* output lengths.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Iterable, Optional

from deepspeed_tpu.serving.request import GenerationRequest, QueueFull

#: Graceful-degradation ladder, mildest first — frozen vocabulary
#: (docs/SERVING.md brownout table; linted by tools/telemetry_check.py).
#: Each level includes every level below it:
#:   normal            — full service
#:   shed_speculation  — disable speculative decoding (greedy outputs are
#:                       bit-identical by construction, so this level is
#:                       invisible to callers except in latency)
#:   cap_decode        — cap concurrently-running requests at
#:                       ``decode_cap`` (admission slows, outputs intact)
#:   shed_low_priority — reject/shed requests below ``priority_floor``
#:   reject_new        — reject every new request; finish what's running
BROWNOUT_LEVELS = ("normal", "shed_speculation", "cap_decode",
                   "shed_low_priority", "reject_new")


def brownout_index(level: str) -> int:
    """Ladder position of ``level`` (raises on unknown names — the same
    tripwire as every other frozen vocabulary)."""
    try:
        return BROWNOUT_LEVELS.index(level)
    except ValueError:
        raise ValueError(f"unknown brownout level {level!r} "
                         f"(one of {BROWNOUT_LEVELS})") from None


class BrownoutConfig:
    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        # pressure thresholds: step UP a level at >= enter, DOWN at
        # <= exit.  The gap is the hysteresis band; inside it the level
        # holds, so a pressure signal oscillating around one threshold
        # cannot flap the ladder.
        self.enter = float(d.get("enter", 0.85))
        self.exit = float(d.get("exit", 0.6))
        if not (0.0 <= self.exit < self.enter):
            raise ValueError(f"brownout thresholds must satisfy 0 <= exit "
                             f"({self.exit}) < enter ({self.enter})")
        # minimum dwell between level changes (either direction): even a
        # pressure step function walks the ladder one level per dwell
        self.dwell_s = float(d.get("dwell_s", 0.5))
        # cap_decode: max concurrently-running requests per replica
        self.decode_cap = int(d.get("decode_cap", 2))
        # shed_low_priority: requests with priority < floor are shed
        self.priority_floor = int(d.get("priority_floor", 0))
        # pressure normalization: SLO error-budget burn at which the burn
        # term saturates to 1.0 (burn 1.0 = exactly on budget)
        self.burn_limit = float(d.get("burn_limit", 4.0))


class BrownoutController:
    """The ladder's state machine: feed it a pressure scalar (0 = idle,
    1 = saturated) on a cadence; it walks :data:`BROWNOUT_LEVELS` up and
    down **one level per observation** with hysteresis + minimum dwell.

    Pure and single-threaded by design (the fleet supervisor's cadence
    thread is the only caller); actuation — what each level *does* — is
    enforced by the servers via ``InferenceServer.set_brownout``.
    """

    def __init__(self, cfg: Optional[BrownoutConfig] = None):
        self.cfg = cfg or BrownoutConfig()
        self._index = 0
        self._changed_at: Optional[float] = None
        self.transitions = 0   # lifetime level changes (tests/bench)

    @property
    def level(self) -> str:
        return BROWNOUT_LEVELS[self._index]

    @property
    def index(self) -> int:
        return self._index

    def observe(self, pressure: float,
                now: Optional[float] = None) -> Optional[str]:
        """One cadence tick: returns the NEW level name when the ladder
        moved, else ``None``."""
        now = time.monotonic() if now is None else now
        if self._changed_at is not None \
                and now - self._changed_at < self.cfg.dwell_s:
            return None
        if pressure >= self.cfg.enter \
                and self._index < len(BROWNOUT_LEVELS) - 1:
            self._index += 1
        elif pressure <= self.cfg.exit and self._index > 0:
            self._index -= 1
        else:
            return None
        self._changed_at = now
        self.transitions += 1
        return self.level


class AdmissionConfig:
    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        self.max_queue_size = int(d.get("max_queue_size", 256))
        self.queue_policy = str(d.get("queue_policy", "reject"))
        if self.queue_policy not in ("reject", "block"):
            raise ValueError(f"queue_policy={self.queue_policy!r}: "
                             "expected 'reject' or 'block'")
        self.kv_low_watermark = float(d.get("kv_low_watermark", 0.0))
        self.kv_high_watermark = float(d.get("kv_high_watermark", 0.05))
        if not (0.0 <= self.kv_low_watermark
                <= self.kv_high_watermark < 1.0):
            raise ValueError(
                f"watermarks must satisfy 0 <= low ({self.kv_low_watermark})"
                f" <= high ({self.kv_high_watermark}) < 1")
        # True = generate()-style worst-case output reservation (no
        # preemption will ever fire, lower concurrency); False = admit on
        # prompt need only and rely on preemption under pressure.
        self.reserve_decode = bool(d.get("reserve_decode", False))
        # A request preempted this many times fails instead of requeueing
        # — the livelock backstop of last resort.  Victim choice already
        # deprioritizes previously-preempted requests, so reaching this
        # means sustained pressure rotated through every running peer.
        self.max_preemptions = int(d.get("max_preemptions", 16))


class AdmissionController:
    """Thread-safe bounded queue + KV admission test + victim choice.

    Producers (``offer``) run on caller threads; consumers (``pop_ready``
    etc.) run on the serve loop only.
    """

    def __init__(self, cfg: AdmissionConfig):
        self.cfg = cfg
        self._lock = threading.Condition()
        self._queue: Deque[GenerationRequest] = deque()
        self._closed = False
        # set by the server when tracing is enabled: the blocking-offer
        # wait is a real request phase (serve.admission_block spans)
        self.tracer = None

    # -- producer side ---------------------------------------------------
    def offer(self, req: GenerationRequest,
              timeout: Optional[float] = None) -> None:
        """Enqueue or shed load per the queue policy."""
        with self._lock:
            if self.cfg.queue_policy == "block" \
                    and len(self._queue) >= self.cfg.max_queue_size \
                    and not self._closed:
                tr = self.tracer
                sp = (tr.span("serve.admission_block", req.trace_id)
                      if tr is not None and tr.enabled else None)
                ok = self._lock.wait_for(
                    lambda: self._closed
                    or len(self._queue) < self.cfg.max_queue_size,
                    timeout)
                if sp is not None:
                    # close() also satisfies the wait predicate, but a
                    # closed queue rejects below — that is not admission
                    sp.end(uid=req.uid,
                           admitted=bool(ok) and not self._closed)
                if not ok:
                    raise QueueFull(
                        f"queue full ({self.cfg.max_queue_size}) after "
                        f"blocking {timeout}s")
            if self._closed:
                raise QueueFull("server not accepting requests")
            if len(self._queue) >= self.cfg.max_queue_size:
                raise QueueFull(
                    f"queue full ({self.cfg.max_queue_size} waiting)")
            self._queue.append(req)
            self._lock.notify_all()

    def close(self) -> None:
        """Stop accepting new requests (graceful-drain entry point)."""
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    # -- serve-loop side -------------------------------------------------
    def requeue_front(self, req: GenerationRequest) -> None:
        """Preempted request: back of nobody's line."""
        with self._lock:
            self._queue.appendleft(req)
            self._lock.notify_all()

    def peek(self) -> Optional[GenerationRequest]:
        with self._lock:
            return self._queue[0] if self._queue else None

    def snapshot(self) -> list:
        """Stable copy for sweeps (offers may race the serve loop)."""
        with self._lock:
            return list(self._queue)

    def pop(self) -> Optional[GenerationRequest]:
        with self._lock:
            req = self._queue.popleft() if self._queue else None
            if req is not None:
                self._lock.notify_all()  # unblock 'block'-policy offers
            return req

    def drain(self) -> Iterable[GenerationRequest]:
        """Remove and return everything queued (shutdown-without-drain)."""
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
            self._lock.notify_all()
            return out

    def remove(self, req: GenerationRequest) -> bool:
        """Drop a queued request (cancelled/expired before admission)."""
        with self._lock:
            try:
                self._queue.remove(req)
            except ValueError:
                return False
            self._lock.notify_all()
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def wait_for_work(self, timeout: float) -> None:
        """Park the serve loop until a request arrives (or timeout — the
        loop still needs to wake for deadline sweeps)."""
        with self._lock:
            if not self._queue:
                self._lock.wait(timeout)

    # -- policy ----------------------------------------------------------
    def kv_floor(self, engine, watermark: float) -> int:
        """Blocks that must stay free under ``watermark`` — THE floor
        formula; the server's eviction shortfalls use it so reclaiming
        exactly a shortfall always satisfies the matching test below."""
        return int(watermark * (engine.cfg.num_blocks - 1))  # block 0 rsvd

    def kv_admissible(self, engine, need_blocks: int) -> bool:
        """Would admitting a prompt needing ``need_blocks`` keep the pool
        above the high watermark?"""
        floor = self.kv_floor(engine, self.cfg.kv_high_watermark)
        return engine.free_blocks - need_blocks >= floor

    def admission_shortfall(self, engine, need_blocks: int) -> int:
        """Blocks short of admitting ``need_blocks`` at the high floor
        (<= 0 when admissible) — the eviction target."""
        floor = self.kv_floor(engine, self.cfg.kv_high_watermark)
        return need_blocks + floor - engine.free_blocks

    def low_watermark_deficit(self, engine) -> int:
        """Blocks below the low floor (<= 0 when healthy)."""
        return (self.kv_floor(engine, self.cfg.kv_low_watermark)
                - engine.free_blocks)

    @staticmethod
    def evictable_headroom(engine, prefix_cache=None) -> int:
        """Blocks a new request could claim without preempting live
        work: the allocator free list PLUS pages the prefix cache could
        evict on demand (solely-cache-owned leaf blocks).  The dispatch
        score must use this, not ``free_blocks`` alone — a cache-warm
        replica whose pool is full of evictable pages has the same real
        capacity as a cold one, and scoring it by the raw free list
        makes the router spill (or reject) exactly the replica whose
        warm cache would serve the request best."""
        free = engine.free_blocks
        if prefix_cache is not None:
            free += prefix_cache.evictable_count()
        return free

    def below_low_watermark(self, engine) -> bool:
        return self.low_watermark_deficit(engine) > 0

    @staticmethod
    def choose_victim(active: Iterable[GenerationRequest]
                      ) -> Optional[GenerationRequest]:
        """Lowest priority first; within a class, fewest prior
        preemptions, then youngest admission.  Preemption count outranks
        age because a just-re-admitted request is always the youngest —
        keying on age alone would bounce the same request until the
        ``max_preemptions`` backstop failed it while never-preempted
        peers kept running."""
        victims = sorted(active,
                         key=lambda r: (r.priority, r.preemptions,
                                        -(r.admitted_at or 0.0)))
        return victims[0] if victims else None
