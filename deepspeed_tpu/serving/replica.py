"""Serving replicas: N engines on disjoint mesh slices, one server each.

The tier between one ``InferenceServer`` (PR 2) and "millions of users":
a :class:`ReplicaSet` owns N data-parallel serving replicas, each an
``InferenceEngineV2`` pinned to a **disjoint slice** of the host's
devices (the replication-over-slices half of the placement composition
in arXiv:2601.02311) plus its own continuous-batching serve loop.  On
the CPU smoke mesh the slices are virtual — 8 forced host devices split
4+4 — but the construction is the same one a multi-chip host uses.

Replicas are fully independent: separate KV pools, separate prefix
caches, separate metrics registries (shared registries would merge
counters), separate serve threads.  The :class:`~.router.Router` above
them is the only component that sees more than one.

This module deliberately imports no jax — engines are built by the
caller (or by :meth:`ReplicaSet.build`, which imports the engine module
lazily), so ``serving/`` stays importable without an accelerator stack.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from deepspeed_tpu.serving.admission import AdmissionController
from deepspeed_tpu.serving.metrics import spec_accept_rate
from deepspeed_tpu.serving.server import InferenceServer
from deepspeed_tpu.utils.logging import log_dist


class ServingReplica:
    """One engine + serve loop on its mesh slice.

    ``tier`` specializes the replica under disaggregated serving
    (serving/disagg.py): ``"prefill"`` replicas run prompt→first-token
    legs and export KV chains, ``"decode"`` replicas adopt them and run
    the token loop (optionally with a draft model for speculative
    decoding), ``"unified"`` replicas (the default) do both."""

    def __init__(self, index: int, engine: Any, server: InferenceServer,
                 tier: str = "unified"):
        self.index = index
        self.name = f"r{index}"
        self.engine = engine
        self.server = server
        self.tier = tier

    @property
    def alive(self) -> bool:
        """Accepting and making progress: serve thread running, no loop
        error, not stopping.  The router consults this for dispatch and
        for the failover decision."""
        s = self.server
        return (s._thread is not None and s._thread.is_alive()
                and s._loop_error is None and not s._stop_requested)

    @property
    def kv_headroom(self) -> float:
        """Fraction of the replica's KV pool on the free list — the
        always-current half of the dispatch score (gauges lag one loop
        tick; the free list does not)."""
        eng = self.engine
        return eng.free_blocks / max(1, eng.cfg.num_blocks - 1)

    @property
    def dispatch_headroom(self) -> float:
        """Fraction of the pool a new request could claim without
        preempting live work: the free list PLUS solely-cache-owned
        evictable pages (``AdmissionController.evictable_headroom``) —
        a warm prefix cache is capacity-in-waiting, not occupancy."""
        eng = self.engine
        free = AdmissionController.evictable_headroom(
            eng, self.server.prefix_cache)
        return free / max(1, eng.cfg.num_blocks - 1)

    @property
    def queue_load(self) -> int:
        """Requests this replica already owes: queued + running."""
        return len(self.server.admission) + len(self.server._active)

    def snapshot(self) -> Dict[str, Any]:
        snap = self.server.metrics.snapshot()
        snap["replica"] = self.index
        snap["alive"] = self.alive
        snap["tier"] = self.tier
        return snap

    def kill(self) -> None:
        """Hard-stop this replica (tests / chaos drills): aborts the
        serve loop without drain — in-flight requests fail over through
        the router.  A crashed loop's error is swallowed here; the
        router's job is to survive it, not to re-raise it."""
        try:
            self.server.stop(drain=False, timeout=30.0)
        except Exception as e:  # already-dead loop re-raises its error
            log_dist(f"replica {self.name}: kill: {e!r}", level="warning")


class ReplicaSet:
    """Owns N replicas; start/stop fan out, build slices the devices.

    A set built through :meth:`build` can also GROW/SHRINK live
    (:meth:`grow`, :meth:`shrink`, :meth:`respawn`): every replica's
    engine derives its weight shardings from the same
    :class:`~deepspeed_tpu.resilience.oracle.PartitionOracle` rules the
    training engine uses, so a replica built mid-flight on a fresh slice
    is bit-identical to the originals and the router's fail-over
    machinery covers requests through the transition."""

    def __init__(self, replicas: Sequence[ServingReplica]):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        self.replicas: List[ServingReplica] = list(replicas)
        self._ctx: Optional[Dict[str, Any]] = None  # set by build()

    def __len__(self) -> int:
        return len(self.replicas)

    def __iter__(self):
        return iter(self.replicas)

    def __getitem__(self, i: int) -> ServingReplica:
        return self.replicas[i]

    @property
    def alive(self) -> List[ServingReplica]:
        return [r for r in self.replicas if r.alive]

    @classmethod
    def build(cls, model: Any, n_replicas: int,
              engine_config: Optional[dict] = None,
              server_config: Optional[dict] = None, seed: int = 0,
              devices: Optional[Sequence[Any]] = None,
              devices_per_replica: Optional[int] = None,
              disagg: Optional[Any] = None) -> "ReplicaSet":
        """Build N engines on disjoint device slices + one server each.

        Every replica gets the SAME model/config/seed, so weights are
        identical and a greedy request finishes bit-identically on any
        replica — the property failover rests on.  ``devices`` defaults
        to all of ``jax.devices()``; the first ``n·(len//n)`` are split
        into N contiguous slices (``mesh_utils`` orders them
        ICI-adjacent, so contiguous slices are intra-slice-fast).

        ``disagg`` (a dict or :class:`~.disagg.DisaggConfig`) splits the
        set into prefill/decode tiers: the first ``prefill_replicas``
        slices become the prefill tier, the next ``decode_replicas`` the
        decode tier (``n_replicas`` must equal their sum), and decode
        replicas grow a draft engine + :class:`~.disagg.SpeculativeDecoder`
        when ``disagg.speculative`` is enabled.  Dispatch through a
        :class:`~.disagg.DisaggRouter`.
        """
        import jax  # lazy: serving/ imports no jax at module scope

        from deepspeed_tpu.serving.disagg import DisaggConfig

        devices = list(devices if devices is not None else jax.devices())
        if n_replicas < 1:
            raise ValueError(f"n_replicas={n_replicas}: must be >= 1")
        if disagg is not None and not isinstance(disagg, DisaggConfig):
            disagg = DisaggConfig(disagg)
        if disagg is not None and not disagg.enabled:
            disagg = None
        if disagg is not None and disagg.n_replicas != n_replicas:
            raise ValueError(
                f"disagg tiers ({disagg.prefill_replicas} prefill + "
                f"{disagg.decode_replicas} decode) must sum to "
                f"n_replicas={n_replicas}; fix serving.disagg or "
                "serving.n_replicas")
        ep = dict(engine_config or {}).get("expert_parallel", {})
        ep_size = int(ep.get("ep_size", 1) if isinstance(ep, dict) else ep)
        if n_replicas > 1 and ep_size > 1:
            # the MoE expert-parallel ragged branch consults the PROCESS-
            # GLOBAL topology at trace time (inference/v2/model.py), and N
            # engines each set_topology() on construction — every replica
            # but the last would trace expert dispatch against the wrong
            # mesh slice.  Refuse loudly until the engine threads its own
            # topology into the forward.
            raise NotImplementedError(
                "multi-replica serving with expert_parallel.ep_size > 1 "
                "is not supported: the MoE dispatch reads the global mesh "
                "topology, which replicas on disjoint slices would "
                "clobber (run one replica, or ep_size=1)")
        # devices_per_replica < len//n leaves headroom slices for grow():
        # the default carves the whole device list into n equal slices
        per = int(devices_per_replica or len(devices) // n_replicas)
        if per < 1 or per * n_replicas > len(devices):
            if disagg is not None:
                raise ValueError(
                    f"serving.disagg wants {disagg.prefill_replicas} "
                    f"prefill + {disagg.decode_replicas} decode replicas "
                    f"on disjoint {max(per, 1)}-device slices, but only "
                    f"{len(devices)} device(s) exist — shrink a tier, "
                    "lower devices_per_replica, or add chips")
            raise ValueError(
                f"{len(devices)} device(s) cannot host {n_replicas} "
                f"replicas on disjoint {per}-device slices")
        ctx = {"model": model, "engine_config": dict(engine_config or {}),
               "server_config": dict(server_config or {}), "seed": seed,
               "devices": devices, "per": per, "disagg": disagg}
        replicas = [cls._build_one(ctx, i) for i in range(n_replicas)]
        rs = cls(replicas)
        rs._ctx = ctx
        return rs

    @staticmethod
    def _build_one(ctx: Dict[str, Any], index: int) -> ServingReplica:
        """One replica on slice ``index`` of the build context — same
        model/config/seed as every sibling (the bit-identity contract),
        used by build(), grow() and respawn() alike.  Under disagg the
        index decides the tier, and decode-tier replicas get a draft
        engine + SpeculativeDecoder on the SAME slice when speculation
        is configured."""
        from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
        from deepspeed_tpu.serving.disagg import SpeculativeDecoder

        per = ctx["per"]
        slice_i = ctx["devices"][index * per:(index + 1) * per]
        if len(slice_i) < per:
            raise ValueError(
                f"no free device slice for replica r{index} "
                f"({len(ctx['devices'])} device(s), {per} per replica)")
        disagg = ctx.get("disagg")
        tier = disagg.tier_of(index) if disagg is not None else "unified"
        engine = InferenceEngineV2(ctx["model"], dict(ctx["engine_config"]),
                                   seed=ctx["seed"], devices=slice_i)
        scfg = dict(ctx["server_config"])
        scfg.setdefault("metrics_label", f"r{index}")
        spec = None
        if (disagg is not None and disagg.speculative.enabled
                and tier in ("decode", "unified")):
            draft_model = disagg.speculative.draft_model
            if isinstance(draft_model, str):
                from deepspeed_tpu.models import get_model_config

                draft_model = get_model_config(draft_model)
            draft = InferenceEngineV2(draft_model,
                                      dict(ctx["engine_config"]),
                                      seed=ctx["seed"], devices=slice_i)
            spec = SpeculativeDecoder(engine, draft,
                                      spec_k=disagg.speculative.spec_k)
        server = InferenceServer(engine, scfg, spec_decoder=spec)
        log_dist(f"replica r{index} [{tier}]: {per} device(s) "
                 f"[{index * per}..{(index + 1) * per - 1}]"
                 + (" +draft" if spec is not None else ""), level="info")
        return ServingReplica(index, engine, server, tier=tier)

    # -- live resizing ---------------------------------------------------
    def _require_ctx(self) -> Dict[str, Any]:
        if self._ctx is None:
            raise RuntimeError("live grow/shrink requires a ReplicaSet "
                               "constructed through ReplicaSet.build")
        return self._ctx

    def respawn(self, index: int) -> ServingReplica:
        """Rebuild a DEAD replica on its own device slice and start it —
        the serving half of self-healing: after fail-over drains a crash,
        capacity grows back without a restart.  The fresh engine re-inits
        from the shared seed through the same oracle-derived shardings,
        so it is bit-identical to the replica it replaces."""
        ctx = self._require_ctx()
        pos = next((p for p, r in enumerate(self.replicas)
                    if r.index == index), None)
        if pos is None:
            raise ValueError(f"no replica with index {index}")
        old = self.replicas[pos]
        if old.alive:
            raise RuntimeError(f"replica r{index} is alive; kill/shrink it "
                               "before respawning")
        fresh = self._build_one(ctx, index)
        fresh.server.start()
        self.replicas[pos] = fresh
        log_dist(f"replica r{index}: respawned on its slice", level="info")
        return fresh

    def grow(self) -> ServingReplica:
        """Add one replica on the lowest unused device slice (started) —
        a slice freed by shrink() is reused before a fresh one is cut."""
        ctx = self._require_ctx()
        used = {r.index for r in self.replicas}
        index = next(i for i in range(len(used) + 1) if i not in used)
        fresh = self._build_one(ctx, index)
        fresh.server.start()
        self.replicas.append(fresh)
        return fresh

    def shrink(self, index: int) -> ServingReplica:
        """Remove a replica: hard-stop it and drop it from the set.  Its
        in-flight requests fail over through the router (same path a
        crash takes); its device slice becomes free for a later grow()."""
        pos = next((p for p, r in enumerate(self.replicas)
                    if r.index == index), None)
        if pos is None:
            raise ValueError(f"no replica with index {index}")
        if len(self.replicas) == 1:
            raise ValueError("cannot shrink the last replica")
        victim = self.replicas.pop(pos)
        victim.kill()
        return victim

    def start(self) -> "ReplicaSet":
        for r in self.replicas:
            r.server.start()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        first_error: Optional[BaseException] = None
        for r in self.replicas:
            try:
                r.server.stop(drain=drain, timeout=timeout)
            except Exception as e:
                # stop every replica before surfacing anything — a dead
                # first replica must not leave the rest running
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error

    def snapshot(self) -> Dict[str, Any]:
        per = {r.name: r.snapshot() for r in self.replicas}
        proposed = sum(s["spec_proposed"] for s in per.values())
        accepted = sum(s["spec_accepted"] for s in per.values())
        return {
            "replicas": per,
            "alive": len(self.alive),
            "tokens_out": sum(s["tokens_out"] for s in per.values()),
            "tokens_per_sec": sum(s["tokens_per_sec"]
                                  for s in per.values()),
            "prefix_hits": sum(s["prefix_hits"] for s in per.values()),
            "prefix_misses": sum(s["prefix_misses"] for s in per.values()),
            "prefill_tokens_saved": sum(s["prefill_tokens_saved"]
                                        for s in per.values()),
            "handoffs_in": sum(s["handoffs_in"] for s in per.values()),
            "handoffs_out": sum(s["handoffs_out"] for s in per.values()),
            "handoff_bytes": sum(s["handoff_bytes"] for s in per.values()),
            "spec_proposed": proposed,
            "spec_accepted": accepted,
            "spec_accept_rate": spec_accept_rate(proposed, accepted),
        }
