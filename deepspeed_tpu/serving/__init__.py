"""deepspeed_tpu.serving — MII-style async serving over InferenceEngineV2.

See docs/SERVING.md for the architecture (queue → admission → SplitFuse
→ streams), the preemption/watermark policy, and a runnable CPU example.
"""

from deepspeed_tpu.serving.admission import (AdmissionConfig,
                                             AdmissionController)
from deepspeed_tpu.serving.disagg import (REQUEST_TIMELINE_KEYS,
                                          DisaggConfig, DisaggRouter,
                                          SpeculativeConfig,
                                          SpeculativeDecoder)
from deepspeed_tpu.serving.fleet import (TIER_SNAPSHOT_KEYS,
                                         TIER_SNAPSHOT_SCHEMA,
                                         FleetSampler)
from deepspeed_tpu.serving.metrics import (RouterMetrics, ServingMetrics,
                                           spec_accept_rate)
from deepspeed_tpu.serving.prefix_cache import PrefixCache, PrefixCacheConfig
from deepspeed_tpu.serving.replica import ReplicaSet, ServingReplica
from deepspeed_tpu.serving.request import (DeadlineExceeded,
                                           GenerationRequest, QueueFull,
                                           RequestCancelled, ResponseStream,
                                           SamplingParams, ServingError)
from deepspeed_tpu.serving.router import Router, RouterConfig
from deepspeed_tpu.serving.server import InferenceServer, ServerConfig

__all__ = [
    "AdmissionConfig", "AdmissionController", "DeadlineExceeded",
    "DisaggConfig", "DisaggRouter", "FleetSampler", "GenerationRequest",
    "InferenceServer", "PrefixCache", "PrefixCacheConfig", "QueueFull",
    "REQUEST_TIMELINE_KEYS", "ReplicaSet", "RequestCancelled",
    "ResponseStream", "Router", "RouterConfig", "RouterMetrics",
    "SamplingParams", "ServerConfig", "ServingError", "ServingMetrics",
    "ServingReplica", "SpeculativeConfig", "SpeculativeDecoder",
    "TIER_SNAPSHOT_KEYS", "TIER_SNAPSHOT_SCHEMA", "spec_accept_rate",
]
