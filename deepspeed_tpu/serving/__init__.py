"""deepspeed_tpu.serving — MII-style async serving over InferenceEngineV2.

See docs/SERVING.md for the architecture (queue → admission → SplitFuse
→ streams), the preemption/watermark policy, fault injection and the
self-healing supervisor, and a runnable CPU example.
"""

from deepspeed_tpu.serving.admission import (BROWNOUT_LEVELS,
                                             AdmissionConfig,
                                             AdmissionController,
                                             BrownoutConfig,
                                             BrownoutController,
                                             brownout_index)
from deepspeed_tpu.serving.disagg import (REQUEST_TIMELINE_KEYS,
                                          DisaggConfig, DisaggRouter,
                                          SpeculativeConfig,
                                          SpeculativeDecoder)
from deepspeed_tpu.serving.fleet import (TIER_SNAPSHOT_KEYS,
                                         TIER_SNAPSHOT_SCHEMA,
                                         FleetSampler)
from deepspeed_tpu.serving.metrics import (RouterMetrics, ServingMetrics,
                                           spec_accept_rate)
from deepspeed_tpu.serving.prefix_cache import PrefixCache, PrefixCacheConfig
from deepspeed_tpu.serving.replica import ReplicaSet, ServingReplica
from deepspeed_tpu.serving.request import (DeadlineExceeded,
                                           GenerationRequest, QueueFull,
                                           RequestCancelled, RequestShed,
                                           ResponseStream, SamplingParams,
                                           ServingError)
from deepspeed_tpu.serving.router import Router, RouterConfig
from deepspeed_tpu.serving.server import InferenceServer, ServerConfig
from deepspeed_tpu.serving.supervisor import (HEALTH_STATES,
                                              FleetHealFailed,
                                              FleetSupervisor,
                                              FleetSupervisorConfig)

__all__ = [
    "AdmissionConfig", "AdmissionController", "BROWNOUT_LEVELS",
    "BrownoutConfig", "BrownoutController", "DeadlineExceeded",
    "DisaggConfig", "DisaggRouter", "FleetHealFailed", "FleetSampler",
    "FleetSupervisor", "FleetSupervisorConfig", "GenerationRequest",
    "HEALTH_STATES", "InferenceServer", "PrefixCache", "PrefixCacheConfig",
    "QueueFull", "REQUEST_TIMELINE_KEYS", "ReplicaSet", "RequestCancelled",
    "RequestShed", "ResponseStream", "Router", "RouterConfig",
    "RouterMetrics", "SamplingParams", "ServerConfig", "ServingError",
    "ServingMetrics", "ServingReplica", "SpeculativeConfig",
    "SpeculativeDecoder", "TIER_SNAPSHOT_KEYS", "TIER_SNAPSHOT_SCHEMA",
    "brownout_index", "spec_accept_rate",
]
