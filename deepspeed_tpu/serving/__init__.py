"""deepspeed_tpu.serving — MII-style async serving over InferenceEngineV2.

See docs/SERVING.md for the architecture (queue → admission → SplitFuse
→ streams), the preemption/watermark policy, and a runnable CPU example.
"""

from deepspeed_tpu.serving.admission import (AdmissionConfig,
                                             AdmissionController)
from deepspeed_tpu.serving.disagg import (DisaggConfig, DisaggRouter,
                                          SpeculativeConfig,
                                          SpeculativeDecoder)
from deepspeed_tpu.serving.metrics import RouterMetrics, ServingMetrics
from deepspeed_tpu.serving.prefix_cache import PrefixCache, PrefixCacheConfig
from deepspeed_tpu.serving.replica import ReplicaSet, ServingReplica
from deepspeed_tpu.serving.request import (DeadlineExceeded,
                                           GenerationRequest, QueueFull,
                                           RequestCancelled, ResponseStream,
                                           SamplingParams, ServingError)
from deepspeed_tpu.serving.router import Router, RouterConfig
from deepspeed_tpu.serving.server import InferenceServer, ServerConfig

__all__ = [
    "AdmissionConfig", "AdmissionController", "DeadlineExceeded",
    "DisaggConfig", "DisaggRouter", "GenerationRequest",
    "InferenceServer", "PrefixCache", "PrefixCacheConfig", "QueueFull",
    "ReplicaSet", "RequestCancelled", "ResponseStream", "Router",
    "RouterConfig", "RouterMetrics", "SamplingParams", "ServerConfig",
    "ServingError", "ServingMetrics", "ServingReplica",
    "SpeculativeConfig", "SpeculativeDecoder",
]
