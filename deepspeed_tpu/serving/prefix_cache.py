"""Paged prefix cache: content-addressed KV pages shared across requests.

System-prompt-heavy traffic — the dominant production shape — re-prefills
the same leading tokens on every request.  This cache keys **token-block-
aligned prompt prefixes** to the KV pages a previous request already
wrote, so a matching prefix is *adopted* (block-table entries point at
the shared pages, prefill starts after them) instead of recomputed.

Correctness rests on three facts:

* **KV is content-addressed.**  A page holds the K/V of tokens
  ``[j·bs, (j+1)·bs)`` computed from the tokens before them; a prefix
  always starts at position 0, so identical token blocks along an
  identical chain produce identical KV (positions included).  Entries
  are therefore keyed by the *chain* of block token-tuples, not by a
  single block's tokens.
* **Shared pages are never written.**  Adoption is block-aligned and
  strictly shorter than the prompt (``DSStateManager.open`` enforces
  both), so the adopting sequence's first KV write lands in a fresh
  page.
* **Refcounts guard frees.**  Every owner — each live sequence sharing
  a page, plus the cache itself — holds one ``BlockedAllocator`` ref;
  a page returns to the free list only at refcount zero, so neither a
  donor's flush, a victim's preemption, nor a cache eviction can free
  a page another live sequence still reads.

Eviction is LRU over **leaf** entries whose page the cache is the sole
owner of (refcount 1 — no live sequence shares it); freeing a leaf may
expose its parent.  Interior entries stay until their subtree drains,
which keeps every cached chain walkable.  The serve loop drives
eviction from the existing ``kv_high_watermark`` admission floor: when
admission (or an engine step) wants pages the free list cannot cover,
cache pages are reclaimed before any live request is preempted.

Zero dependencies (no jax, no numpy): handles and token ids are plain
Python ints, same as the rest of ``serving/``.
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional, Sequence, Tuple


class PrefixCacheConfig:
    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        self.enabled = bool(d.get("enabled", False))
        # hard cap on pages the cache may hold (0 = bounded only by the
        # watermark-driven eviction); a cap keeps one giant system prompt
        # from squatting the whole pool on an idle server
        self.max_blocks = int(d.get("max_blocks", 0))
        if self.max_blocks < 0:
            raise ValueError(f"prefix_cache.max_blocks={self.max_blocks}: "
                             "must be >= 0 (0 = unbounded)")
        # prefixes shorter than this many blocks are not worth caching
        # (adoption saves < min_prefix_blocks·block_size prefill tokens)
        self.min_prefix_blocks = int(d.get("min_prefix_blocks", 1))
        if self.min_prefix_blocks < 1:
            raise ValueError(
                f"prefix_cache.min_prefix_blocks={self.min_prefix_blocks}: "
                "must be >= 1")


class _Entry:
    """One cached page: a node in the chain trie."""

    __slots__ = ("block", "parent", "children", "last_used")

    def __init__(self, block: int, parent: Optional["_Entry"]):
        self.block = block
        self.parent = parent
        # block token-tuple -> child entry (the NEXT block of the chain)
        self.children: Dict[Tuple[int, ...], "_Entry"] = {}
        self.last_used = 0


class PrefixCache:
    """Chain-keyed trie of shared KV pages over one engine's allocator.

    Single-threaded by design: every method runs on the serve loop (the
    only thread that touches the engine and its allocator), so no lock
    is needed — same threading contract as ``DSStateManager``.
    """

    def __init__(self, cfg: PrefixCacheConfig, allocator, block_size: int):
        self.cfg = cfg
        self.allocator = allocator
        self.block_size = int(block_size)
        self._root: Dict[Tuple[int, ...], _Entry] = {}
        self._entries: List[_Entry] = []       # flat view for eviction
        # logical LRU clock — deterministic, monotonic, no wall time
        self._clock = itertools.count(1)
        # (monotonic_ts, value) memo for evictable_count: dispatch
        # scoring calls it per replica per routed request, and a full
        # trie walk per call would grow with cache occupancy exactly
        # when the system is busiest
        self._evictable_memo = (-1.0, 0)

    # -- introspection ---------------------------------------------------
    @property
    def cached_blocks(self) -> int:
        return len(self._entries)

    def evictable_count(self, max_age_s: float = 0.05) -> int:
        """Pages eviction could free on demand: every entry whose whole
        subtree the cache solely owns (refcount 1 throughout — no live
        sequence shares any page below it).  ``evict()`` reaches them
        leaf-first across repeated passes, so for dispatch scoring
        (``AdmissionController.evictable_headroom``) they are
        headroom-in-waiting, not occupancy.  A shared page pins its
        ancestors (interior entries stay until their subtree drains)
        but not its fully-cache-owned siblings.  Safe from a non-serve
        thread — it only reads snapshots of the trie and the
        allocator's refcounts.  Results are memoized for ``max_age_s``
        (dispatch scores tolerate a loop-tick of staleness; pass 0 to
        force a fresh walk)."""
        now = time.monotonic()
        ts, value = self._evictable_memo
        if max_age_s > 0 and ts >= 0 and now - ts < max_age_s:
            return value

        def walk(entry: _Entry):
            n = 0
            fully = self.allocator.refcount(entry.block) == 1
            for child in list(entry.children.values()):
                c_n, c_fully = walk(child)
                n += c_n
                fully = fully and c_fully
            return (n + 1, True) if fully else (n, False)

        value = sum(walk(e)[0] for e in list(self._root.values()))
        self._evictable_memo = (now, value)
        return value

    def _chain(self, tokens: Sequence[int], limit_blocks: int):
        """Yield (block_tokens_tuple, entry-or-None) down the trie."""
        bs = self.block_size
        node = self._root
        for j in range(limit_blocks):
            key = tuple(tokens[j * bs:(j + 1) * bs])
            entry = node.get(key)
            yield key, entry
            if entry is None:
                return
            node = entry.children

    # -- serve-loop API --------------------------------------------------
    def adopt(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Acquire the longest cached chain for ``tokens``.

        Returns ``(blocks, n_cached_tokens)``; the caller owns one ref
        per returned page (hand them to ``DSStateManager.open``, or
        ``release`` them if admission is abandoned).  Acquiring FIRST is
        what makes the subsequent admission-pressure eviction safe: an
        adopted page is refcount >= 2 and cannot be reclaimed out from
        under the pending request.  Adoption is capped at
        ``(len(tokens) - 1) // block_size`` full blocks so at least one
        token remains to prefill (the sampling step needs a real row).
        """
        limit = (len(tokens) - 1) // self.block_size
        entries: List[_Entry] = []
        for _key, entry in self._chain(tokens, limit):
            if entry is None:
                break
            entries.append(entry)
        if not entries:
            return [], 0
        now = next(self._clock)
        for e in entries:
            e.last_used = now
        blocks = [e.block for e in entries]
        self.allocator.acquire(blocks)
        return blocks, len(blocks) * self.block_size

    def release(self, blocks: Sequence[int]) -> None:
        """Return adoption refs for a request that was NOT admitted."""
        if blocks:
            self.allocator.free(blocks)

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> int:
        """Register a freshly-prefilled sequence's full prefix blocks.

        ``tokens`` is the prefilled prefix (everything in the sequence at
        admission time); ``blocks`` the sequence's page list.  Only the
        leading ``len(tokens) // block_size`` FULL pages are cacheable —
        a partial last block will be appended into by decode and can
        never be shared.  The cache acquires one ref per newly-inserted
        page (so it outlives the donor); chains that already exist keep
        their existing pages (first writer wins — both hold identical
        KV, and swapping would orphan refs mid-chain).  Returns the
        number of pages newly inserted.
        """
        bs = self.block_size
        n_full = min(len(tokens) // bs, len(blocks))
        if n_full < self.cfg.min_prefix_blocks:
            return 0
        inserted = 0
        now = next(self._clock)
        node = self._root
        parent: Optional[_Entry] = None
        for j in range(n_full):
            key = tuple(tokens[j * bs:(j + 1) * bs])
            entry = node.get(key)
            if entry is None:
                if (self.cfg.max_blocks
                        and len(self._entries) >= self.cfg.max_blocks
                        and self.evict(1) == 0):
                    break  # cap hit and nothing reclaimable: stop here
                entry = _Entry(blocks[j], parent)
                self.allocator.acquire([blocks[j]])
                node[key] = entry
                self._entries.append(entry)
                inserted += 1
            entry.last_used = now
            parent = entry
            node = entry.children
        return inserted

    def evict(self, need_blocks: int) -> int:
        """Free at least ``need_blocks`` pages if possible; returns the
        number actually freed.  Victims are LRU over leaf entries whose
        page has no live-sequence owner (refcount 1: the cache alone);
        freeing a leaf may expose its parent, so the scan repeats until
        satisfied or dry."""
        freed = 0
        while freed < need_blocks:
            victim: Optional[_Entry] = None
            for e in self._entries:
                if e.children or self.allocator.refcount(e.block) != 1:
                    continue
                if victim is None or e.last_used < victim.last_used:
                    victim = e
            if victim is None:
                break
            self._remove(victim)
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every entry the cache solely owns (server shutdown)."""
        return self.evict(len(self._entries))

    def _remove(self, entry: _Entry) -> None:
        parent_map = (entry.parent.children if entry.parent is not None
                      else self._root)
        for key, e in list(parent_map.items()):
            if e is entry:
                del parent_map[key]
                break
        self._entries.remove(entry)
        self.allocator.free([entry.block])
