"""Serving metrics: per-request latency decomposition + service gauges.

Glossary (the standard LLM-serving vocabulary; see docs/SERVING.md):

* **TTFT** — time to first token: submit → first token out the stream.
* **TPOT** — time per output token: (last token − first token) / (n − 1),
  the steady-state decode cadence one request observes.
* **queue wait** — submit → admission into the SplitFuse scheduler.

All primitives come from the shared ``telemetry.registry`` — the same
Counter/Gauge/Histogram the training engine exports — so "p95" means
the same thing on both hot loops and a ``MetricsRegistry`` can be
shared with a :class:`telemetry.Telemetry` hub (serving tags then land
in the same Prometheus exposition).  Histograms keep a bounded sliding
window of recent samples — a long-lived server must not grow without
bound.  Export goes through ``monitor.MonitorMaster`` as plain
``(tag, value, step)`` events so TensorBoard/WandB/CSV all work
unchanged.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.registry import MetricsRegistry

Event = Tuple[str, float, int]

_WINDOW = 2048  # per-distribution sample cap

# outcome name (record_finish) → counter attribute; "shed" covers both
# submit-time brownout rejections (record_shed) and queued requests
# terminated with RequestShed by the degradation ladder
_OUTCOMES = ("completed", "failed", "cancelled", "expired", "shed")


def spec_accept_rate(proposed: int, accepted: int) -> float:
    """THE accept-rate definition: accepted/proposed draft tokens, 0.0
    when no rounds ran.  One function so ``snapshot()``, the replica-set
    rollup, the fleet sampler, and bench.py cannot drift on the
    denominator (bonus tokens are excluded by construction — see
    :meth:`ServingMetrics.record_spec_round`)."""
    return accepted / max(1, proposed)


class ServingMetrics:
    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 label: str = "", window_s: float = 0.0):
        """``label`` namespaces the MONITOR tags (``serving/<label>/…``)
        for per-replica export under a router; metric names are
        unchanged, so per-replica instances must use per-replica
        registries (the default) — sharing one registry would merge the
        replicas' counters.  ``window_s > 0`` time-bounds the latency
        histograms (``max_age_s``) so an idle server's percentiles decay
        instead of pinning at the last burst — required under a
        ``FleetSampler`` (server config key ``metrics_window_s``)."""
        self.registry = registry or MetricsRegistry()
        self.label = label
        self.window_s = float(window_s)
        reg = self.registry
        self._t0 = time.monotonic()
        # counters
        self._c = {name: reg.counter(f"serving_{name}_total")
                   for name in ("submitted", "admitted", "rejected",
                                "preemptions", "tokens_out", "steps",
                                "flight_dumps", "prefix_hits",
                                "prefix_misses", "prefill_tokens_saved",
                                "handoffs_in", "handoffs_out",
                                "handoff_bytes", "spec_rounds",
                                "spec_proposed", "spec_accepted")
                   + _OUTCOMES}
        # distributions (seconds)
        self._ttft = reg.histogram("serving_ttft_seconds",
                                   "submit to first token", window=_WINDOW,
                                   max_age_s=self.window_s)
        self._tpot = reg.histogram("serving_tpot_seconds",
                                   "steady-state time per output token",
                                   window=_WINDOW, max_age_s=self.window_s)
        self._queue_wait = reg.histogram("serving_queue_wait_seconds",
                                         "submit to admission",
                                         window=_WINDOW,
                                         max_age_s=self.window_s)
        self._handoff = reg.histogram(
            "serving_handoff_seconds",
            "KV-chain export/import time, one observation per side",
            window=_WINDOW, max_age_s=self.window_s)
        # gauges (set by the serve loop each iteration)
        self._g_queue_depth = reg.gauge("serving_queue_depth")
        self._g_active = reg.gauge("serving_active_requests")
        self._g_kv_util = reg.gauge("serving_kv_utilization")
        self._g_prefix_blocks = reg.gauge("serving_prefix_cached_blocks")

    # counter values read by the serve loop / tests
    def _cv(self, name: str) -> int:
        return int(self._c[name].value)

    submitted = property(lambda self: self._cv("submitted"))
    admitted = property(lambda self: self._cv("admitted"))
    completed = property(lambda self: self._cv("completed"))
    failed = property(lambda self: self._cv("failed"))
    cancelled = property(lambda self: self._cv("cancelled"))
    expired = property(lambda self: self._cv("expired"))
    shed = property(lambda self: self._cv("shed"))
    rejected = property(lambda self: self._cv("rejected"))
    preemptions = property(lambda self: self._cv("preemptions"))
    tokens_out = property(lambda self: self._cv("tokens_out"))
    steps = property(lambda self: self._cv("steps"))
    flight_dumps = property(lambda self: self._cv("flight_dumps"))
    prefix_hits = property(lambda self: self._cv("prefix_hits"))
    prefix_misses = property(lambda self: self._cv("prefix_misses"))
    prefill_tokens_saved = property(
        lambda self: self._cv("prefill_tokens_saved"))
    handoffs_in = property(lambda self: self._cv("handoffs_in"))
    handoffs_out = property(lambda self: self._cv("handoffs_out"))
    handoff_bytes = property(lambda self: self._cv("handoff_bytes"))
    spec_rounds = property(lambda self: self._cv("spec_rounds"))
    spec_proposed = property(lambda self: self._cv("spec_proposed"))
    spec_accepted = property(lambda self: self._cv("spec_accepted"))
    queue_depth = property(lambda self: int(self._g_queue_depth.value))
    active_requests = property(lambda self: int(self._g_active.value))
    kv_utilization = property(lambda self: self._g_kv_util.value)

    # -- recording (serve loop / submit path) ----------------------------
    def record_submit(self) -> None:
        self._c["submitted"].inc()

    def record_reject(self) -> None:
        self._c["rejected"].inc()

    def record_shed(self) -> None:
        """A submit shed by the brownout ladder before a stream existed
        (queued sheds arrive through ``record_finish("shed", ...)``)."""
        self._c["shed"].inc()

    def record_admit(self, queue_wait_s: float) -> None:
        self._c["admitted"].inc()
        self._queue_wait.observe(queue_wait_s)

    def record_first_token(self, ttft_s: float) -> None:
        self._ttft.observe(ttft_s)

    def record_tokens(self, n: int) -> None:
        self._c["tokens_out"].inc(n)

    def record_step(self) -> None:
        self._c["steps"].inc()

    def record_preemption(self) -> None:
        self._c["preemptions"].inc()

    def record_flight_dump(self) -> None:
        """A flight-recorder bundle was written for this server (watchdog
        fire or crash handler) — the ops-alert counter."""
        self._c["flight_dumps"].inc()

    def record_prefix(self, tokens_saved: int) -> None:
        """One admission's prefix-cache outcome: a hit adopted
        ``tokens_saved`` tokens of already-written KV (prefill skipped
        them); zero is a miss.  Re-admissions count again — a preempted
        victim re-adopting its prefix really does skip that prefill."""
        if tokens_saved > 0:
            self._c["prefix_hits"].inc()
            self._c["prefill_tokens_saved"].inc(tokens_saved)
        else:
            self._c["prefix_misses"].inc()

    def record_handoff_out(self, export_s: float) -> None:
        """Prefill-tier side: one KV chain exported for adoption."""
        self._c["handoffs_out"].inc()
        self._handoff.observe(export_s)

    def record_handoff_in(self, bytes_moved: int, import_s: float) -> None:
        """Decode-tier side: one handed-off chain adopted at admission —
        ``bytes_moved`` is 0 for the zero-copy path (the local prefix
        cache already held the chain; adoption was a ref acquire)."""
        self._c["handoffs_in"].inc()
        self._c["handoff_bytes"].inc(int(bytes_moved))
        self._handoff.observe(import_s)

    def record_spec_round(self, proposed: int, accepted: int) -> None:
        """One speculative verify round: the draft proposed ``proposed``
        tokens across the batch, the target accepted ``accepted`` of
        them (bonus tokens are not counted — accept rate measures the
        draft's hit rate, accepted/proposed)."""
        self._c["spec_rounds"].inc()
        self._c["spec_proposed"].inc(int(proposed))
        self._c["spec_accepted"].inc(int(accepted))

    def record_finish(self, outcome: str, n_tokens: int,
                      first_token_at: Optional[float],
                      finished_at: float) -> None:
        """``outcome``: completed | failed | cancelled | expired | shed."""
        if outcome not in _OUTCOMES:
            raise ValueError(f"unknown outcome {outcome!r}")
        self._c[outcome].inc()
        if (outcome == "completed" and n_tokens > 1
                and first_token_at is not None):
            self._tpot.observe(
                (finished_at - first_token_at) / (n_tokens - 1))

    def set_gauges(self, queue_depth: int, active: int,
                   kv_utilization: float,
                   prefix_cached_blocks: int = 0) -> None:
        self._g_queue_depth.set(queue_depth)
        self._g_active.set(active)
        self._g_kv_util.set(kv_utilization)
        self._g_prefix_blocks.set(prefix_cached_blocks)

    # -- reading ---------------------------------------------------------
    def latency_values(self) -> Dict[str, List[float]]:
        """Raw current-window latency samples (seconds), for cross-
        replica pooling: a tier percentile must be computed over the
        POOLED samples of its replicas, not an average of per-replica
        percentiles — the fleet sampler's read path."""
        return {"ttft": self._ttft.values(),
                "tpot": self._tpot.values(),
                "queue_wait": self._queue_wait.values()}

    def snapshot(self) -> Dict[str, object]:
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        tokens_out = self.tokens_out
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "completed": self.completed,
            "failed": self.failed,
            "cancelled": self.cancelled,
            "expired": self.expired,
            "shed": self.shed,
            "rejected": self.rejected,
            "preemptions": self.preemptions,
            "flight_dumps": self.flight_dumps,
            "tokens_out": tokens_out,
            "steps": self.steps,
            "tokens_per_sec": tokens_out / elapsed,
            "queue_depth": self.queue_depth,
            "active_requests": self.active_requests,
            "kv_utilization": self.kv_utilization,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_rate": (self.prefix_hits
                                / max(1, self.prefix_hits
                                      + self.prefix_misses)),
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "prefix_cached_blocks": int(self._g_prefix_blocks.value),
            "handoffs_in": self.handoffs_in,
            "handoffs_out": self.handoffs_out,
            "handoff_bytes": self.handoff_bytes,
            "spec_rounds": self.spec_rounds,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "spec_accept_rate": spec_accept_rate(self.spec_proposed,
                                                 self.spec_accepted),
            "ttft": self._ttft.snapshot(),
            "tpot": self._tpot.snapshot(),
            "queue_wait": self._queue_wait.snapshot(),
            "handoff": self._handoff.snapshot(),
        }

    def events(self, step: int) -> List[Event]:
        """Flatten the snapshot into MonitorMaster events.  With a
        ``label`` (per-replica export under a router) tags nest one
        level deeper: ``serving/<label>/<key>``."""
        snap = self.snapshot()
        prefix = f"serving/{self.label}" if self.label else "serving"
        out: List[Event] = []
        for k, v in snap.items():
            if isinstance(v, dict):
                for sub, x in v.items():
                    out.append((f"{prefix}/{k}_{sub}", float(x), step))
            else:
                out.append((f"{prefix}/{k}", float(v), step))
        return out

    def write_to(self, monitor, step: int) -> None:
        """Export through a ``monitor.MonitorMaster`` (or anything with
        ``write_events``)."""
        monitor.write_events(self.events(step))


class RouterMetrics:
    """Router-tier counters over the shared registry.

    Per-replica dispatch counts get per-replica metric NAMES
    (``router_routed_r<i>_total`` — documented as the
    ``router_routed_r*_total`` wildcard row) because the registry has no
    label dimension; everything else is a flat counter/gauge.  The
    replicas' own ``ServingMetrics`` live in per-replica registries —
    this class only holds what exists *above* them."""

    def __init__(self, n_replicas: int,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        reg = self.registry
        self.n_replicas = n_replicas
        self._requests = reg.counter("router_requests_total")
        self._rejected = reg.counter("router_rejected_total")
        self._failovers = reg.counter("router_failovers_total")
        self._routed = {i: reg.counter(f"router_routed_r{i}_total")
                        for i in range(n_replicas)}
        self._g_alive = reg.gauge("router_replicas_alive")
        # disaggregated tiers: per-request prefill→decode KV handoffs
        # observed at the router (export + import, end to end)
        self._handoffs = reg.counter("router_handoffs_total")
        self._handoff_bytes = reg.counter("router_handoff_bytes_total")
        self._handoff_s = reg.histogram(
            "router_handoff_seconds",
            "per-request KV handoff latency (export + import)",
            window=_WINDOW)

    requests = property(lambda self: int(self._requests.value))
    rejected = property(lambda self: int(self._rejected.value))
    failovers = property(lambda self: int(self._failovers.value))
    handoffs = property(lambda self: int(self._handoffs.value))
    handoff_bytes = property(lambda self: int(self._handoff_bytes.value))

    def routed(self, i: int) -> int:
        c = self._routed.get(i)
        return int(c.value) if c is not None else 0

    def ensure_replica(self, i: int) -> None:
        """Counter for a replica added AFTER construction (live grow /
        respawn) — the registry get-or-creates, so an index that comes
        back keeps its lifetime count."""
        if i not in self._routed:
            self._routed[i] = self.registry.counter(
                f"router_routed_r{i}_total")
            self.n_replicas = max(self.n_replicas, i + 1)

    def record_submit(self) -> None:
        self._requests.inc()

    def record_reject(self) -> None:
        self._rejected.inc()

    def record_route(self, replica: int) -> None:
        self.ensure_replica(replica)
        self._routed[replica].inc()

    def record_failover(self) -> None:
        self._failovers.inc()

    def record_handoff(self, bytes_moved: int, seconds: float) -> None:
        """One request's prefill→decode KV handoff completed (0 bytes =
        the zero-copy ref-acquire path)."""
        self._handoffs.inc()
        self._handoff_bytes.inc(int(bytes_moved))
        self._handoff_s.observe(seconds)

    def set_alive(self, n: int) -> None:
        self._g_alive.set(n)

    def snapshot(self) -> Dict[str, object]:
        return {
            "requests": self.requests,
            "rejected": self.rejected,
            "failovers": self.failovers,
            "replicas_alive": int(self._g_alive.value),
            "handoffs": self.handoffs,
            "handoff_bytes": self.handoff_bytes,
            "handoff": self._handoff_s.snapshot(),
            "routed": {f"r{i}": self.routed(i)
                       for i in sorted(self._routed)},
        }
