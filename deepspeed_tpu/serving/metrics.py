"""Serving metrics: per-request latency decomposition + service gauges.

Glossary (the standard LLM-serving vocabulary; see docs/SERVING.md):

* **TTFT** — time to first token: submit → first token out the stream.
* **TPOT** — time per output token: (last token − first token) / (n − 1),
  the steady-state decode cadence one request observes.
* **queue wait** — submit → admission into the SplitFuse scheduler.

Everything is recorded under one lock (the serve loop is the writer; any
thread may ``snapshot()``).  Distributions keep a bounded window of the
most recent samples — a long-lived server must not grow without bound.
Export goes through ``monitor.MonitorMaster`` as plain
``(tag, value, step)`` events so TensorBoard/WandB/CSV all work unchanged.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

Event = Tuple[str, float, int]

_WINDOW = 2048  # per-distribution sample cap


def _percentiles(xs: Deque[float]) -> Dict[str, float]:
    if not xs:
        return {"p50": 0.0, "p95": 0.0, "mean": 0.0, "count": 0}
    a = np.asarray(xs, np.float64)
    return {"p50": float(np.percentile(a, 50)),
            "p95": float(np.percentile(a, 95)),
            "mean": float(a.mean()), "count": int(a.size)}


class ServingMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        # counters
        self.submitted = 0
        self.admitted = 0
        self.completed = 0
        self.failed = 0
        self.cancelled = 0
        self.expired = 0
        self.rejected = 0
        self.preemptions = 0
        self.tokens_out = 0
        self.steps = 0
        # distributions (seconds)
        self._ttft: Deque[float] = deque(maxlen=_WINDOW)
        self._tpot: Deque[float] = deque(maxlen=_WINDOW)
        self._queue_wait: Deque[float] = deque(maxlen=_WINDOW)
        # gauges (set by the serve loop each iteration)
        self.queue_depth = 0
        self.active_requests = 0
        self.kv_utilization = 0.0

    # -- recording (serve loop / submit path) ----------------------------
    def record_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_admit(self, queue_wait_s: float) -> None:
        with self._lock:
            self.admitted += 1
            self._queue_wait.append(queue_wait_s)

    def record_first_token(self, ttft_s: float) -> None:
        with self._lock:
            self._ttft.append(ttft_s)

    def record_tokens(self, n: int) -> None:
        with self._lock:
            self.tokens_out += n

    def record_step(self) -> None:
        with self._lock:
            self.steps += 1

    def record_preemption(self) -> None:
        with self._lock:
            self.preemptions += 1

    def record_finish(self, outcome: str, n_tokens: int,
                      first_token_at: Optional[float],
                      finished_at: float) -> None:
        """``outcome``: completed | failed | cancelled | expired."""
        with self._lock:
            setattr(self, outcome, getattr(self, outcome) + 1)
            if (outcome == "completed" and n_tokens > 1
                    and first_token_at is not None):
                self._tpot.append(
                    (finished_at - first_token_at) / (n_tokens - 1))

    def set_gauges(self, queue_depth: int, active: int,
                   kv_utilization: float) -> None:
        with self._lock:
            self.queue_depth = queue_depth
            self.active_requests = active
            self.kv_utilization = kv_utilization

    # -- reading ---------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            return {
                "submitted": self.submitted,
                "admitted": self.admitted,
                "completed": self.completed,
                "failed": self.failed,
                "cancelled": self.cancelled,
                "expired": self.expired,
                "rejected": self.rejected,
                "preemptions": self.preemptions,
                "tokens_out": self.tokens_out,
                "steps": self.steps,
                "tokens_per_sec": self.tokens_out / elapsed,
                "queue_depth": self.queue_depth,
                "active_requests": self.active_requests,
                "kv_utilization": self.kv_utilization,
                "ttft": _percentiles(self._ttft),
                "tpot": _percentiles(self._tpot),
                "queue_wait": _percentiles(self._queue_wait),
            }

    def events(self, step: int) -> List[Event]:
        """Flatten the snapshot into MonitorMaster events."""
        snap = self.snapshot()
        out: List[Event] = []
        for k, v in snap.items():
            if isinstance(v, dict):
                for sub, x in v.items():
                    out.append((f"serving/{k}_{sub}", float(x), step))
            else:
                out.append((f"serving/{k}", float(v), step))
        return out

    def write_to(self, monitor, step: int) -> None:
        """Export through a ``monitor.MonitorMaster`` (or anything with
        ``write_events``)."""
        monitor.write_events(self.events(step))
