"""Router: one front door over N serving replicas.

The DeepSpeed-MII deployment shape — a load-balancer in front of N
data-parallel model replicas — with the same ``submit()/generate()``
surface as a single :class:`~.server.InferenceServer`, so callers never
know how many engines sit behind it.

Dispatch policy (docs/SERVING.md has the table):

* **Least-loaded, KV-headroom-aware.**  Each replica is scored
  ``kv_headroom − queue_weight · (queued + running + router-inflight)``;
  the highest score wins.  KV headroom comes straight off the replica's
  allocator free list (always current); the load term folds in the
  router's own not-yet-terminal dispatches so a burst between serve-loop
  ticks doesn't pile onto one replica.
* **Sticky routing.**  A streamed request is pumped from the ONE replica
  it was dispatched to (its KV lives there).  Optionally, a caller's
  ``session`` key pins successive requests to the same replica while it
  stays healthy — that is what makes the replica-local prefix cache hit
  on the session's shared prompt.
* **Fail-over.**  When a replica dies mid-request (serve-loop crash,
  hard stop), the pump re-submits prompt + tokens-delivered-so-far to a
  surviving replica and keeps streaming into the SAME caller-held
  stream; under greedy sampling the continuation is bit-identical
  (weights are identical across replicas, and generated-so-far re-enters
  as prompt — the same recompute contract preemption uses).  The dead
  replica's flight-recorder bundle, if configured, was already dumped by
  its own crash handler.

Threading: ``submit`` may be called from any thread.  Each routed
request owns one daemon pump thread that blocks on the replica stream —
the per-request-thread model matches the caller side of the serving API
(callers block on streams anyway) and keeps fail-over logic local to
the request it serves.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set

from deepspeed_tpu.serving.metrics import RouterMetrics
from deepspeed_tpu.serving.replica import ReplicaSet, ServingReplica
from deepspeed_tpu.serving.request import (DeadlineExceeded, QueueFull,
                                           RequestCancelled, ResponseStream,
                                           SamplingParams, ServingError)
from deepspeed_tpu.telemetry.flight import make_span_recorder
from deepspeed_tpu.utils.logging import log_dist


class RouterConfig:
    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        # score penalty per queued/running/in-flight request, in units of
        # KV-headroom fraction: 0.05 means ~20 outstanding requests
        # outweigh a fully-free pool
        self.queue_weight = float(d.get("queue_weight", 0.05))
        if self.queue_weight < 0:
            raise ValueError(f"router.queue_weight={self.queue_weight}: "
                             "must be >= 0")
        # a request is failed over at most this many times before its
        # last error propagates to the caller
        self.max_failovers = int(d.get("max_failovers", 2))
        # fail-over pacing: the k-th re-dispatch of a request sleeps
        # min(base · 2^(k-1), cap) · U[0.5, 1.0) in its own pump thread
        # before picking a new replica, so a crash burst doesn't slam
        # every orphaned request onto the survivors in the same instant.
        # base=0 disables the backoff (tests that pin instant fail-over).
        self.backoff_base_s = float(d.get("backoff_base_s", 0.05))
        self.backoff_cap_s = float(d.get("backoff_cap_s", 1.0))
        if self.backoff_base_s < 0 or self.backoff_cap_s < self.backoff_base_s:
            raise ValueError(
                f"router backoff: need 0 <= base ({self.backoff_base_s}) "
                f"<= cap ({self.backoff_cap_s})")
        # after this many CONSECUTIVE failed legs on one replica, new
        # dispatches skip it for mask_cooldown_s (a flapping replica
        # stops being everyone's first retry target); a completed leg
        # resets its counter.  0 disables the cooldown.
        self.mask_after_failures = int(d.get("mask_after_failures", 3))
        self.mask_cooldown_s = float(d.get("mask_cooldown_s", 2.0))
        # session -> replica affinity map bound (oldest evicted)
        self.sticky_sessions = bool(d.get("sticky_sessions", True))
        self.max_sessions = int(d.get("max_sessions", 4096))


class RoutedStream(ResponseStream):
    """Caller-facing stream that survives replica fail-over: the pump
    re-points ``_inner`` at the new replica's stream; ``cancel()``
    reaches whichever replica currently serves the request."""

    def __init__(self, uid: int):
        super().__init__(uid)
        self._inner: Optional[ResponseStream] = None
        # per-request disagg handoff report (set at finish by the disagg
        # router; None under homogeneous routing): end-to-end KV-chain
        # transfer latency and bytes moved (0 = zero-copy ref acquire)
        self.handoff_ms: Optional[float] = None
        self.handoff_bytes: Optional[int] = None
        # per-request phase breakdown (disagg.REQUEST_TIMELINE_KEYS),
        # stamped at finish by the disagg router
        self.timeline: Optional[Dict[str, object]] = None

    def _attach(self, inner: ResponseStream) -> None:
        with self._cond:
            self._inner = inner
            cancelled = self._cancel_requested
        if cancelled:  # cancel raced the (re)dispatch
            inner.cancel()

    def cancel(self) -> None:
        super().cancel()
        with self._cond:
            inner = self._inner
        if inner is not None:
            inner.cancel()


class _RoutedRequest:
    """Router-side bookkeeping for one in-flight request."""

    __slots__ = ("uid", "prompt", "params", "priority", "deadline",
                 "stream", "replica", "inner", "delivered", "failovers",
                 "trace_id", "span", "phase", "payload", "leg_t0", "legs",
                 "t_submit")

    def __init__(self, uid: int, prompt: List[int], params: SamplingParams,
                 priority: int, deadline: Optional[float],
                 stream: RoutedStream):
        self.uid = uid
        self.prompt = prompt
        self.params = params
        self.priority = priority
        self.deadline = deadline            # absolute time.monotonic()
        self.stream = stream
        self.replica: Optional[ServingReplica] = None
        self.inner: Optional[ResponseStream] = None
        self.delivered: List[int] = []
        self.failovers = 0
        self.trace_id = ""
        self.span = None
        # disaggregated tiers (serving/disagg.py DisaggRouter): the leg
        # this request currently runs (None = homogeneous routing) and
        # the KV payload riding from the prefill leg to the decode leg
        self.phase: Optional[str] = None
        self.payload = None
        # per-leg wall timing for the RequestTimeline export (disagg):
        # _dispatch stamps leg_t0, the disagg pump banks phase -> ms
        self.leg_t0 = 0.0
        self.legs: Dict[str, float] = {}
        self.t_submit = time.monotonic()


class Router:
    """Replica-set front door with the ``InferenceServer`` surface."""

    def __init__(self, replicas: ReplicaSet, config: Optional[dict] = None,
                 telemetry=None):
        self.replicas = replicas
        self.cfg = RouterConfig(config)
        self.telemetry = telemetry
        if telemetry is not None:
            self.tracer = telemetry.tracer
            registry = telemetry.registry
        else:
            self.tracer, _ = make_span_recorder(False, False, 0, 0)
            registry = None
        self.metrics = RouterMetrics(len(replicas), registry=registry)
        self._lock = threading.Lock()
        self._uid = 0
        self._inflight: Dict[int, int] = {r.index: 0 for r in replicas}
        self._sessions: "OrderedDict[str, int]" = OrderedDict()
        self._pumps: List[threading.Thread] = []
        self._started = False
        self._stop_requested = False
        # dispatch mask: replica index -> monotonic expiry (None =
        # indefinite, i.e. supervisor quarantine).  Masked replicas take
        # no NEW legs; their in-flight streams keep pumping (and fail
        # over organically if the replica then dies).
        self._mask: Dict[int, Optional[float]] = {}
        # consecutive failed legs per replica (cleared by a completed leg
        # or an unmask) — feeds the mask_after_failures cooldown
        self._leg_failures: Dict[int, int] = {}
        # deterministic jitter source for fail-over backoff: chaos runs
        # stay reproducible under a fixed fault plan
        self._rng = random.Random(0x0D15)
        # fault-injection hook (resilience/chaos.py attach_chaos); None
        # keeps the dispatch path injection-free
        self._chaos = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "Router":
        if self._started:
            raise RuntimeError("router already started")
        self._started = True
        for rep in self.replicas:
            # before replicas.start(): server.start() then wires the
            # adopted tracer into its engine itself
            self._adopt_tracer(rep)
        self.replicas.start()
        self.metrics.set_alive(len(self.replicas.alive))
        return self

    def _adopt_tracer(self, rep: ServingReplica) -> None:
        """Replica servers built without a telemetry hub carry DISABLED
        tracers — under a traced router their serve-side spans (queue
        wait, prefill, decode, handoff) would simply vanish.  Point such
        a server at the router's tracer so ONE Chrome trace shows a
        request end to end across tiers.  A server that brought its own
        enabled tracer keeps it (it owns its export)."""
        srv = rep.server
        if not self.tracer.enabled or srv.tracer.enabled:
            return
        srv.tracer = self.tracer
        srv.admission.tracer = self.tracer
        srv._loop_trace_id = self.tracer.new_trace_id()
        if srv._thread is not None:
            # grown/respawned replica, serve loop already running: redo
            # the tracer wiring start() does (attribute stores are atomic
            # — the loop picks the new tracer up on its next span)
            if hasattr(srv.engine, "tracer"):
                srv.engine.tracer = self.tracer
                srv.engine.trace_id = srv._loop_trace_id
            if srv._spec is not None:
                srv._spec.bind(self.tracer, srv._loop_trace_id,
                               srv.metrics)

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Drain (or abort) every replica, then join the pumps."""
        self._stop_requested = True
        try:
            self.replicas.stop(drain=drain, timeout=timeout)
        except Exception as e:
            # a replica that died mid-run re-raises its loop error here —
            # but its requests were already failed over (or terminated
            # through their streams), which is the contract that matters
            # at the router tier.  Surface it as a warning, not a crash.
            log_dist(f"router: replica stop raised: {e!r}",
                     level="warning")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            pumps = list(self._pumps)
        for t in pumps:
            t.join(None if deadline is None
                   else max(0.1, deadline - time.monotonic()))
        if self.telemetry is not None:
            snap = self.snapshot()
            agg = snap["aggregate"]
            flat = _flatten(snap)
            # record_serving_step reads tokens_out / tokens_per_sec at the
            # TOP level (the flattened copies carry aggregate_ prefixes)
            flat["tokens_out"] = float(agg["tokens_out"])
            flat["tokens_per_sec"] = float(agg["tokens_per_sec"])
            self.telemetry.record_serving_step(self.metrics.requests, flat)

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    # -- dispatch masking ------------------------------------------------
    def mask(self, index: int, cooldown_s: Optional[float] = None) -> None:
        """Stop NEW legs landing on a replica.  ``cooldown_s`` bounds the
        mask (leg-failure cooldown); ``None`` masks until :meth:`unmask`
        (supervisor quarantine).  In-flight streams on the replica keep
        pumping — masking is an admission decision, not an eviction."""
        with self._lock:
            self._mask[index] = (None if cooldown_s is None
                                 else time.monotonic() + float(cooldown_s))

    def unmask(self, index: int) -> None:
        """Readmit a replica to dispatch and forget its failure streak
        (the supervisor calls this after a successful respawn)."""
        with self._lock:
            self._mask.pop(index, None)
            self._leg_failures.pop(index, None)

    def masked_indices(self) -> Set[int]:
        """Currently-masked replica indices; expired cooldowns are
        dropped on read, so this is also the mask GC."""
        now = time.monotonic()
        with self._lock:
            for i in [i for i, until in self._mask.items()
                      if until is not None and until <= now]:
                del self._mask[i]
            return set(self._mask)

    def set_brownout(self, level: str) -> None:
        """Fan a brownout level out to every replica server (the fleet
        supervisor's actuation point — one ladder, N enforcers)."""
        for rep in self.replicas:
            rep.server.set_brownout(level)

    # -- dispatch policy -------------------------------------------------
    def _candidates(self, tier: Optional[str],
                    exclude: Sequence[int]) -> List[ServingReplica]:
        """Dispatchable replicas for a leg; the disagg router narrows
        this to the leg's tier (with cross-tier fallback)."""
        return self._unmasked(
            [r for r in self.replicas.alive if r.index not in exclude])

    def _unmasked(self, reps: List[ServingReplica]) -> List[ServingReplica]:
        masked = self.masked_indices()
        if not masked:
            return reps
        keep = [r for r in reps if r.index not in masked]
        # availability beats cleanliness: when EVERY candidate is masked
        # (tiny fleet mid-heal), dispatching to a suspect replica still
        # dominates failing the request outright — fail-over covers us
        # if the suspicion was right
        return keep or reps

    def _score(self, rep: ServingReplica,
               tier: Optional[str] = None) -> float:
        with self._lock:
            # .get, not []: the replica may have been grown/respawned
            # into the set after this router was constructed
            inflight = self._inflight.get(rep.index, 0)
        # dispatch_headroom, not kv_headroom: pages the prefix cache
        # could evict on demand are capacity, not occupancy — scoring by
        # the raw free list makes the router spill away from exactly the
        # cache-warm replica that would serve the request best
        return rep.dispatch_headroom - self.cfg.queue_weight * (
            rep.queue_load + inflight)

    def _choose(self, exclude: Sequence[int] = (),
                session: Optional[str] = None,
                tier: Optional[str] = None) -> ServingReplica:
        alive = self._candidates(tier, exclude)
        if not alive:
            raise ServingError("no live replica to dispatch to")
        # tier-local affinity: under disagg a session pins one replica
        # PER TIER (its prefill cache and its decode cache both stay warm)
        skey = (session if session is None or tier is None
                else f"{tier}:{session}")
        if skey is not None and self.cfg.sticky_sessions:
            with self._lock:
                idx = self._sessions.get(skey)
                if idx is not None:
                    # refresh on HIT too: an actively-used session must
                    # not be the first one the bound evicts
                    self._sessions.move_to_end(skey)
            if idx is not None and idx not in exclude:
                for r in alive:
                    if r.index == idx:
                        return r
        # max score; ties broken by replica index for determinism
        best = max(alive, key=lambda r: (self._score(r, tier), -r.index))
        if skey is not None and self.cfg.sticky_sessions:
            with self._lock:
                self._sessions[skey] = best.index
                self._sessions.move_to_end(skey)
                while len(self._sessions) > self.cfg.max_sessions:
                    self._sessions.popitem(last=False)
        return best

    def _dispatch(self, rr: _RoutedRequest, exclude: Sequence[int] = (),
                  session: Optional[str] = None) -> None:
        """Pick a replica and submit (the remainder of) the request to
        it.  Replicas whose queue rejects are excluded and the next one
        tried; raises the last error when every live replica refused.
        Under disagg, ``rr.phase`` selects the tier and the leg shape:
        a prefill leg runs prompt→1 token with the KV export armed, a
        decode leg carries the exported payload into admission."""
        if self._chaos is not None:
            for f in self._chaos.fire("router.dispatch"):
                if f.kind == "slow_replica":
                    time.sleep(float(f.params.get("delay_ms", 50.0)) / 1e3)
                elif f.kind == "handoff_fail" and rr.payload is not None:
                    # payload lost in transit: the decode leg re-prefills
                    # from the prompt (the documented degrade path)
                    rr.payload = None
        remaining = rr.params.max_new_tokens - len(rr.delivered)
        params = (rr.params if not rr.delivered else
                  dataclasses.replace(rr.params, max_new_tokens=remaining))
        submit_kw = {}
        if rr.phase == "prefill":
            params = dataclasses.replace(params, max_new_tokens=1)
            submit_kw["handoff"] = True
        elif rr.phase == "decode" and rr.payload is not None:
            submit_kw["kv_payload"] = rr.payload
        prompt = rr.prompt + rr.delivered
        tried = list(exclude)
        last_error: Optional[ServingError] = None
        while True:
            try:
                rep = self._choose(exclude=tried, session=session,
                                   tier=rr.phase)
            except ServingError:
                raise (last_error or
                       ServingError("no live replica to dispatch to"))
            deadline_s = (None if rr.deadline is None
                          else rr.deadline - time.monotonic())
            self._adopt_tracer(rep)   # grown/respawned after start()
            trace_kw = {}
            if (self.tracer.enabled
                    and rep.server.tracer is self.tracer):
                # same tracer on both sides -> the serve-side request
                # span chains under the routed-request root span and
                # keeps the caller-visible trace_id; a server with its
                # OWN tracer gets neither (span ids are per-tracer
                # counters — a foreign parent id would alias)
                trace_kw = {"trace_id": rr.trace_id,
                            "parent_span": rr.span}
            try:
                inner = rep.server.submit(prompt, params,
                                          priority=rr.priority,
                                          deadline_s=deadline_s,
                                          **submit_kw, **trace_kw)
            except QueueFull as e:
                tried.append(rep.index)
                last_error = e
                continue
            rr.replica = rep
            rr.inner = inner
            rr.leg_t0 = time.monotonic()
            rr.stream._attach(inner)
            with self._lock:
                self._inflight[rep.index] = \
                    self._inflight.get(rep.index, 0) + 1
            self.metrics.record_route(rep.index)
            if self.tracer.enabled:
                self.tracer.instant("router.dispatch", rr.trace_id,
                                    uid=rr.uid, replica=rep.index,
                                    failovers=rr.failovers)
            return

    # -- client API ------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               params: Optional[SamplingParams] = None, priority: int = 0,
               deadline_s: Optional[float] = None,
               session: Optional[str] = None,
               phase: Optional[str] = None) -> ResponseStream:
        """Same contract as ``InferenceServer.submit`` plus ``session``:
        requests sharing a session key stick to one replica while it
        lives, which is what lets its replica-local prefix cache serve
        the session's shared prompt.  ``phase`` is internal — the
        disagg subclass opens every request on its prefill leg."""
        if not self._started or self._stop_requested:
            raise QueueFull("router not accepting requests")
        params = params or SamplingParams()
        self.metrics.record_submit()
        with self._lock:
            uid = self._uid
            self._uid += 1
        rr = _RoutedRequest(
            uid=uid, prompt=[int(t) for t in prompt], params=params,
            priority=priority,
            deadline=(None if deadline_s is None
                      else time.monotonic() + deadline_s),
            stream=RoutedStream(uid))
        rr.phase = phase
        if self.tracer.enabled:
            rr.trace_id = rr.stream.trace_id = self.tracer.new_trace_id()
            rr.span = self.tracer.span("router.request", rr.trace_id).set(
                uid=uid, prompt_tokens=len(rr.prompt),
                max_new_tokens=params.max_new_tokens)
        try:
            self._dispatch(rr, session=session)
        except (ServingError, ValueError):
            # ValueError = per-request validation from the replica server
            # (empty prompt, bad sampling params, impossible KV need) —
            # it must close the books like any rejection or the root span
            # leaks open and requests/rejected counters drift apart
            self.metrics.record_reject()
            if rr.span is not None:
                rr.span.end(outcome="rejected")
                rr.span = None
            raise
        pump = threading.Thread(target=self._pump, args=(rr, session),
                                name=f"ds-router-pump-{uid}", daemon=True)
        with self._lock:
            # prune finished pumps so a long-lived router stays O(inflight)
            self._pumps = [t for t in self._pumps if t.is_alive()]
            self._pumps.append(pump)
        pump.start()
        return rr.stream

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Blocking convenience wrapper (``InferenceServer.generate``
        parity through the routed path)."""
        streams = [self.submit(p, SamplingParams(
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id, seed=i))
            for i, p in enumerate(prompts)]
        return [s.result() for s in streams]

    # -- pump ------------------------------------------------------------
    def _pump(self, rr: _RoutedRequest, session: Optional[str]) -> None:
        try:
            self._pump_loop(rr, session)
        except BaseException as e:  # noqa: BLE001 — last-resort backstop
            # anything escaping the leg loop (a replica's plain ValueError
            # on re-submit, a bug in the router itself) must still reach
            # the caller: a silently-dead pump leaves the stream open and
            # the caller blocked forever
            log_dist(f"router: pump for request {rr.uid} died: {e!r}",
                     level="error")
            self._finish(rr, ServingError(
                f"request {rr.uid}: router pump died: {e!r}"))

    def _pump_loop(self, rr: _RoutedRequest, session: Optional[str]) -> None:
        out = rr.stream
        while True:
            leg = (self.tracer.span("router.leg", rr.trace_id, rr.span)
                   .set(uid=rr.uid, replica=rr.replica.index)
                   if self.tracer.enabled else None)
            try:
                for tok in rr.inner:
                    rr.delivered.append(tok)
                    out._put_token(tok)
                self._leg_done(rr)
                with self._lock:
                    # a completed leg ends the replica's failure streak
                    self._leg_failures.pop(rr.replica.index, None)
                if leg is not None:
                    leg.end(outcome="completed")
                self._finish(rr, None)
                return
            except ServingError as e:
                self._leg_done(rr)
                if leg is not None:
                    leg.end(outcome=type(e).__name__)
                err = self._on_leg_error(rr, e, session)
                if err is not _RETRY:
                    self._finish(rr, err)
                    return

    def _leg_done(self, rr: _RoutedRequest) -> None:
        """Exactly-once inflight release per dispatched leg."""
        with self._lock:
            self._inflight[rr.replica.index] -= 1

    def _on_leg_error(self, rr: _RoutedRequest, e: ServingError,
                      session: Optional[str]):
        """Decide: propagate (returns the terminal error / None) or
        fail over (returns _RETRY after re-dispatching)."""
        rep = rr.replica
        self.metrics.set_alive(len(self.replicas.alive))
        if rr.stream.cancel_requested:
            return RequestCancelled(f"request {rr.uid} cancelled")
        if isinstance(e, DeadlineExceeded):
            return e
        delivered = rr.delivered
        eos = rr.params.eos_token_id
        if (len(delivered) >= rr.params.max_new_tokens
                or (eos is not None and delivered and delivered[-1] == eos)):
            # the output was already complete when the replica died —
            # nothing left to recompute
            return None
        if rep.alive:
            # a healthy replica failed THIS request for per-request
            # reasons (impossible KV need, max_preemptions, …); another
            # replica with the same config would fail it the same way
            return e
        if self._stop_requested:
            return e
        if rr.failovers >= self.cfg.max_failovers:
            return ServingError(
                f"request {rr.uid} failed over {rr.failovers}x, giving "
                f"up") if rr.failovers else e
        rr.failovers += 1
        self.metrics.record_failover()
        # failure streak -> cooldown mask: after N consecutive failed
        # legs the replica stops being anyone's dispatch target for
        # mask_cooldown_s (a crash-looping replica otherwise keeps
        # winning the score race the moment it respawns empty)
        with self._lock:
            streak = self._leg_failures.get(rep.index, 0) + 1
            self._leg_failures[rep.index] = streak
        if (self.cfg.mask_after_failures > 0
                and streak >= self.cfg.mask_after_failures):
            self.mask(rep.index, cooldown_s=self.cfg.mask_cooldown_s)
        if self.tracer.enabled:
            self.tracer.instant("router.failover", rr.trace_id, uid=rr.uid,
                                from_replica=rep.index,
                                delivered=len(delivered))
        log_dist(f"router: replica r{rep.index} died with request "
                 f"{rr.uid} in flight ({len(delivered)} tokens out) — "
                 "failing over", level="warning")
        # bounded exponential backoff with jitter, slept in THIS
        # request's own pump thread (nobody else waits on it): the k-th
        # fail-over of a request waits ~base·2^(k-1), so a mass crash
        # spreads its re-dispatch burst instead of thundering onto the
        # first surviving replica
        if self.cfg.backoff_base_s > 0:
            delay = min(self.cfg.backoff_base_s * (2 ** (rr.failovers - 1)),
                        self.cfg.backoff_cap_s)
            with self._lock:
                delay *= 0.5 + 0.5 * self._rng.random()
            time.sleep(delay)
        try:
            self._dispatch(rr, exclude=[rep.index], session=session)
        except ServingError as e2:
            return e2
        return _RETRY

    def _finish(self, rr: _RoutedRequest,
                error: Optional[ServingError]) -> None:
        if rr.span is not None:
            rr.span.end(outcome=("completed" if error is None
                                 else type(error).__name__),
                        generated=len(rr.delivered),
                        failovers=rr.failovers)
            rr.span = None
        rr.stream._finish(error)

    # -- reading ---------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        snap = self.metrics.snapshot()
        snap["aggregate"] = self.replicas.snapshot()
        return snap


_RETRY = object()  # sentinel: _on_leg_error re-dispatched, keep pumping


def _flatten(d: Dict, prefix: str = "") -> Dict[str, float]:
    """Nested snapshot -> flat float dict for record_serving_step."""
    out: Dict[str, float] = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, f"{key}_"))
        elif isinstance(v, (int, float, bool)):
            out[key] = float(v)
    return out
