"""MII-style async serving loop over ``InferenceEngineV2``.

Analog of DeepSpeed-MII's ``RaggedBatchBase``/``MIIPipeline`` serve thread
(mii/batching/ragged_batching.py): the server owns an engine on a
background thread and exposes an async request API —

    server = InferenceServer(engine)
    server.start()
    stream = server.submit([1, 2, 3], SamplingParams(max_new_tokens=16))
    for tok in stream:          # tokens appear as they are decoded
        ...
    server.stop()               # graceful drain

Loop anatomy (docs/SERVING.md has the diagram):

    submit() → bounded queue → admission (slots + KV watermarks)
             → SplitFuse scheduler → engine.step() → per-request streams

Robustness: cancellation and deadlines are swept every iteration; KV
exhaustion preempts the lowest-priority/youngest running request
(recompute-style requeue at the front of the queue) instead of crashing;
``stop()`` drains in-flight work before joining the thread.

Threading contract: the engine is touched ONLY by the serve thread.
``submit``/``cancel``/stream reads are safe from any thread.  Sampling
runs on-device when every running request is greedy (one int32 per slot
crosses to the host); any non-greedy request switches the step to the
full-logits path with per-request host RNGs, so heterogeneous sampling
params coexist in one ragged batch.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.ragged import KVCacheExhausted
from deepspeed_tpu.serving.admission import (BROWNOUT_LEVELS,
                                             AdmissionConfig,
                                             AdmissionController,
                                             BrownoutConfig, brownout_index)
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.prefix_cache import PrefixCache, PrefixCacheConfig
from deepspeed_tpu.serving.request import (DeadlineExceeded,
                                           GenerationRequest,
                                           RequestCancelled, RequestShed,
                                           ResponseStream, SamplingParams,
                                           ServingError)
from deepspeed_tpu.telemetry.flight import (Watchdog, dump_bundle,
                                            make_span_recorder,
                                            make_watchdog)
from deepspeed_tpu.utils.logging import log_dist

# ladder positions consulted on the hot paths (admission/spec/submit) —
# resolved once so enforcement is integer compares, not tuple scans
_BL_SHED_SPEC = brownout_index("shed_speculation")
_BL_CAP_DECODE = brownout_index("cap_decode")
_BL_SHED_LOW = brownout_index("shed_low_priority")
_BL_REJECT_NEW = brownout_index("reject_new")


def _softmax(x: np.ndarray) -> np.ndarray:
    x = x - x.max()
    e = np.exp(x)
    return e / e.sum()


def _host_sample(logits: np.ndarray, params: SamplingParams,
                 rng: np.random.Generator) -> int:
    """Numpy twin of ``model.sample_tokens`` for the heterogeneous-
    sampling step (greedy argmax is bit-identical to the device path)."""
    if params.greedy:
        return int(np.argmax(logits))
    x = logits.astype(np.float64) / max(params.temperature, 1e-6)
    if params.top_k > 0:
        kth = np.sort(x)[-min(params.top_k, x.size)]
        x = np.where(x >= kth, x, -np.inf)
    if params.top_p < 1.0:
        order = np.argsort(-x)
        p_sorted = _softmax(x[order])
        keep = (np.cumsum(p_sorted) - p_sorted) < params.top_p
        kept = order[keep]
        masked = np.full_like(x, -np.inf)
        masked[kept] = x[kept]
        x = masked
    return int(rng.choice(x.size, p=_softmax(x)))


class ServerConfig:
    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        self.admission = AdmissionConfig(d.get("admission", {}))
        # paged prefix cache (serving/prefix_cache.py): shared-prefix
        # requests adopt already-written KV pages instead of re-prefilling
        self.prefix_cache = PrefixCacheConfig(d.get("prefix_cache", {}))
        # how long the idle loop parks before re-sweeping deadlines
        self.idle_wait_s = float(d.get("idle_wait_s", 0.02))
        # namespaces monitor-export tags (serving/<label>/…) so N replica
        # servers under one router stay distinguishable
        self.metrics_label = str(d.get("metrics_label", ""))
        # export metrics through `monitor` every N engine steps (0 = only
        # at stop()); the monitor is any object with write_events()
        self.metrics_interval_steps = int(d.get("metrics_interval_steps", 0))
        # time-bound the latency percentile windows (seconds; 0 = count-
        # bounded only): under a FleetSampler an idle replica's p95 must
        # decay instead of pinning at its last burst
        self.metrics_window_s = float(d.get("metrics_window_s", 0.0))
        # standalone span tracing / flight recorder (same keys as the
        # engine's telemetry.tracing / telemetry.flight blocks); ignored
        # when a telemetry hub is passed — the hub's tracer/ring win so
        # train + serve spans land in ONE trace file
        self.tracing = dict(d.get("tracing", {}))
        self.flight = dict(d.get("flight", {}))
        # graceful-degradation ladder knobs (admission.py BrownoutConfig);
        # the LEVEL is pushed by a FleetSupervisor via set_brownout — a
        # standalone server stays at "normal" forever
        self.brownout = BrownoutConfig(d.get("brownout", {}))


class InferenceServer:
    """Continuous-batching serve loop owning one ``InferenceEngineV2``."""

    def __init__(self, engine: InferenceEngineV2,
                 config: Optional[dict] = None, monitor: Any = None,
                 telemetry: Any = None, spec_decoder: Any = None):
        self.engine = engine
        self.cfg = ServerConfig(config)
        self.monitor = monitor
        # speculative decoding (serving/disagg.py SpeculativeDecoder): a
        # draft model living in this serve loop.  Anything with
        # round()/flush() works; None disables per-request `speculative`
        self._spec = spec_decoder
        # a telemetry.Telemetry hub: serving histograms register in ITS
        # registry (one Prometheus exposition for both hot loops) and the
        # loop emits kind="serving" StepRecords to the same JSONL
        self.telemetry = telemetry
        self.metrics = ServingMetrics(
            registry=telemetry.registry if telemetry is not None else None,
            label=self.cfg.metrics_label,
            window_s=self.cfg.metrics_window_s)
        self.admission = AdmissionController(self.cfg.admission)
        # owned and touched ONLY by the serve thread (like the engine);
        # refcounts on the engine's allocator keep shared pages safe
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.cfg.prefix_cache, engine.state_manager.allocator,
                        engine.cfg.block_size)
            if self.cfg.prefix_cache.enabled else None)
        # -- spans + flight recorder (telemetry/tracing.py, flight.py) --
        # one hub predicate (`telemetry is not None`) at every site — it
        # must agree with stop()'s standalone-trace-export gate or a hub
        # that took this branch would record spans nobody exports
        if telemetry is not None:
            self.tracer = telemetry.tracer
            self._flight_ring = telemetry.flight_ring
        else:
            # same bootstrap rule as the Telemetry hub (one shared
            # factory: flight alone also enables span recording so
            # bundle rings are populated)
            self.tracer, self._flight_ring = make_span_recorder(
                tracing_enabled=self.cfg.tracing.get("enabled", False),
                flight_enabled=self.cfg.flight.get("enabled", False),
                max_events=self.cfg.tracing.get("max_events", 0),
                ring_size=self.cfg.flight.get("ring_size", 0))
        self.admission.tracer = self.tracer
        # trace export gated on the tracing block itself (flight-only
        # configs record spans for the ring but write no trace file)
        self._trace_path = (str(self.cfg.tracing.get("trace_path", ""))
                            if self.cfg.tracing.get("enabled") else "")
        self._loop_trace_id = (self.tracer.new_trace_id()
                               if self.tracer.enabled else "")
        self._watchdog: Optional[Watchdog] = None
        self._flight_dir: Optional[str] = None
        # the watchdog skips this process's first engine.step (jit
        # compile time is not a stall) — see _step_once
        self._first_engine_step_done = False
        if telemetry is not None:
            # hub present: its flight block decides, server blocks are
            # ignored end-to-end — building a watchdog from the server's
            # flight config here would pair it with the hub's (possibly
            # disabled) tracer and dump forever-empty rings
            self._watchdog = telemetry.make_watchdog("serve")
            if self._watchdog is not None:
                self._flight_dir = self._watchdog.output_dir
        else:
            # same factory as the hub: falsy config values (deadline_s 0,
            # empty output_dir) must fall back identically on both paths
            self._watchdog = make_watchdog(
                "serve", self.cfg.flight, ring=self._flight_ring,
                telemetry=telemetry, tracer=self.tracer)
            if self._watchdog is not None:
                self._flight_dir = self._watchdog.output_dir
        # fault injection (resilience/chaos.py): attach_chaos wires an
        # injector here; None keeps the loop at one attr check per tick
        self._chaos = None
        # graceful-degradation ladder position (index into
        # BROWNOUT_LEVELS); written via set_brownout from the supervisor
        # thread, read by the serve loop + submit — int store/load, no lock
        self._brownout = 0
        # liveness-probe surface (serving/supervisor.py FleetSupervisor):
        # the serve loop stamps loop_beat_t every iteration and folds each
        # engine-step wall time into step_ema_s — a stale beat with queued
        # work means "stuck", a step EMA far above the peer median means
        # "straggler".  Plain attribute writes: probes tolerate staleness.
        self.loop_beat_t: Optional[float] = None
        self.loop_iters = 0
        self.step_ema_s = 0.0
        self._active: Dict[int, GenerationRequest] = {}
        self._uid = itertools.count()
        self._uid_lock = threading.Lock()
        self._rngs: Dict[int, np.random.Generator] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop_requested = False
        self._abort = False
        self._loop_error: Optional[BaseException] = None
        # per-seq hard cap, checked at submit so an impossible request
        # fails fast instead of crashing the loop mid-decode (page
        # accounting lives in the ENGINE — engine.seq_blocks — so
        # admission and allocator can never disagree)
        self._total_blocks = engine.cfg.num_blocks - 1

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "InferenceServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        if self._stop_requested or self._loop_error is not None:
            # stop() closed admission and left the terminal flags set; a
            # "restarted" loop would exit immediately while submits get
            # QueueFull — fail loudly instead of running dead
            raise RuntimeError(
                "server already stopped; create a new InferenceServer")
        if self._watchdog is not None:
            self._watchdog.on_fire = \
                lambda _bundle: self.metrics.record_flight_dump()
            self._watchdog.start()
        # the engine annotates its ragged dispatch into the same trace,
        # chained to this loop's trace id
        if hasattr(self.engine, "tracer"):
            self.engine.tracer = self.tracer
            self.engine.trace_id = self._loop_trace_id
        if self._spec is not None:
            # spec.draft / spec.verify spans + accept-rate counters land
            # in THIS loop's trace and registry
            self._spec.bind(self.tracer, self._loop_trace_id, self.metrics)
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="ds-serve-loop", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop the loop.  ``drain=True`` finishes all queued + running
        requests first; ``drain=False`` cancels them.

        Fail-fast contract: a crashed loop must not make a draining
        ``stop()`` wait out the full timeout — the join polls, and the
        moment ``_loop_error`` is set (the crash handler records it
        FIRST, before any cleanup that might itself wedge on the broken
        engine) the wait collapses to a short grace period and the loop
        error is raised, chained."""
        self.admission.close()
        self._stop_requested = True
        if not drain:
            self._abort = True
        thread = self._thread
        if thread is not None:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while thread.is_alive():
                if self._loop_error is not None:
                    # dead loop: give its crash handler a short grace to
                    # terminate the streams, then surface the error
                    # below instead of waiting out the drain timeout
                    thread.join(1.0)
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError(f"serve loop still running after "
                                       f"{timeout}s (drain={drain})")
                thread.join(0.05)
            self._thread = None
        if self._watchdog is not None:
            self._watchdog.stop()
        if self.prefix_cache is not None:
            # every sequence is flushed by now, so all entries are
            # cache-only owners — return the pool whole to the engine
            self.prefix_cache.clear()
        if (self.telemetry is None and self._trace_path
                and self.tracer.enabled):
            # standalone tracer: nobody else will flush the trace file
            # (with a hub, Telemetry.close() owns the export)
            try:
                self.tracer.export_chrome_trace(self._trace_path)
            except OSError as e:
                log_dist(f"serving: trace export failed: {e}",
                         level="warning")
        if self.monitor is not None:
            self.metrics.write_to(self.monitor, self.metrics.snapshot()["steps"])
        if self.telemetry is not None:
            self.telemetry.record_serving_step(self.metrics.steps,
                                               self.metrics.snapshot())
        if self._loop_error is not None:
            raise RuntimeError("serve loop died") from self._loop_error

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    # -- graceful degradation (admission.py BROWNOUT_LEVELS) -------------
    @property
    def brownout_level(self) -> str:
        return BROWNOUT_LEVELS[self._brownout]

    def set_brownout(self, level: str) -> None:
        """Move this server to a ladder level (idempotent; any thread).
        The supervisor is the normal caller — levels compose downward, so
        ``reject_new`` also sheds low priority, caps decode concurrency
        and disables speculation."""
        self._brownout = brownout_index(level)

    # -- client API ------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               params: Optional[SamplingParams] = None, priority: int = 0,
               deadline_s: Optional[float] = None,
               timeout: Optional[float] = None, handoff: bool = False,
               kv_payload: Any = None, trace_id: str = "",
               parent_span: Any = None) -> ResponseStream:
        """Enqueue one generation request; returns its stream immediately.

        ``deadline_s`` is a wall budget from now — queued or mid-decode,
        the request fails with ``DeadlineExceeded`` once it passes.
        ``timeout`` only applies to the enqueue itself under the "block"
        queue policy.  Raises ``QueueFull`` (reject policy / closed
        server) or ``ValueError`` for requests no admission order could
        ever run.

        Disaggregated tiers (serving/disagg.py): ``handoff=True`` makes
        the serve loop export the sequence's full KV blocks onto
        ``stream.handoff_payload`` at completion (the prefill leg);
        ``kv_payload`` hands such an export IN — admission adopts the
        covered pages instead of re-prefilling them (the decode leg).

        ``trace_id``/``parent_span`` stitch this request into a caller's
        existing trace (the router passes its routed-request span so a
        disagg request's prefill and decode legs chain under ONE
        trace_id); by default each request roots its own trace.
        ``parent_span`` must come from THIS server's tracer — span ids
        are per-tracer counters, so a foreign span would alias.
        """
        params = params or SamplingParams()
        if not len(prompt):
            raise ValueError("empty prompt")
        if params.max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {params.max_new_tokens}")
        # same boundary contract as model.check_sampling_params — a
        # degenerate value must fail HERE, not crash the serve loop at
        # this request's first sampled token (top_p=0 masks every logit)
        if not (0.0 < float(params.top_p) <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {params.top_p}")
        if params.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {params.top_k}")
        need = self.engine.seq_blocks(len(prompt) + params.max_new_tokens)
        if need > self.engine.max_seq_blocks:
            raise ValueError(
                f"prompt+output needs {need} KV blocks but the engine "
                f"allows {self.engine.max_seq_blocks} per sequence; raise "
                "num_blocks/max_context or shorten the request")
        # brownout gate: a shed submit is load shedding, not a failure —
        # typed RequestShed, counted as submitted + rejected + shed (the
        # same accounting shape as a QueueFull reject)
        lvl = self._brownout
        if lvl >= _BL_SHED_LOW:
            if lvl >= _BL_REJECT_NEW \
                    or priority < self.cfg.brownout.priority_floor:
                self.metrics.record_submit()
                self.metrics.record_reject()
                self.metrics.record_shed()
                raise RequestShed(
                    f"request shed at brownout level "
                    f"{BROWNOUT_LEVELS[lvl]!r} (priority={priority})")
        with self._uid_lock:
            uid = next(self._uid)
        req = GenerationRequest(
            uid=uid, prompt=list(prompt), params=params,
            stream=ResponseStream(uid), priority=priority,
            deadline=(None if deadline_s is None
                      else time.monotonic() + deadline_s),
            handoff=handoff, kv_payload=kv_payload)
        tr = self.tracer
        if tr.enabled:
            req.trace_id = req.stream.trace_id = (trace_id
                                                  or tr.new_trace_id())
            req.span_request = tr.span("serve.request", req.trace_id,
                                       parent_span).set(
                uid=uid, prompt_tokens=len(req.prompt),
                max_new_tokens=params.max_new_tokens)
            tr.instant("serve.enqueue", req.trace_id, uid=uid)
            req.span_phase = tr.span("serve.queue_wait", req.trace_id,
                                     req.span_request)
        self.metrics.record_submit()
        try:
            self.admission.offer(req, timeout=timeout)
        except ServingError:
            self.metrics.record_reject()
            if req.span_request is not None:
                req.span_phase.end(rejected=True)
                req.span_request.end(outcome="rejected")
            raise
        return req.stream

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Blocking convenience wrapper: ``engine.generate()`` parity
        through the serving path (used by tests and the bench row)."""
        streams = [self.submit(p, SamplingParams(
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id, seed=i))
            for i, p in enumerate(prompts)]
        return [s.result() for s in streams]

    # -- serve loop ------------------------------------------------------
    def _serve_loop(self) -> None:
        wd = self._watchdog
        try:
            while True:
                if wd is not None:
                    wd.beat()
                self.loop_beat_t = time.monotonic()
                self.loop_iters += 1
                if self._chaos is not None:
                    self._chaos_tick(self._chaos)
                if self._abort:
                    self._fail_everything(
                        RequestCancelled("server shutdown"))
                    return
                now = time.monotonic()
                self._sweep_queue(now)
                self._sweep_active(now)
                self._try_admit(now)
                self._update_gauges()
                if self.engine.scheduler.has_work:
                    self._step_once()
                elif self._stop_requested and len(self.admission) == 0 \
                        and not self._active:
                    return
                else:
                    self.admission.wait_for_work(self.cfg.idle_wait_s)
        except BaseException as e:  # never die silently: fail the streams
            # error FIRST: stop() fail-fasts on this flag, and the
            # cleanup below may itself wedge on the broken engine
            self._loop_error = e
            # close next: a submit() racing the cleanup must get
            # QueueFull, not an accepted request nobody will ever serve
            self.admission.close()
            if wd is not None:
                # a dead loop stops beating by definition — silence the
                # watchdog so the crash isn't double-reported as a stall
                wd.pause()
            log_dist(f"serving: loop crashed: {e!r}", level="error")
            self._dump_flight("serve_crash", e)
            self._fail_everything(ServingError(f"serve loop died: {e!r}"))

    def _chaos_tick(self, ch: Any) -> None:
        """The ``server.step`` injection point: act on every due fault
        (resilience/chaos.py decides *when*; the semantics live here).
        Crashes/hangs deliberately ride the loop's real failure paths —
        a ChaosError is indistinguishable from an organic death."""
        from deepspeed_tpu.resilience.chaos import ChaosError
        for f in ch.fire("server.step"):
            kind = f.kind
            if kind == "replica_crash":
                raise ChaosError(
                    f"injected replica_crash on {ch.target}")
            if kind == "replica_hang":
                # simulated wedge: thread alive, no beats, no progress.
                # Only stop()/kill() (the supervisor's quarantine path)
                # clears it; surfacing as a crash afterwards fails the
                # in-flight streams over instead of hanging them forever.
                while not self._stop_requested:
                    time.sleep(0.01)
                raise ChaosError(
                    f"injected replica_hang on {ch.target} "
                    "(cleared by stop)")
            if kind == "slow_replica":
                time.sleep(float(f.params.get("delay_ms", 50.0)) / 1e3)
            elif kind == "cancel_storm":
                # deterministic victims: the lowest-priority actives
                n = int(f.params.get("count", 2))
                victims = sorted(self._active.values(),
                                 key=lambda r: (r.priority, r.uid))[:n]
                for v in victims:
                    v.stream.cancel()
            elif kind == "admission_storm":
                burst = int(f.params.get("burst", 8))
                pr = int(f.params.get("priority", -100))
                mnt = int(f.params.get("max_new_tokens", 4))
                for _ in range(burst):
                    try:
                        self.submit([1, 2, 3],
                                    SamplingParams(max_new_tokens=mnt),
                                    priority=pr)
                    except ServingError:
                        break  # queue full / brownout already shedding

    def _dump_flight(self, reason: str,
                     error: Optional[BaseException] = None) -> None:
        """Crash forensics: ring + stacks + telemetry snapshot bundle
        (no flight config ⇒ no-op)."""
        if self._flight_dir is None:
            return
        try:
            dump_bundle(self._flight_dir, reason, ring=self._flight_ring,
                        telemetry=self.telemetry, error=error)
            self.metrics.record_flight_dump()
        except Exception:
            pass  # forensics must never mask the original failure

    def _fail_everything(self, err: ServingError) -> None:
        for req in self.admission.drain():
            self._finish(req, error=err)
        for uid in list(self._active):
            req = self._active.pop(uid)
            try:
                if uid in self.engine.state_manager:
                    self._flush_seq(uid)
            except Exception:
                # the crash handler may be running BECAUSE engine state
                # is inconsistent — a failing flush must not leave the
                # remaining streams unterminated
                pass
            self._finish(req, error=err)

    def _sweep_queue(self, now: float) -> None:
        """Cancelled/expired requests that never got admitted; under
        ``shed_low_priority``+ the below-floor queued requests shed too
        (strictly the lowest-priority class — the floor rule is the same
        one the submit gate applies to new arrivals)."""
        shed_floor = (self.cfg.brownout.priority_floor
                      if self._brownout >= _BL_SHED_LOW else None)
        # snapshot: drain() would drop healthy requests, so walk a copy
        for req in self.admission.snapshot():
            if req.stream.cancel_requested:
                if self.admission.remove(req):
                    self._finish(req, error=RequestCancelled(
                        f"request {req.uid} cancelled while queued"))
            elif req.expired(now):
                if self.admission.remove(req):
                    self._finish(req, error=DeadlineExceeded(
                        f"request {req.uid} deadline passed while queued"))
            elif shed_floor is not None and req.priority < shed_floor:
                if self.admission.remove(req):
                    self._finish(req, error=RequestShed(
                        f"request {req.uid} (priority={req.priority}) "
                        "shed from queue at brownout level "
                        f"{self.brownout_level!r}"))

    def _sweep_active(self, now: float) -> None:
        for uid in list(self._active):
            req = self._active[uid]
            err = None
            if req.stream.cancel_requested:
                err = RequestCancelled(f"request {uid} cancelled")
            elif req.expired(now):
                err = DeadlineExceeded(f"request {uid} deadline passed "
                                       f"after {req.n_generated} tokens")
            if err is not None:
                del self._active[uid]
                self._flush_seq(uid)
                self._finish(req, error=err)

    def _try_admit(self, now: float) -> None:
        """Admit queue head while slots + KV watermark allow (FIFO — a
        stuck head blocks later arrivals on purpose: skipping it would
        starve big requests under steady small-request load)."""
        eng = self.engine
        pc = self.prefix_cache
        while eng.state_manager.n_active < eng.state_manager.max_seqs:
            if self._brownout >= _BL_CAP_DECODE \
                    and len(self._active) >= self.cfg.brownout.decode_cap:
                # cap_decode: hold admissions so the running set stays
                # small — queued requests wait (outputs stay intact;
                # truncating decode lengths would not be bit-identical)
                break
            req = self.admission.peek()
            if req is None:
                break
            # Adopt the cached prefix FIRST: the acquired refs (>= 2 with
            # the cache's own) pin those pages against the eviction pass
            # below — and against this very request's need (adopted pages
            # are not new allocations).  If admission is abandoned this
            # tick, the refs are released before breaking.
            adopted, n_cached = pc.adopt(req.tokens) if pc else ([], 0)
            # A once-preempted request re-admits on its FULL remaining
            # need: optimistic re-admission would just bounce it through
            # another admit→exhaust→preempt cycle (observed thrash).
            conservative = (self.cfg.admission.reserve_decode
                            or req.preemptions > 0)
            need = eng.seq_blocks(len(req.tokens)
                                  + (req.remaining if conservative else 0)) \
                - len(adopted)
            if self.cfg.admission.reserve_decode:
                need += self._reserved_decode_blocks()
            if not self.admission.kv_admissible(eng, need) and pc:
                # reclaim idle cache pages down to the admission floor
                # before making anyone wait (or preempting live work)
                shortfall = self.admission.admission_shortfall(eng, need)
                if shortfall > 0:
                    pc.evict(shortfall)
            if not self.admission.kv_admissible(eng, need):
                if self._active:
                    if pc:
                        pc.release(adopted)
                    break  # running work will free pages; head waits
                # Progress guarantee: with the engine idle nothing will
                # ever free pages, so the watermark must yield — admit if
                # the request fits at all, else it can never run.
                if need > eng.free_blocks:
                    if pc:
                        pc.release(adopted)
                    assert self.admission.pop() is req
                    self._finish(req, error=ServingError(
                        f"request {req.uid} needs {need} KV blocks; only "
                        f"{eng.free_blocks} exist even with the pool "
                        "drained"))
                    continue
            popped = self.admission.pop()
            assert popped is req
            if req.kv_payload is not None:
                adopted, n_cached = self._import_handoff(req, adopted,
                                                         n_cached)
            eng.admit(req.uid, req.tokens, priority=req.priority,
                      front=req.preemptions > 0, cached_blocks=adopted,
                      num_cached=n_cached)
            if pc:
                self.metrics.record_prefix(n_cached)
                if n_cached and self.tracer.enabled:
                    self.tracer.instant("serve.prefix_hit", req.trace_id,
                                        uid=req.uid, tokens_saved=n_cached)
                # everything known at admission prefills this admission —
                # its full pages become cacheable at the first sampled
                # token (see _step_once)
                req.pending_insert = len(req.tokens)
            first_admission = req.admitted_at is None
            req.admitted_at = now
            if req.span_phase is not None:
                # queue_wait (or post-preemption requeue wait) ends here;
                # the prefill phase runs until this request's next token
                req.span_phase.end()
                req.span_phase = self.tracer.span(
                    "serve.prefill", req.trace_id, req.span_request).set(
                        uid=req.uid, tokens=len(req.tokens),
                        readmission=not first_admission)
            self._rngs.setdefault(
                req.uid, np.random.default_rng(req.params.seed))
            if first_admission:
                # re-admissions after preemption are service time, not
                # queue wait — recording them would double-count the
                # request and skew the distribution
                self.metrics.record_admit(now - req.submitted_at)
            self._active[req.uid] = req

    def _import_handoff(self, req: GenerationRequest, adopted: List[int],
                        n_cached: int):
        """Adopt a prefill replica's handed-off KV chain at admission.

        The payload and the local prefix cache share the chain-keyed
        identity (both are KV for the same leading tokens of
        ``req.tokens``), so any locally-adopted blocks are a prefix of
        the payload's — when the cache already covers the whole payload
        the handoff is a pure ref acquire (zero bytes moved); otherwise
        only the uncovered tail is written device-to-device.  Failures
        degrade to re-running prefill (correctness never depends on the
        import).  Returns the combined ``(cached_blocks, num_cached)``.
        """
        payload = req.kv_payload
        bs = self.engine.cfg.block_size
        pay_blocks = len(payload["tokens"]) // bs
        skip = len(adopted)
        t0 = time.monotonic()
        sp = (self.tracer.span("serve.handoff", req.trace_id,
                               req.span_request)
              if self.tracer.enabled else None)
        moved = 0
        try:
            if self._chaos is not None:
                # "server.handoff" injection point (import side): ride the
                # organic failure path below — degrade to re-prefill
                for f in self._chaos.fire("server.handoff"):
                    if f.kind == "handoff_fail":
                        from deepspeed_tpu.resilience.chaos import ChaosError
                        raise ChaosError("injected handoff_fail (import)")
            if skip < pay_blocks:
                blocks, n_tok, moved = self.engine.import_kv_chain(
                    payload, skip_blocks=skip)
                adopted = list(adopted) + blocks
                n_cached = n_tok
        except Exception as e:  # geometry mismatch / transient exhaustion
            log_dist(f"serving: handoff import for request {req.uid} "
                     f"failed ({e!r}); re-running prefill", level="warning")
            req.kv_payload = None
            if sp is not None:
                sp.end(uid=req.uid, failed=True)
            return adopted, n_cached
        import_s = time.monotonic() - t0
        self.metrics.record_handoff_in(moved, import_s)
        # the router reads these back for the per-request report
        payload["import_ms"] = import_s * 1e3
        payload["import_bytes"] = moved
        if sp is not None:
            sp.end(uid=req.uid, bytes=moved, blocks=len(adopted),
                   zero_copy=(moved == 0))
        return adopted, n_cached

    def _reserved_decode_blocks(self) -> int:
        """generate()-style worst-case growth of the running set (only
        consulted under ``reserve_decode=True``)."""
        eng = self.engine
        reserved = 0
        for req in self._active.values():
            seq = eng.state_manager.get(req.uid)
            final = eng.seq_blocks(len(seq.tokens) + req.remaining)
            reserved += max(0, final - len(seq.blocks))
        return reserved

    def _reclaim_cache(self, n_blocks: int) -> int:
        """Evict up to ``n_blocks`` idle prefix-cache pages (0 without a
        cache) — always tried before preempting live work: recomputing a
        cached prefix later is cheaper than recomputing a live request
        now."""
        if self.prefix_cache is None or n_blocks <= 0:
            return 0
        return self.prefix_cache.evict(n_blocks)

    def _step_once(self) -> None:
        """One engine step; KV exhaustion reclaims cache pages, then
        preempts, and retries next tick."""
        deficit = self.admission.low_watermark_deficit(self.engine)
        if deficit > 0 and len(self._active) > 1:
            # floor hit: reclaim idle cache pages first, shed live work
            # only if that was not enough
            if self._reclaim_cache(deficit) < deficit:
                self._preempt_one()
        all_greedy = all(r.params.greedy for r in self._active.values())
        spec_ready = self._spec_eligible()
        tr = self.tracer
        step_span = tr.span("serve.step", self._loop_trace_id)
        if tr.enabled:
            step_span.set(n_active=len(self._active), greedy=all_greedy,
                          speculative=spec_ready)
        # the first engine.step of the process pays the jit compile,
        # which can legitimately exceed any sane stall deadline — keep
        # the watchdog disarmed for it (same per-process rule as the
        # train engine's first-step skip)
        warm = not self._first_engine_step_done
        if warm and self._watchdog is not None:
            self._watchdog.pause()
        step_t0 = time.monotonic()
        try:
            try:
                if spec_ready:
                    # draft proposes, target verifies in ONE ragged step;
                    # each value is the accepted token burst (>= 1), and
                    # the engine's sequences already carry them
                    emitted = self._spec.round(self._active)
                elif all_greedy:
                    emitted = {u: [t] for u, t in
                               self.engine.step(temperature=0.0).items()}
                else:
                    logits = self.engine.step(return_logits=True)
                    emitted = {u: [_host_sample(out,
                                                self._active[u].params,
                                                self._rngs[u])]
                               for u, out in logits.items()
                               if u in self._active}
                # only a step that actually ran proves the compile is
                # behind us — KVCacheExhausted rolls back with nothing
                # run, so the retry still pays the first jit compile and
                # must keep the watchdog disarmed for it
                self._first_engine_step_done = True
            finally:
                if warm and self._watchdog is not None:
                    self._watchdog.resume()
        except KVCacheExhausted:
            step_span.end(kv_exhausted=True)
            # a step's worth of pages from the cache buys a retry without
            # touching live work; preempt only if the cache came up dry
            want = max(1, self.engine.seq_blocks(
                self.engine.scheduler.token_budget))
            if self._reclaim_cache(want) == 0:
                self._preempt_one()
            return
        except BaseException:
            # close the span before the crash handler runs so the dying
            # step is present in the flight ring it dumps
            step_span.end(crashed=True)
            raise
        step_span.end()
        self.metrics.record_step()
        if not warm:
            # straggler signal for the fleet supervisor: EMA of steady-
            # state step wall time (the compile-paying first step would
            # poison the average for the whole early window)
            dt = time.monotonic() - step_t0
            self.step_ema_s = (dt if self.step_ema_s == 0.0
                               else 0.8 * self.step_ema_s + 0.2 * dt)
        if (self.cfg.metrics_interval_steps and self.metrics.steps
                % self.cfg.metrics_interval_steps == 0):
            if self.monitor is not None:
                self.metrics.write_to(self.monitor, self.metrics.steps)
            if self.telemetry is not None:
                self.telemetry.record_serving_step(self.metrics.steps,
                                                   self.metrics.snapshot())
        now = time.monotonic()
        for uid, burst in emitted.items():
            req = self._active.get(uid)
            if req is None:       # flushed between schedule and fetch
                continue          # (cannot happen today; belt+braces)
            done = False
            for tok in burst:
                tok = int(tok)
                req.tokens.append(tok)
                if self.prefix_cache is not None and req.pending_insert:
                    # first sampled token of this admission ⇒ its prefill
                    # is complete: every full page under the admitted
                    # prefix now holds final KV and becomes shareable.
                    # Must run before any flush below — insert acquires
                    # the cache's refs.
                    seq = self.engine.state_manager.get(uid)
                    self.prefix_cache.insert(
                        req.tokens[:req.pending_insert], seq.blocks)
                    req.pending_insert = 0
                self.metrics.record_tokens(1)
                if req.n_generated == 1:
                    req.first_token_at = now
                    self.metrics.record_first_token(now - req.submitted_at)
                    if req.span_request is not None:
                        tr.instant("serve.first_token", req.trace_id,
                                   uid=uid)
                if (req.span_phase is not None
                        and req.span_phase.name == "serve.prefill"):
                    # prefill → decode at this request's first token of
                    # the current admission (re-prefills transition too)
                    req.span_phase.end()
                    req.span_phase = tr.span("serve.decode", req.trace_id,
                                             req.span_request).set(uid=uid)
                req.stream._put_token(tok)
                if req.span_request is not None:
                    tr.instant("serve.emit", req.trace_id, uid=uid,
                               token=tok)
                eos_hit = (req.params.eos_token_id is not None
                           and tok == req.params.eos_token_id)
                if eos_hit or req.remaining <= 0:
                    # a speculative burst may overshoot eos /
                    # max_new_tokens — undelivered tokens die with the
                    # flushed sequence
                    done = True
                    break
            if done:
                del self._active[uid]
                if req.handoff:
                    # prefill-tier leg: export the finished chain's full
                    # KV blocks for adoption by a decode replica (must
                    # precede the flush that frees them)
                    self._export_handoff(req)
                self._flush_seq(uid)
                self._finish(req)
            elif not spec_ready:
                # speculative bursts were appended to the engine sequence
                # by verify_step itself; a plain step's token must extend
                self.engine.extend(uid, burst[-1])

    def _spec_eligible(self) -> bool:
        """A speculative round needs EVERY active request greedy, opted
        in, and in steady-state decode (exactly one pending sampled
        token) — the decode tier's steady state.  Mixed batches (a
        prefill mid-flight, a non-greedy or non-speculative peer) run
        the plain step; speculation resumes when the batch is
        homogeneous again."""
        if self._spec is None or not self._active:
            return False
        if self._brownout >= _BL_SHED_SPEC:
            # shed_speculation: drop to plain greedy steps — outputs are
            # bit-identical by the acceptance rule, only latency changes,
            # and the draft model's step cost comes off the replica
            return False
        if len(self._active) > self.engine.scheduler.token_budget:
            # even k=0 needs one verify row per sequence; an active set
            # wider than the ragged budget must take the plain step path
            # (the scheduler splits it into budget-sized steps)
            return False
        sm = self.engine.state_manager
        for uid, req in self._active.items():
            p = req.params
            if not (p.greedy and p.speculative):
                return False
            if uid not in sm or sm.get(uid).uncached != 1:
                return False
        return True

    def _flush_seq(self, uid: int) -> None:
        """Release a sequence from the target engine AND the draft
        model's mirror (the speculative decoder self-heals a missing
        mirror, but a leaked one would pin draft KV pages forever)."""
        self.engine.flush(uid)
        if self._spec is not None:
            self._spec.flush(uid)

    def _export_handoff(self, req: GenerationRequest) -> None:
        """Export a completed handoff request's full KV blocks onto its
        stream (the prefill-tier half of a prefill→decode handoff).
        Failure degrades to no payload — the decode leg re-runs
        prefill."""
        t0 = time.monotonic()
        sp = (self.tracer.span("serve.handoff", req.trace_id,
                               req.span_request)
              if self.tracer.enabled else None)
        payload = None
        try:
            if self._chaos is not None:
                # "server.handoff" injection point (export side): the
                # decode leg sees no payload and re-runs prefill
                for f in self._chaos.fire("server.handoff"):
                    if f.kind == "handoff_fail":
                        from deepspeed_tpu.resilience.chaos import ChaosError
                        raise ChaosError("injected handoff_fail (export)")
            payload = self.engine.export_kv_chain(req.uid)
        except Exception as e:
            log_dist(f"serving: handoff export for request {req.uid} "
                     f"failed: {e!r}", level="warning")
        if payload is not None:
            self.metrics.record_handoff_out(time.monotonic() - t0)
        req.stream.handoff_payload = payload
        if sp is not None:
            sp.end(uid=req.uid, exported=payload is not None,
                   bytes=(payload or {}).get("nbytes", 0))

    def _preempt_one(self) -> None:
        """Evict the lowest-priority/youngest runner and requeue it with
        prompt+generated-so-far (recompute-style degradation)."""
        victim = self.admission.choose_victim(self._active.values())
        if victim is None:
            return
        if len(self._active) <= 1 \
                or victim.preemptions >= self.cfg.admission.max_preemptions:
            # preempting the only runner (or a chronically-preempted one)
            # cannot make progress — fail it instead of livelocking
            del self._active[victim.uid]
            self._flush_seq(victim.uid)
            self._finish(victim, error=ServingError(
                f"request {victim.uid} cannot fit the KV pool "
                f"(preempted {victim.preemptions}×, "
                f"{self.engine.free_blocks} blocks free)"))
            return
        tokens = self.engine.preempt(victim.uid)
        if self._spec is not None:
            self._spec.flush(victim.uid)
        victim.tokens = tokens
        victim.preemptions += 1
        del self._active[victim.uid]
        if victim.span_request is not None:
            self.tracer.instant("serve.preempt", victim.trace_id,
                                uid=victim.uid,
                                n_generated=victim.n_generated)
            if victim.span_phase is not None:
                victim.span_phase.end(preempted=True)
            # back to waiting: the requeue wait is queue time again
            victim.span_phase = self.tracer.span(
                "serve.queue_wait", victim.trace_id, victim.span_request
            ).set(uid=victim.uid, after_preemption=True)
        self.admission.requeue_front(victim)
        self.metrics.record_preemption()
        log_dist(f"serving: preempted uid {victim.uid} "
                 f"({victim.n_generated} tokens in, requeued)",
                 level="warning")

    def _finish(self, req: GenerationRequest,
                error: Optional[ServingError] = None) -> None:
        now = time.monotonic()
        outcome = ("completed" if error is None else
                   "cancelled" if isinstance(error, RequestCancelled) else
                   "expired" if isinstance(error, DeadlineExceeded) else
                   "shed" if isinstance(error, RequestShed) else
                   "failed")
        self.metrics.record_finish(outcome, req.n_generated,
                                   getattr(req, "first_token_at", None), now)
        self._rngs.pop(req.uid, None)
        if req.span_phase is not None:
            req.span_phase.end()
            req.span_phase = None
        if req.span_request is not None:
            self.tracer.instant("serve.finish", req.trace_id, uid=req.uid,
                                outcome=outcome)
            req.span_request.end(outcome=outcome,
                                 generated=req.n_generated,
                                 preemptions=req.preemptions)
            req.span_request = None
        req.stream._finish(error)

    def _update_gauges(self) -> None:
        free = self.engine.free_blocks
        self.metrics.set_gauges(
            queue_depth=len(self.admission),
            active=len(self._active),
            kv_utilization=1.0 - free / max(1, self._total_blocks),
            prefix_cached_blocks=(self.prefix_cache.cached_blocks
                                  if self.prefix_cache is not None else 0))
