"""Request/response handles for the serving layer.

Analog of DeepSpeed-MII's request pipeline (mii/batching/data_classes.py
``Request``/``RequestBatch`` + the streaming reply path): a
``GenerationRequest`` pairs a token prompt with ``SamplingParams`` and a
``ResponseStream`` — the caller-facing handle that yields tokens as the
serve loop produces them, supports cancellation and deadlines, and
offers a blocking ``result()``.

Thread model: the serve loop is the only *producer* (``_put_token`` /
``_finish``); any number of consumer threads may iterate, poll, or block
on the stream.  All shared state sits behind one ``Condition``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, List, Optional


class ServingError(RuntimeError):
    """Base class for request-terminating serving failures."""


class RequestCancelled(ServingError):
    """The request was cancelled (by the caller or server shutdown)."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed before it finished."""


class QueueFull(ServingError):
    """Admission queue at capacity under the 'reject' policy."""


class RequestShed(ServingError):
    """The request was shed by the graceful-degradation ladder (brownout
    levels ``shed_low_priority`` / ``reject_new``) — a typed, load-caused
    terminal state distinct from a failure: the request was well-formed
    and the server healthy, but capacity was deliberately withheld."""


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (mirrors ``engine.generate()``'s
    signature, so one-shot and served generation stay comparable)."""
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    # opt this request into speculative decoding (greedy only — the
    # acceptance rule is the bit-identical-to-greedy argmax test; a
    # server without a draft model ignores the flag)
    speculative: bool = False

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


@dataclass
class GenerationRequest:
    """One in-flight generation job (serve-loop-internal bookkeeping)."""
    uid: int
    prompt: List[int]
    params: SamplingParams
    stream: "ResponseStream"
    priority: int = 0
    deadline: Optional[float] = None      # absolute time.monotonic()
    submitted_at: float = field(default_factory=time.monotonic)
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    preemptions: int = 0
    # prompt + generated-so-far; rebuilt as the re-prefill prompt after a
    # preemption (recompute-style: KV is rebuilt, not migrated)
    tokens: List[int] = field(default_factory=list)
    # tokens prefilled by the CURRENT admission, pending prefix-cache
    # insertion at the first sampled token (0 = nothing pending; only
    # set when the server runs a prefix cache)
    pending_insert: int = 0
    # -- disaggregated serving (serving/disagg.py) --
    # handoff=True: at completion, export this sequence's full KV blocks
    # onto the stream (prefill-tier leg of a prefill→decode handoff)
    handoff: bool = False
    # a handoff payload from a prefill replica: admission imports these
    # pages instead of re-prefilling the covered prefix
    kv_payload: Any = None
    # distributed-tracing identity: every span this request emits shares
    # this id ("" = tracing disabled; see telemetry/tracing.py).  The
    # span handles are serve-loop-internal (only it starts/ends them).
    trace_id: str = ""
    span_request: Any = None       # root span: enqueue -> terminal
    span_phase: Any = None         # current phase: queue_wait|prefill|decode

    def __post_init__(self):
        self.prompt = [int(t) for t in self.prompt]
        if not self.tokens:
            self.tokens = list(self.prompt)

    @property
    def n_generated(self) -> int:
        return len(self.tokens) - len(self.prompt)

    @property
    def remaining(self) -> int:
        return self.params.max_new_tokens - self.n_generated

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)


class ResponseStream:
    """Caller-facing handle: iterate for tokens as they are produced, or
    block on ``result()`` for the full output.

    Terminal states are exclusive: exactly one of *completed* (all tokens
    delivered), *failed* (``error`` holds a ``ServingError`` — cancelled /
    deadline / rejected / engine failure).  Tokens delivered before a
    failure remain readable via ``tokens``.
    """

    def __init__(self, uid: int):
        self.uid = uid
        # set by the server at submit when tracing is enabled, so callers
        # can cross-link their stream to the exported Perfetto trace
        self.trace_id = ""
        # prefill-tier handoff: the exported KV payload, set by the serve
        # loop BEFORE _finish so a consumer observing the terminal state
        # always sees it (None = no handoff was requested/possible)
        self.handoff_payload = None
        self._cond = threading.Condition()
        self._tokens: List[int] = []
        self._done = False
        self._error: Optional[ServingError] = None
        self._cancel_requested = False

    # -- producer side (serve loop only) --------------------------------
    def _put_token(self, token: int) -> None:
        with self._cond:
            self._tokens.append(int(token))
            self._cond.notify_all()

    def _finish(self, error: Optional[ServingError] = None) -> None:
        with self._cond:
            if self._done:
                return
            self._done = True
            self._error = error
            self._cond.notify_all()

    # -- consumer side ---------------------------------------------------
    def cancel(self) -> None:
        """Request cancellation.  Asynchronous: the serve loop observes the
        flag at its next iteration and fails the stream with
        ``RequestCancelled``; already-produced tokens stay readable."""
        with self._cond:
            self._cancel_requested = True
            self._cond.notify_all()

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_requested

    @property
    def done(self) -> bool:
        with self._cond:
            return self._done

    @property
    def error(self) -> Optional[ServingError]:
        with self._cond:
            return self._error

    @property
    def tokens(self) -> List[int]:
        """Snapshot of tokens produced so far (safe from any thread)."""
        with self._cond:
            return list(self._tokens)

    def __iter__(self) -> Iterator[int]:
        """Yield tokens as they arrive; raises the terminal error (if any)
        after the last delivered token."""
        i = 0
        while True:
            with self._cond:
                while i >= len(self._tokens) and not self._done:
                    self._cond.wait()
                if i < len(self._tokens):
                    tok = self._tokens[i]
                else:  # done, no more tokens
                    if self._error is not None:
                        raise self._error
                    return
            i += 1
            yield tok

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; returns the full generated
        token list or raises the terminal ``ServingError``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._done:
                rem = None if deadline is None else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise TimeoutError(
                        f"request {self.uid} unfinished after {timeout}s")
                self._cond.wait(rem)
            if self._error is not None:
                raise self._error
            return list(self._tokens)
