"""FleetSupervisor: liveness probe → quarantine → respawn → brownout.

The serving twin of :class:`~deepspeed_tpu.resilience.supervisor.
RecoverySupervisor`.  Training recovery restarts a whole worker group
from the last checkpoint; a serving fleet instead heals IN PLACE — one
replica at a time, behind a router that keeps streaming — and when
healing lags demand it degrades SERVICE (the brownout ladder) rather
than correctness.  Three loops, one cadence thread:

* **Health state machine** (frozen vocabulary :data:`HEALTH_STATES`,
  linted like the recovery states)::

      healthy ──(probe miss)──▶ suspect ──(N ticks)──▶ dead ─┐
         │                         │ (probe ok)               │
         │◀────────────────────────┘                          ▼
         │   stuck      (beat stale + work queued) ────▶ quarantined
         │   straggler  (step EMA ≫ peer median)   ────▶    │ mask+kill+bundle
         │◀──(next tick)── respawned ◀──(ReplicaSet.respawn)─┤
         └──────────────────────────── retired ◀──(respawn failed)

  Every transition emits a ``fleet.heal`` trace instant; quarantine
  dumps a flight bundle (reason ``"fleet"``) carrying the sampler's
  recent tier history, and ``max_heals`` exhaustion fails loudly
  through :meth:`check` — exactly the RecoverySupervisor budget
  contract.

* **Tier collapse/restore** (disagg fleets): when a whole tier's
  dispatchable pool empties, the supervisor folds the router into
  degraded homogeneous routing (``DisaggRouter.collapse_tiers``) so
  requests keep completing on the survivors, and restores the tiers
  the moment both pools are live again.

* **Brownout ladder**: fleet pressure — max of queue fraction, KV
  occupancy, and SLO error-budget burn (PR 18 ledger) — feeds a
  :class:`~.admission.BrownoutController`; level changes fan out to
  every replica server and emit a ``fleet.brownout`` instant.  The
  ladder is monotone with hysteresis (enter high, exit low, dwell
  between moves), so the fleet never flaps between levels.

The supervisor only ACTUATES through public surfaces — ``Router.mask/
unmask``, ``ReplicaSet.respawn``, ``InferenceServer.set_brownout``,
``DisaggRouter.collapse_tiers/restore_tiers`` — so every move it makes
is one a human operator could.  Like the rest of ``serving/``, this
module imports no jax.
"""

from __future__ import annotations

import os
import statistics
import threading
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.serving.admission import (BrownoutConfig,
                                             BrownoutController)
from deepspeed_tpu.telemetry.flight import dump_bundle
from deepspeed_tpu.telemetry.tracing import NULL_TRACER
from deepspeed_tpu.utils.logging import log_dist

#: frozen replica health-state machine (docs/SERVING.md table; linted by
#: tools/telemetry_check.py like the recovery states)
HEALTH_STATES = ("healthy", "suspect", "stuck", "straggler", "dead",
                 "quarantined", "respawned", "retired")


class FleetHealFailed(RuntimeError):
    """The supervisor ran out of healing budget (``max_heals``) — the
    fleet is losing replicas faster than it can respawn them, which is
    an incident, not a steady state."""


class FleetSupervisorConfig:
    def __init__(self, d: Optional[dict] = None, **kw):
        d = {**(d or {}), **kw}
        self.cadence_s = float(d.get("cadence_s", 0.25))
        if self.cadence_s <= 0:
            raise ValueError(f"supervisor cadence_s={self.cadence_s}: "
                             "must be > 0")
        # probe misses (consecutive ticks not alive) before suspect
        # hardens into dead — one missed tick is a race, two is a corpse
        self.suspect_ticks = int(d.get("suspect_ticks", 2))
        # serve-loop beat staleness (with work queued) that means stuck:
        # generous against GC pauses, tiny against a real hang
        self.stuck_after_s = float(d.get("stuck_after_s", 5.0))
        # a replica whose steady-state step EMA exceeds factor × the
        # peer median for this many consecutive ticks is a straggler
        # (needs >= 2 peers with an EMA — no median, no verdict)
        self.straggler_factor = float(d.get("straggler_factor", 4.0))
        self.straggler_ticks = int(d.get("straggler_ticks", 4))
        # quarantine→respawned wall-clock target; exceeding it is the
        # heal_latency anomaly the run ledger scans for
        self.heal_deadline_s = float(d.get("heal_deadline_s", 30.0))
        # healing budget: the (max_heals+1)-th quarantine fails loudly
        self.max_heals = int(d.get("max_heals", 8))
        # actuation switches (observe-only supervisors set both False)
        self.respawn = bool(d.get("respawn", True))
        self.manage_brownout = bool(d.get("manage_brownout", True))
        self.brownout = BrownoutConfig(d.get("brownout", {}))


class FleetSupervisor:
    """Cadence thread healing a :class:`~.replica.ReplicaSet`.

    ``router`` enables dispatch masking and (for a ``DisaggRouter``)
    tier collapse; ``sampler`` supplies the SLO burn signal and the
    tier history attached to flight bundles; both are optional — a bare
    supervisor still probes, quarantines and respawns.  ``tick()`` is
    the whole control loop and is callable directly (tests, bench rows)
    without ``start()``.
    """

    def __init__(self, replicas: Any, router: Any = None,
                 sampler: Any = None, config: Optional[dict] = None,
                 telemetry: Any = None, flight_dir: str = ""):
        self.replicas = replicas
        self.router = router
        self.sampler = sampler
        self.cfg = (config if isinstance(config, FleetSupervisorConfig)
                    else FleetSupervisorConfig(config))
        self.telemetry = telemetry
        self.flight_dir = str(flight_dir)
        self.tracer = (telemetry.tracer if telemetry is not None
                       else NULL_TRACER)
        self._ring = (telemetry.flight_ring if telemetry is not None
                      else None)
        self._trace_id = (self.tracer.new_trace_id()
                          if self.tracer.enabled else "")
        self.brownout = BrownoutController(self.cfg.brownout)
        self.heals = 0
        self.collapses = 0
        self.restores = 0
        self.events: List[Dict[str, Any]] = []
        # replica index -> mutable probe record; replicas enter lazily
        # so grow()/respawn() need no registration call
        self._track: Dict[int, Dict[str, Any]] = {}
        self._collapsed = False
        self._error: Optional[FleetHealFailed] = None
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        if self._thread is not None:
            raise RuntimeError("fleet supervisor already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="ds-fleet-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(5.0, 8 * self.cfg.cadence_s))
            self._thread = None

    def __enter__(self) -> "FleetSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def check(self) -> None:
        """Re-raise a heal-budget failure caught on the cadence thread —
        the caller-side half of failing loudly (benches and tests call
        this after the run; a silent supervisor death would otherwise
        read as a healthy fleet)."""
        if self._error is not None:
            raise self._error

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.cadence_s):
            try:
                self.tick()
            except FleetHealFailed:
                break            # stored by tick(); check() re-raises
            except Exception as e:   # probing must never kill serving
                log_dist(f"fleet supervisor: tick failed: {e!r}",
                         level="warning")

    # -- bookkeeping -----------------------------------------------------
    def _rec(self, rep: Any) -> Dict[str, Any]:
        return self._track.setdefault(rep.index, {
            "state": "healthy", "miss": 0, "slow": 0,
            "since": time.monotonic(), "quarantined_at": 0.0})

    def _transition(self, rep: Any, rec: Dict[str, Any], state: str,
                    **detail) -> None:
        assert state in HEALTH_STATES, state
        rec["state"] = state
        rec["since"] = time.monotonic()
        ev = {"replica": rep.name, "state": state, "t": time.time(),
              **detail}
        with self._lock:
            self.events.append(ev)
        log_dist(f"fleet supervisor: {rep.name} -> {state} {detail}",
                 level="warning" if state not in ("healthy", "respawned")
                 else "info")
        if self.tracer.enabled:
            self.tracer.instant("fleet.heal", self._trace_id,
                                replica=rep.name, state=state, **detail)

    def _dump(self, **extra) -> str:
        if not self.flight_dir:
            return ""
        history = (self.sampler.history()[-64:]
                   if self.sampler is not None else [])
        return dump_bundle(self.flight_dir, "fleet", ring=self._ring,
                           telemetry=self.telemetry,
                           extra={**extra, "heals": self.heals,
                                  "fleet_history": history})

    # -- one control-loop tick ------------------------------------------
    def tick(self, now: Optional[float] = None) -> Dict[str, str]:
        """Probe → classify → quarantine → respawn → tiers → brownout.
        Returns the post-tick ``{replica_name: state}`` map."""
        self.check()
        now = time.monotonic() if now is None else now
        reps = list(self.replicas)
        for rep in reps:
            rec = self._rec(rep)
            if rec["state"] in ("quarantined", "retired"):
                continue
            if rec["state"] == "respawned":
                # one full tick of health after the respawn closes the
                # heal; the instant-worthy transition already fired
                rec["state"] = "healthy"
                rec["miss"] = rec["slow"] = 0
            if not rep.alive:
                rec["miss"] += 1
                if rec["miss"] >= max(1, self.cfg.suspect_ticks):
                    self._transition(rep, rec, "dead", misses=rec["miss"])
                    self._quarantine(rep, rec, "dead")
                elif rec["state"] != "suspect":
                    self._transition(rep, rec, "suspect")
                continue
            rec["miss"] = 0
            if rec["state"] == "suspect":
                self._transition(rep, rec, "healthy")
            if self._probe_stuck(rep, now):
                self._transition(rep, rec, "stuck",
                                 beat_age_s=round(
                                     now - rep.server.loop_beat_t, 3))
                self._quarantine(rep, rec, "stuck")
                continue
            if self._probe_straggler(rep, rec, reps):
                self._transition(rep, rec, "straggler",
                                 step_ema_s=round(rep.server.step_ema_s, 4))
                self._quarantine(rep, rec, "straggler")
                continue
        # tiers BEFORE healing: the tick that quarantines a tier's last
        # replica must observe (and actuate) the collapse before the
        # respawn in the same tick refills the pool — otherwise a fast
        # heal hides the degraded window from routing entirely
        self._manage_tiers()
        self._heal_quarantined()
        self._manage_tiers()
        if self.cfg.manage_brownout:
            self._manage_brownout()
        return {r.name: self._rec(r)["state"] for r in self.replicas}

    # -- probes ----------------------------------------------------------
    def _probe_stuck(self, rep: Any, now: float) -> bool:
        """Alive thread, queued work, stale serve-loop beat = hung (the
        thread exists but its loop stopped turning).  An IDLE replica is
        never stuck — its loop may legitimately block waiting for
        work."""
        beat = rep.server.loop_beat_t
        return (beat is not None and rep.queue_load > 0
                and now - beat > self.cfg.stuck_after_s)

    def _probe_straggler(self, rep: Any, rec: Dict[str, Any],
                         reps: List[Any]) -> bool:
        """Steady-state step EMA ≫ peer median, sustained.  Needs two
        peers with a warm EMA — no distribution, no verdict (a fleet of
        two can't tell slow from different)."""
        mine = rep.server.step_ema_s
        peers = [r.server.step_ema_s for r in reps
                 if r.index != rep.index and r.alive
                 and r.server.step_ema_s > 0]
        if mine <= 0 or len(peers) < 2:
            rec["slow"] = 0
            return False
        if mine > self.cfg.straggler_factor * statistics.median(peers):
            rec["slow"] += 1
        else:
            rec["slow"] = 0
        return rec["slow"] >= max(1, self.cfg.straggler_ticks)

    # -- actuation -------------------------------------------------------
    def _quarantine(self, rep: Any, rec: Dict[str, Any],
                    why: str) -> None:
        """Mask, kill, bundle — and charge the healing budget."""
        self.heals += 1
        if self.heals > self.cfg.max_heals:
            self._dump(replica=rep.name, health_state=why,
                       budget_exhausted=True)
            self._error = FleetHealFailed(
                f"healing budget exhausted ({self.cfg.max_heals}); "
                f"last casualty {rep.name} ({why})")
            self._transition(rep, rec, "retired", why=why,
                             budget_exhausted=True)
            raise self._error
        if self.router is not None:
            self.router.mask(rep.index)     # indefinite: no new legs
        if rep.alive:
            rep.kill()   # stuck/straggler: in-flight legs fail over
        bundle = self._dump(replica=rep.name, health_state=why)
        rec["quarantined_at"] = time.monotonic()
        self._transition(rep, rec, "quarantined", why=why,
                         bundle=os.path.basename(bundle) if bundle else "")

    def _heal_quarantined(self) -> None:
        if not self.cfg.respawn:
            return
        for rep in list(self.replicas):
            rec = self._track.get(rep.index)
            if rec is None or rec["state"] != "quarantined":
                continue
            try:
                fresh = self.replicas.respawn(rep.index)
            except Exception as e:
                self._transition(rep, rec, "retired", error=repr(e))
                continue
            heal_s = time.monotonic() - rec["quarantined_at"]
            if self.router is not None:
                self.router.unmask(rep.index)
                # the fresh server starts at brownout "normal"; keep the
                # fleet's ladder uniform
                fresh.server.set_brownout(self.brownout.level)
            # the tracked record carries over to the fresh replica (same
            # index); heal_s vs deadline_s is the run ledger's
            # heal_latency anomaly signal
            self._transition(fresh, rec, "respawned",
                             heal_s=round(heal_s, 3),
                             deadline_s=self.cfg.heal_deadline_s)
            if heal_s > self.cfg.heal_deadline_s:
                log_dist(f"fleet supervisor: {fresh.name} healed in "
                         f"{heal_s:.1f}s (deadline "
                         f"{self.cfg.heal_deadline_s:.1f}s)",
                         level="warning")

    def _manage_tiers(self) -> None:
        """Collapse disagg routing while a tier's dispatchable pool is
        empty; restore once both pools live again."""
        router = self.router
        if router is None or not hasattr(router, "collapse_tiers"):
            return
        masked = router.masked_indices()
        pools = {"prefill": 0, "decode": 0}
        for rep in self.replicas:
            if rep.tier in pools and rep.alive and rep.index not in masked:
                pools[rep.tier] += 1
        empty = [t for t, n in pools.items() if n == 0]
        if empty and not self._collapsed:
            self._collapsed = True
            self.collapses += 1
            router.collapse_tiers()
            self._dump(tier_collapse=empty)
            with self._lock:
                self.events.append({"state": "collapsed", "tiers": empty,
                                    "t": time.time()})
            if self.tracer.enabled:
                self.tracer.instant("fleet.heal", self._trace_id,
                                    action="tier_collapse",
                                    tiers=",".join(empty))
        elif not empty and self._collapsed:
            self._collapsed = False
            self.restores += 1
            router.restore_tiers()
            with self._lock:
                self.events.append({"state": "restored", "t": time.time()})
            if self.tracer.enabled:
                self.tracer.instant("fleet.heal", self._trace_id,
                                    action="tier_restore")

    # -- brownout --------------------------------------------------------
    def fleet_pressure(self) -> float:
        """Max of the three load signals, each normalised to ~[0, 1]:
        queue fraction (worst replica), KV occupancy (worst replica),
        and SLO error-budget burn over ``brownout.burn_limit`` (worst
        tier, PR 18 ledger)."""
        q = kv = 0.0
        for rep in self.replicas:
            if not rep.alive:
                continue
            cap = max(1, rep.server.admission.cfg.max_queue_size)
            q = max(q, len(rep.server.admission) / cap)
            kv = max(kv, 1.0 - rep.kv_headroom)
        burn = 0.0
        if self.sampler is not None:
            for row in self.sampler.slo_snapshot().values():
                burn = max(burn, float(row.get("error_budget_burn", 0.0)))
        burn = min(1.0, burn / max(1e-9, self.cfg.brownout.burn_limit))
        return max(q, kv, burn)

    def _manage_brownout(self) -> None:
        pressure = self.fleet_pressure()
        level = self.brownout.observe(pressure)
        if level is None:
            return
        if self.router is not None:
            self.router.set_brownout(level)
        else:
            for rep in self.replicas:
                rep.server.set_brownout(level)
        with self._lock:
            self.events.append({"state": "brownout", "level": level,
                                "pressure": round(pressure, 3),
                                "t": time.time()})
        log_dist(f"fleet supervisor: brownout -> {level} "
                 f"(pressure {pressure:.2f})", level="warning")
        if self.tracer.enabled:
            self.tracer.instant("fleet.brownout", self._trace_id,
                                level=level, pressure=round(pressure, 3))

    # -- reading ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            n_events = len(self.events)
        return {
            "states": {r.name: self._rec(r)["state"]
                       for r in self.replicas},
            "heals": self.heals,
            "collapses": self.collapses,
            "restores": self.restores,
            "brownout_level": self.brownout.level,
            "events": n_events,
            "failed": self._error is not None,
        }
