"""Fleet observability plane: per-tier snapshots on a cadence thread.

ROADMAP item 4's autoscaler "watches the router's per-tier telemetry
(TTFT/TPOT percentiles, queue depth, evictable headroom, handoff
volume, spec accept rate)" — but those signals natively live in N
per-replica ``MetricsRegistry`` instances plus router counters nobody
rolls up by tier.  The :class:`FleetSampler` is that sensor layer: a
cadence thread that polls every LIVE replica and folds the fleet into
one frozen-schema :class:`TierSnapshot` row per tier per tick
(:data:`TIER_SNAPSHOT_KEYS`, schema :data:`TIER_SNAPSHOT_SCHEMA` —
linted by ``tools/telemetry_check.py`` like the StepRecord key set),
appended to a bounded in-memory ring, an optional JSONL file, and
Prometheus gauges / MonitorMaster tags.  ``latest()`` is the
autoscaler's live query surface.

Aggregation rules worth stating once:

* **Percentiles pool samples.**  A tier p95 is a percentile of the
  POOLED per-replica latency samples (``ServingMetrics.latency_values``)
  — never an average of per-replica p95s, which has no distributional
  meaning.  Build replicas with ``metrics_window_s`` set so the pooled
  windows are TIME-bounded and an idle tier's percentiles decay.
* **Rates are tick deltas keyed by tier NAME.**  Counter deltas divide
  by the tick's elapsed time; keying by tier (not replica index) is
  what makes live ``grow()/shrink()/respawn()`` safe — a dead replica
  simply stops contributing at the next tick, a respawned one re-enters,
  and no dynamic index can KeyError.
* **Dead replicas drop within one tick.**  Only ``replica.alive``
  members contribute; the snapshot's ``replicas_alive`` is the
  autoscaler's capacity denominator.

With an :class:`~deepspeed_tpu.telemetry.slo.SLOSpec`, every tick also
feeds the per-tier :class:`~deepspeed_tpu.telemetry.slo.SLOLedger`
(attainment / violations / error-budget burn) and marks the snapshot's
``slo_violation`` flag, emitting an ``slo.violation`` trace instant.

Like the rest of ``serving/``, this module imports no jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deepspeed_tpu.serving.admission import AdmissionController
from deepspeed_tpu.serving.metrics import spec_accept_rate
from deepspeed_tpu.telemetry.registry import MetricsRegistry, _percentile
from deepspeed_tpu.telemetry.slo import SLOLedger, SLOSpec
from deepspeed_tpu.telemetry.tracing import NULL_TRACER
from deepspeed_tpu.utils.logging import log_dist

#: TierSnapshot schema version (bump on any key change)
TIER_SNAPSHOT_SCHEMA = 2

#: frozen key set of one TierSnapshot row — every signal ROADMAP item 4
#: names, flat and sorted; linted against docs/OBSERVABILITY.md by
#: tools/telemetry_check.py (check_fleet)
TIER_SNAPSHOT_KEYS = (
    "evictable_headroom_blocks",   # pool-wide evictable pages (sum)
    "handoff_bytes_per_sec",       # KV handoff volume, this tick
    "handoffs_per_sec",            # KV handoffs (in+out), this tick
    "kv_utilization",              # mean fraction of KV pool in use
    "prefix_hit_rate",             # lifetime hits/(hits+misses)
    "queue_depth",                 # queued requests (sum)
    "queue_wait_p50_ms",
    "queue_wait_p95_ms",
    "queue_wait_p99_ms",
    "replicas_alive",
    "run_id",                      # owning run (schema 2; "" = unstitched)
    "running",                     # admitted + decoding requests (sum)
    "schema",                      # TIER_SNAPSHOT_SCHEMA
    "slo_violation",               # 1 = this tick breached a target
    "spec_accept_rate",            # lifetime accepted/proposed
    "tick",                        # sampler tick counter
    "tier",                        # prefill | decode | unified
    "tokens_per_sec",              # decoded tokens, this tick
    "tpot_p50_ms",
    "tpot_p95_ms",
    "tpot_p99_ms",
    "ts",                          # wall-clock unix seconds
    "ttft_p50_ms",
    "ttft_p95_ms",
    "ttft_p99_ms",
)

# counters whose tick-over-tick deltas become the snapshot's rates
_RATE_COUNTERS = ("tokens_out", "handoffs", "handoff_bytes")


def _pool_pct(samples: List[float], q: float) -> float:
    """Percentile (ms) of pooled second-valued latency samples."""
    return round(_percentile(sorted(samples), q) * 1e3, 3)


class FleetSampler:
    """Cadence thread folding a ReplicaSet into per-tier snapshots.

    ``router`` is optional (its RouterMetrics are exported alongside);
    ``telemetry`` is a ``telemetry.Telemetry`` hub — its registry hosts
    the ``fleet_<tier>_<key>`` gauges and its tracer records the
    ``fleet.sample`` span per tick (standalone samplers keep their own
    registry and stay untraced).  ``jsonl_path`` appends one JSON line
    per tier per tick.  Use as a context manager or ``start()/stop()``;
    ``sample_once()`` works without the thread (tests, bench rows).
    """

    def __init__(self, replicas: Any, router: Any = None,
                 slo: Optional[SLOSpec] = None, cadence_s: float = 1.0,
                 ring: int = 512, jsonl_path: str = "",
                 telemetry: Any = None, monitor: Any = None,
                 run_id: str = ""):
        if cadence_s <= 0:
            raise ValueError(f"fleet cadence_s={cadence_s}: must be > 0")
        self.replicas = replicas
        self.router = router
        # the stitching key every snapshot row carries (schema 2):
        # explicit arg wins, else inherited from the telemetry hub
        self.run_id = str(run_id
                          or getattr(telemetry, "run_id", "") or "")
        self.cadence_s = float(cadence_s)
        self.jsonl_path = str(jsonl_path)
        self.telemetry = telemetry
        self.monitor = monitor
        self.tracer = (telemetry.tracer if telemetry is not None
                       else NULL_TRACER)
        self.registry = (telemetry.registry if telemetry is not None
                         else MetricsRegistry())
        self.slo = slo if (slo is not None and slo.enabled) else None
        self.ledger = SLOLedger(self.slo) if self.slo is not None else None
        self._ring: deque = deque(maxlen=max(1, int(ring)))
        self._latest: Dict[str, Dict[str, Any]] = {}
        self._prev: Dict[str, Any] = {}   # tier -> (t, {counter: value})
        self._tick = 0
        self._export_tiers: set = set()   # tiers with live gauges
        self._lock = threading.Lock()
        # serialises whole ticks: a manual sample_once() may overlap the
        # cadence thread, and _prev pairing + ring/JSONL ordering assume
        # one tick at a time (self._lock alone only guards the fields)
        self._tick_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "FleetSampler":
        if self._thread is not None:
            raise RuntimeError("fleet sampler already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="ds-fleet-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(5.0, 4 * self.cadence_s))
            self._thread = None

    def __enter__(self) -> "FleetSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.cadence_s):
            try:
                self.sample_once()
            except Exception as e:   # sampling must never kill serving
                log_dist(f"fleet sampler: tick failed: {e!r}",
                         level="warning")

    # -- one cadence tick ------------------------------------------------
    def sample_once(self) -> Dict[str, Dict[str, Any]]:
        """Poll the fleet; returns ``{tier: TierSnapshot}`` (also the
        value ``latest()`` serves until the next tick).  Safe to call
        concurrently with the cadence thread: whole ticks are serialised
        so two ticks can never pair one tick's clock with the other's
        counters or interleave their ring/JSONL rows."""
        with self._tick_lock:
            return self._sample_once_locked()

    def _sample_once_locked(self) -> Dict[str, Dict[str, Any]]:
        span = self.tracer.span("fleet.sample") if self.tracer.enabled \
            else None
        now = time.monotonic()
        with self._lock:
            self._tick += 1
            tick = self._tick
        by_tier: Dict[str, List[Any]] = {}
        for rep in list(self.replicas):
            if rep.alive:
                by_tier.setdefault(rep.tier, []).append(rep)
        out: Dict[str, Dict[str, Any]] = {}
        for tier in sorted(by_tier):
            out[tier] = self._tier_snapshot(tier, by_tier[tier], now, tick)
        with self._lock:
            self._latest = out
            for snap in out.values():
                self._ring.append(snap)
            # a tier with no live replicas stops advancing _prev: when
            # it comes back its first rates restart from the new counts
            self._prev = {t: self._prev.get(t) for t in out
                          if self._prev.get(t) is not None}
            for tier, snap in out.items():
                self._prev[tier] = (now, snap.pop("_counters"))
        self._export(out, tick)
        if span is not None:
            span.end(tick=tick, tiers=len(out))
        return out

    def _tier_snapshot(self, tier: str, reps: List[Any], now: float,
                       tick: int) -> Dict[str, Any]:
        pooled: Dict[str, List[float]] = {"ttft": [], "tpot": [],
                                          "queue_wait": []}
        counters = {k: 0 for k in _RATE_COUNTERS}
        queue_depth = running = 0
        headroom = 0
        kv_util = 0.0
        hits = misses = proposed = accepted = 0
        for rep in reps:
            m = rep.server.metrics
            for k, vals in m.latency_values().items():
                pooled[k].extend(vals)
            counters["tokens_out"] += m.tokens_out
            counters["handoffs"] += m.handoffs_in + m.handoffs_out
            counters["handoff_bytes"] += m.handoff_bytes
            queue_depth += len(rep.server.admission)
            running += len(rep.server._active)
            headroom += AdmissionController.evictable_headroom(
                rep.engine, rep.server.prefix_cache)
            kv_util += 1.0 - rep.kv_headroom
            hits += m.prefix_hits
            misses += m.prefix_misses
            proposed += m.spec_proposed
            accepted += m.spec_accepted
        n = len(reps)
        prev = self._prev.get(tier)
        rates = {k: 0.0 for k in _RATE_COUNTERS}
        if prev is not None:
            t_prev, c_prev = prev
            dt = max(now - t_prev, 1e-9)
            for k in _RATE_COUNTERS:
                # max(0, ·): a replica death/respawn can step a pooled
                # lifetime counter backwards; a negative rate is noise
                rates[k] = max(0, counters[k] - c_prev.get(k, 0)) / dt
        snap: Dict[str, Any] = {
            "schema": TIER_SNAPSHOT_SCHEMA,
            "run_id": self.run_id,
            "tick": tick,
            "ts": round(time.time(), 3),
            "tier": tier,
            "replicas_alive": n,
            "queue_depth": queue_depth,
            "running": running,
            "evictable_headroom_blocks": headroom,
            "kv_utilization": round(kv_util / max(1, n), 4),
            "ttft_p50_ms": _pool_pct(pooled["ttft"], 50.0),
            "ttft_p95_ms": _pool_pct(pooled["ttft"], 95.0),
            "ttft_p99_ms": _pool_pct(pooled["ttft"], 99.0),
            "tpot_p50_ms": _pool_pct(pooled["tpot"], 50.0),
            "tpot_p95_ms": _pool_pct(pooled["tpot"], 95.0),
            "tpot_p99_ms": _pool_pct(pooled["tpot"], 99.0),
            "queue_wait_p50_ms": _pool_pct(pooled["queue_wait"], 50.0),
            "queue_wait_p95_ms": _pool_pct(pooled["queue_wait"], 95.0),
            "queue_wait_p99_ms": _pool_pct(pooled["queue_wait"], 99.0),
            "tokens_per_sec": round(rates["tokens_out"], 3),
            "handoffs_per_sec": round(rates["handoffs"], 3),
            "handoff_bytes_per_sec": round(rates["handoff_bytes"], 3),
            "prefix_hit_rate": round(hits / max(1, hits + misses), 4),
            "spec_accept_rate": round(spec_accept_rate(proposed,
                                                       accepted), 4),
            "slo_violation": 0,
        }
        if self.ledger is not None:
            bad = self.ledger.observe(tier, snap["ttft_p95_ms"],
                                      snap["tpot_p95_ms"],
                                      snap["queue_wait_p95_ms"])
            snap["slo_violation"] = int(bad)
            if bad and self.tracer.enabled:
                self.tracer.instant("slo.violation", "", tier=tier,
                                    ttft_p95_ms=snap["ttft_p95_ms"],
                                    tpot_p95_ms=snap["tpot_p95_ms"])
        if tuple(sorted(snap)) != TIER_SNAPSHOT_KEYS:
            raise RuntimeError(       # schema tripwire (StepRecord rule)
                "TierSnapshot drifted from TIER_SNAPSHOT_KEYS: "
                f"{sorted(set(snap) ^ set(TIER_SNAPSHOT_KEYS))}")
        snap["_counters"] = counters   # stripped before export
        return snap

    # -- export ----------------------------------------------------------
    def _export(self, out: Dict[str, Dict[str, Any]], tick: int) -> None:
        # a tier that lost its last live replica drops out of `out`, but
        # its gauges would otherwise hold the final tick's values forever
        # — a registry consumer would keep seeing a healthy-looking dead
        # tier.  Zero every gauge of a disappeared tier so monitors see
        # replicas_alive=0 instead of frozen last-known-good numbers.
        for tier in self._export_tiers - set(out):
            for k in TIER_SNAPSHOT_KEYS:
                if k in ("tier", "schema", "run_id"):
                    continue
                self.registry.gauge(f"fleet_{tier}_{k}").set(0.0)
        self._export_tiers = set(out)
        for tier, snap in out.items():
            for k, v in snap.items():
                if k in ("tier", "schema", "run_id"):
                    continue
                self.registry.gauge(f"fleet_{tier}_{k}").set(float(v))
        if self.monitor is not None:
            events = [(f"fleet/{tier}/{k}", float(v), tick)
                      for tier, snap in out.items()
                      for k, v in snap.items()
                      if k not in ("tier", "schema", "run_id")]
            self.monitor.write_events(events)
        if self.jsonl_path:
            parent = os.path.dirname(os.path.abspath(self.jsonl_path))
            os.makedirs(parent, exist_ok=True)
            with open(self.jsonl_path, "a", encoding="utf-8") as f:
                for tier in sorted(out):
                    f.write(json.dumps(out[tier], sort_keys=True) + "\n")

    # -- reading ---------------------------------------------------------
    def latest(self) -> Dict[str, Dict[str, Any]]:
        """Most recent ``{tier: TierSnapshot}`` — the autoscaler's live
        query surface (empty before the first tick)."""
        with self._lock:
            return {t: dict(s) for t, s in self._latest.items()}

    def history(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first (every tier's rows interleaved)."""
        with self._lock:
            return [dict(s) for s in self._ring]

    def slo_snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-tier SLO ledger rows (empty without an enabled SLOSpec)."""
        return self.ledger.snapshot() if self.ledger is not None else {}
