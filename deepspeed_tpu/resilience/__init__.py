"""Self-healing elastic training: sharding oracle + recovery supervisor.

Two halves (docs/ELASTICITY.md):

* :mod:`~deepspeed_tpu.resilience.oracle` — :class:`PartitionOracle`,
  the ONE name-based partition-spec source shared by engine init,
  checkpoint save/load and the serving replicas, which is what lets a
  universal checkpoint saved on one mesh land on any other
  (dp/fsdp/tp refactorizations, shrunk worlds).
* :mod:`~deepspeed_tpu.resilience.supervisor` — the watchdog → elastic
  agent → universal-resume recovery loop that turns a mid-run worker
  death or hang into a measured goodput gap instead of a dead job.

``oracle`` imports jax; ``supervisor``/``worker`` drive subprocesses and
stay importable without an accelerator stack, so the import here is
split the same way as :mod:`deepspeed_tpu.serving`.
"""

from deepspeed_tpu.resilience.oracle import (DEFAULT_RULES, PartitionOracle,
                                             path_str, plan_mesh)

__all__ = ["PartitionOracle", "DEFAULT_RULES", "path_str", "plan_mesh"]
