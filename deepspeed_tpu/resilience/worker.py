"""Resumable training worker — the process the recovery supervisor runs.

One incarnation of one rank: build an engine on the mesh the supervisor
planned (``DSTPU_MESH``), optionally resume from the latest COMMITTED
universal checkpoint, train, heartbeat every step, and persist a
crash-atomic universal checkpoint so the next incarnation — possibly on
a smaller mesh — can pick up where this one died.  Used directly by the
chaos bench row and the tier-1 chaos e2e test; any real training script
that honors the same env contract (docs/ELASTICITY.md "worker
contract") plugs into the supervisor identically.

Env contract (all optional unless marked):
    DSTPU_MESH           json mesh sizes, e.g. '{"data": 4}'  [required]
    DSTPU_CKPT_DIR       checkpoint root                       [required]
    DSTPU_PROGRESS       rank-0 heartbeat/progress JSONL path  [required]
    DSTPU_TOTAL_STEPS    train until global_steps reaches this (default 8)
    DSTPU_RESUME         "1": resume from the latest committed universal
    DSTPU_MODEL          model-zoo name (default gpt2-tiny)
    DSTPU_SEQ            sequence length (default 16)
    DSTPU_BATCH          GLOBAL batch size (default 8) — held fixed across
                         resizes so the loss curve stays comparable
    DSTPU_ZERO_STAGE     zero_optimization.stage (default 2)
    DSTPU_SAVE_EVERY     checkpoint cadence in steps (default 1)
    DSTPU_FORCE_CPU      "1": force the cpu platform with
                         product(mesh) virtual host devices (the smoke /
                         tier-1 harness; on-chip runs leave it unset)
    DSTPU_CHAOS          json fault injection, honored ONCE per ckpt dir
                         (a sentinel file arms exactly one incarnation):
                         {"die_at": N}          — exit(13) after step N,
                                                  BEFORE saving it
                         {"hang_at": N}         — stop heartbeating after
                                                  step N (simulated wedge)
                         {"ignore_term": true}  — also swallow SIGTERM, so
                                                  only SIGKILL escalation
                                                  can clear the worker
                         {"rank": r}            — which rank acts (default 0)

Per-step progress lines ``{"step", "loss", "rank", "incarnation",
"time_unix"}`` are the supervisor's heartbeat AND the loss-continuity
evidence: batches are a pure function of the step index, so a resumed
curve must land on the unkilled run's curve.
"""

from __future__ import annotations

import json
import os
import sys
import time

# env read + platform forcing BEFORE any jax device use (backends are
# lazy, so this is early enough even though the package __init__ already
# imported jax)
_MESH = {k: int(v) for k, v in
         json.loads(os.environ.get("DSTPU_MESH") or "{}").items()}
_NDEV = 1
for _v in _MESH.values():
    _NDEV *= max(1, _v)
if os.environ.get("DSTPU_FORCE_CPU", "0") == "1":
    _flag = f"--xla_force_host_platform_device_count={_NDEV}"
    if _flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " " + _flag)
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> int:
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.checkpoint.universal import (ds_to_universal,
                                                    load_universal,
                                                    resolve_universal_dir)
    from deepspeed_tpu.models import get_model_config
    # the shared chaos module (resilience/chaos.py): same DSTPU_CHAOS env
    # contract and exactly-once sentinel, one vocabulary with serving
    from deepspeed_tpu.resilience.chaos import TrainChaos

    rank = int(os.environ.get("DSTPU_PROC_ID", "0"))
    ckpt_dir = os.environ["DSTPU_CKPT_DIR"]
    progress = os.environ["DSTPU_PROGRESS"]
    if rank != 0:
        progress = f"{progress}.r{rank}"
    total_steps = int(os.environ.get("DSTPU_TOTAL_STEPS", "8"))
    seq = int(os.environ.get("DSTPU_SEQ", "16"))
    batch_size = int(os.environ.get("DSTPU_BATCH", "8"))
    save_every = int(os.environ.get("DSTPU_SAVE_EVERY", "1"))
    resume = os.environ.get("DSTPU_RESUME", "0") == "1"
    incarnation = int(os.environ.get("DSTPU_INCARNATION", "0"))

    chaos = TrainChaos.from_env(rank, ckpt_dir)
    if chaos is not None:
        chaos.install_signals()

    model = get_model_config(os.environ.get("DSTPU_MODEL", "gpt2-tiny"),
                             max_seq_len=max(seq, 16))
    dp = (_MESH.get("data", 1) * _MESH.get("subdata", 1)
          * _MESH.get("expert", 1))
    cfg = {
        "train_batch_size": batch_size,
        "train_micro_batch_size_per_gpu": max(1, batch_size // dp),
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {
            "stage": int(os.environ.get("DSTPU_ZERO_STAGE", "2"))},
        "steps_per_print": 100000,
        "mesh": _MESH,
    }
    engine, _, _, _ = ds.initialize(model=model, config=cfg, seed=7)
    if resume:
        try:
            load_universal(engine, resolve_universal_dir(ckpt_dir))
        except FileNotFoundError:
            # crashed before the FIRST committed save: nothing to resume,
            # start over — a missing checkpoint must not wedge recovery
            print("worker: no committed universal checkpoint yet; "
                  "starting from step 0", flush=True)

    def batch_for(step: int):
        # pure function of the step index: every incarnation (any mesh)
        # consumes the identical global batch, so curves are comparable
        rng = np.random.default_rng(1000 + step)
        ids = rng.integers(0, model.vocab_size, size=(batch_size, seq + 1),
                           dtype=np.int32)
        return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    while engine.global_steps < total_steps:
        step = engine.global_steps  # 0-based index of the step we run
        loss = float(np.asarray(engine.train_batch(batch_for(step))))
        with open(progress, "a") as f:
            f.write(json.dumps({"step": engine.global_steps, "loss": loss,
                                "rank": rank, "incarnation": incarnation,
                                "time_unix": time.time()}) + "\n")
            f.flush()
            os.fsync(f.fileno())

        done = engine.global_steps
        if chaos is not None:
            # BEFORE the save: a die loses the step we just ran and the
            # resumed incarnation must recompute it from the previous
            # committed checkpoint — the real mid-train crash shape
            chaos.fire(done)

        if rank == 0 and done % save_every == 0:
            tag = f"step{done}"
            engine.save_checkpoint(ckpt_dir, tag=tag)
            ds_to_universal(ckpt_dir, tag=tag)
    return 0


if __name__ == "__main__":
    sys.exit(main())
