"""Deterministic fault injection shared by training and serving.

PR 13 proved the *training* self-healing loop by hand-rolling faults in
``resilience/worker.py`` (the ``DSTPU_CHAOS`` env contract); serving
faults were hand-rolled ``replica.kill()`` calls scattered through
individual tests.  This module is the one chaos vocabulary both halves
speak: a typed, **seeded** :class:`FaultPlan` (frozen fault kinds,
:data:`FAULT_KINDS`) scheduled against **named injection points**
(:data:`INJECTION_POINTS`) that the serve loop, router, disagg handoff
path and ``InferenceEngineV2.step`` poll, plus the training worker's
die/hang/ignore-term contract re-implemented on the same kinds
(``die_at`` ≡ ``replica_crash``, ``hang_at`` ≡ ``replica_hang``).

Design constraints:

* **Deterministic.**  A plan is a sorted tuple of :class:`FaultSpec`;
  any randomness (storm victim choice, burst sizing) comes from a
  ``random.Random`` seeded by ``(plan.seed, target)`` — two runs of the
  same plan against the same fleet inject identically.
* **Free when disabled.**  Call sites hold ``self._chaos = None`` by
  default and guard with one attribute check — no plan, no work, no
  allocation (the same contract as the disabled tracer).
* **Attributable.**  Every injection emits a frozen ``chaos.inject``
  trace instant (kind / point / target), so flight bundles and the run
  ledger can pin observed damage on the fault that caused it.
* **Injection points describe *where*, specs describe *what*.**  The
  semantics of a fault (raise, sleep, cancel, flood) live at the call
  site — this module only decides *when a spec is due*.

See docs/SERVING.md "Fault injection & self-healing".
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence

# ---------------------------------------------------------------------------
# Frozen vocabularies (linted against docs/SERVING.md by telemetry_check)
# ---------------------------------------------------------------------------

#: every fault kind a plan may schedule — train and serve share this set
FAULT_KINDS = (
    "admission_storm",   # flood the admission queue with junk requests
    "cancel_storm",      # cancel a batch of in-flight streams
    "handoff_fail",      # fail a KV-chain export/import (disagg legs)
    "replica_crash",     # serve loop dies mid-step (train: exit(13))
    "replica_hang",      # serve loop wedges: alive, silent, no progress
    "slow_replica",      # injected per-step delay over a window
)

#: named places the hot loops poll for due faults
INJECTION_POINTS = (
    "engine.step",       # InferenceEngineV2.step ragged dispatch
    "router.dispatch",   # router binding a request leg to a replica
    "server.handoff",    # KV-chain export/import in the serve loop
    "server.step",       # top of one serve-loop engine step
    "train.step",        # training worker, after one train_batch
)

# default injection point per kind (a spec may pin a different one, e.g.
# slow_replica at engine.step to delay inside the engine instead of the
# serve loop)
_KIND_POINT = {
    "admission_storm": "server.step",
    "cancel_storm": "server.step",
    "handoff_fail": "server.handoff",
    "replica_crash": "server.step",
    "replica_hang": "server.step",
    "slow_replica": "server.step",
}

# kinds active over a [at, at+duration_s] window, re-returned on every
# poll while due; everything else fires exactly once per injector
_DURATIONAL = ("slow_replica",)

#: training env contract (resilience/worker.py): honored ONCE per ckpt
#: dir via the :data:`CHAOS_SENTINEL` file
TRAIN_CHAOS_ENV = "DSTPU_CHAOS"
CHAOS_SENTINEL = ".chaos_fired"


class ChaosError(RuntimeError):
    """An injected fault firing — deliberately NOT a ServingError, so it
    rides the same "unexpected engine/loop failure" paths a real crash
    takes instead of being treated as a typed request outcome."""


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault.

    ``at`` is seconds after the injector is armed; ``target`` names a
    replica (``"r0"``) or ``None`` for every component sharing the plan;
    ``params`` carries kind-specific knobs (``delay_ms`` for
    ``slow_replica``, ``burst``/``priority`` for ``admission_storm``,
    ``count`` for ``cancel_storm``)."""

    kind: str
    at: float = 0.0
    target: Optional[str] = None
    duration_s: float = 0.0
    point: Optional[str] = None
    params: Mapping[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FAULT_KINDS})")
        point = self.point or _KIND_POINT[self.kind]
        if point not in INJECTION_POINTS:
            raise ValueError(f"unknown injection point {point!r} "
                             f"(one of {INJECTION_POINTS})")
        object.__setattr__(self, "point", point)
        object.__setattr__(self, "at", float(self.at))
        object.__setattr__(self, "duration_s", float(self.duration_s))
        object.__setattr__(self, "params", dict(self.params))


class FaultPlan:
    """An ordered, validated schedule of faults plus the seed every
    injector derives its randomness from."""

    def __init__(self, faults: Sequence[Any], seed: int = 0):
        specs = [f if isinstance(f, FaultSpec) else FaultSpec(**dict(f))
                 for f in faults]
        self.faults = tuple(sorted(
            specs, key=lambda s: (s.at, s.kind, s.target or "")))
        self.seed = int(seed)

    def __len__(self) -> int:
        return len(self.faults)

    def for_target(self, target: Optional[str]) -> List[FaultSpec]:
        """Specs an injector named ``target`` must honor: its own plus
        the broadcast (``target=None``) ones."""
        return [f for f in self.faults
                if f.target is None or f.target == target]


class ChaosInjector:
    """One component's view of a plan: ``fire(point)`` returns the specs
    due *now* at that point (thread-safe; one-shot kinds are consumed
    exactly once, durational kinds re-fire while inside their window)
    and emits one ``chaos.inject`` instant per spec activation."""

    def __init__(self, plan: FaultPlan, target: Optional[str] = None,
                 tracer: Any = None, trace_id: str = "chaos"):
        self.plan = plan
        self.target = target
        self.tracer = tracer
        self.trace_id = trace_id
        self.rng = random.Random(
            (plan.seed << 16) ^ zlib.crc32((target or "*").encode()))
        self._specs = plan.for_target(target)
        self._t0: Optional[float] = None
        self._fired: set = set()       # consumed one-shot spec indices
        self._announced: set = set()   # durational specs already instant-ed
        self._lock = threading.Lock()
        self.injected = 0              # lifetime activations (bench/test)
        self.fired_kinds: set = set()  # distinct kinds activated so far

    @property
    def armed(self) -> bool:
        return self._t0 is not None

    def arm(self, now: Optional[float] = None) -> "ChaosInjector":
        """Start the plan clock (monotonic).  Pass a shared ``now`` to
        arm a whole fleet's injectors against one origin."""
        self._t0 = time.monotonic() if now is None else float(now)
        return self

    def fire(self, point: str,
             now: Optional[float] = None) -> List[FaultSpec]:
        t0 = self._t0
        if t0 is None or not self._specs:
            return []
        dt = (time.monotonic() if now is None else now) - t0
        due: List[FaultSpec] = []
        with self._lock:
            for i, f in enumerate(self._specs):
                if f.point != point or dt < f.at:
                    continue
                if f.kind in _DURATIONAL:
                    if f.duration_s > 0 and dt > f.at + f.duration_s:
                        continue
                    due.append(f)
                    if i not in self._announced:
                        self._announced.add(i)
                        self._activate(f, point)
                else:
                    if i in self._fired:
                        continue
                    self._fired.add(i)
                    due.append(f)
                    self._activate(f, point)
        return due

    def _activate(self, f: FaultSpec, point: str) -> None:
        self.injected += 1
        self.fired_kinds.add(f.kind)
        tr = self.tracer
        if tr is not None and getattr(tr, "enabled", False):
            tr.instant("chaos.inject", self.trace_id, kind=f.kind,
                       point=point, target=self.target or "*", at=f.at)

    def delay_s(self, specs: Sequence[FaultSpec]) -> float:
        """Total injected delay of the ``slow_replica`` specs in a
        ``fire()`` result (default 50 ms per spec)."""
        return sum(float(f.params.get("delay_ms", 50.0)) / 1e3
                   for f in specs if f.kind == "slow_replica")


def attach_chaos(replicas: Any, plan: FaultPlan, router: Any = None,
                 arm: bool = True) -> Dict[str, ChaosInjector]:
    """Wire one injector per replica (serve loop + engine share it) and
    optionally one for the router, all armed against one shared origin
    so ``at`` offsets line up fleet-wide.  Returns ``{target: injector}``
    (router under ``"router"``)."""
    injectors: Dict[str, ChaosInjector] = {}
    for rep in replicas:
        inj = ChaosInjector(plan, target=rep.name,
                            tracer=getattr(rep.server, "tracer", None))
        rep.server._chaos = inj
        rep.engine.chaos = inj
        injectors[rep.name] = inj
    if router is not None:
        inj = ChaosInjector(plan, target=None,
                            tracer=getattr(router, "tracer", None))
        router._chaos = inj
        injectors["router"] = inj
    if arm:
        t0 = time.monotonic()
        for inj in injectors.values():
            inj.arm(t0)
    return injectors


# ---------------------------------------------------------------------------
# Training contract (resilience/worker.py) on the shared vocabulary
# ---------------------------------------------------------------------------

def chaos_env_cfg(env: Optional[Mapping[str, str]] = None) -> dict:
    """Parse the ``DSTPU_CHAOS`` JSON env contract (empty dict = off)."""
    src = os.environ if env is None else env
    return json.loads(src.get(TRAIN_CHAOS_ENV) or "{}")


def chaos_armed(ckpt_dir: str) -> bool:
    """Fault injection fires in exactly one incarnation: the sentinel is
    written BEFORE the fatal action, so the restarted worker sees it and
    trains through."""
    return not os.path.exists(os.path.join(ckpt_dir, CHAOS_SENTINEL))


def arm_sentinel(ckpt_dir: str) -> None:
    with open(os.path.join(ckpt_dir, CHAOS_SENTINEL), "w") as f:
        f.write(str(os.getpid()))


class TrainChaos:
    """The training worker's ``DSTPU_CHAOS`` contract expressed on the
    shared kinds: ``die_at`` is a ``replica_crash`` at the ``train.step``
    point, ``hang_at`` a ``replica_hang`` (``ignore_term`` additionally
    swallows SIGTERM so only SIGKILL escalation clears the worker).
    Exactly-once semantics ride the :data:`CHAOS_SENTINEL` file."""

    def __init__(self, cfg: Mapping[str, Any], ckpt_dir: str):
        self.cfg = dict(cfg)
        self.ckpt_dir = ckpt_dir

    @classmethod
    def from_env(cls, rank: int, ckpt_dir: str,
                 env: Optional[Mapping[str, str]] = None
                 ) -> Optional["TrainChaos"]:
        """The rank's armed chaos config, or ``None`` when chaos is off,
        targets another rank, or already fired in a past incarnation."""
        cfg = chaos_env_cfg(env)
        if not cfg or int(cfg.get("rank", 0)) != rank \
                or not chaos_armed(ckpt_dir):
            return None
        return cls(cfg, ckpt_dir)

    def install_signals(self) -> None:
        if self.cfg.get("ignore_term"):
            signal.signal(signal.SIGTERM, signal.SIG_IGN)

    def fire(self, done: int) -> None:
        """The ``train.step`` injection point — call after step ``done``
        is trained but BEFORE it is saved, so a die loses the step and
        the resumed incarnation must recompute it from the previous
        committed checkpoint (the real mid-train crash shape)."""
        cfg = self.cfg
        if cfg.get("die_at") is not None and done >= int(cfg["die_at"]):
            arm_sentinel(self.ckpt_dir)
            os._exit(13)
        if cfg.get("hang_at") is not None and done >= int(cfg["hang_at"]):
            arm_sentinel(self.ckpt_dir)
            while True:  # simulated wedge: alive, silent, not progressing
                time.sleep(3600)
