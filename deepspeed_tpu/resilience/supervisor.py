"""RecoverySupervisor: watchdog → elastic agent → universal resume.

PR 4's flight recorder DETECTS (hang watchdog, crash bundles) and the
elastic agent can RESTART a process group — this module is the loop that
connects them, so a mid-run worker death or wedge ends in a converged
loss curve instead of a dead job:

    running ──(worker exit!=0 | heartbeat stall)──▶ detected
      ▲                                               │ flight bundle
      │                                               ▼ (reason "recovery")
    resumed ◀── first post-restart progress ◀── restarted ◀── replanned
      │ goodput-gap StepRecord                        ▲          │
      └── recovery.outage span ends                   └──────────┘
                                        stop_group (SIGTERM→SIGKILL) +
                                        plan_mesh over surviving hosts

Recovery is possible at all because of two invariants built elsewhere:
the universal checkpoint is CRASH-ATOMIC (``checkpoint/universal.py``
staging + completion marker — a worker killed mid-save leaves the
previous good tag resumable) and partition specs are a pure function of
name+shape+mesh (:class:`~deepspeed_tpu.resilience.oracle.
PartitionOracle`), so the restarted group can be a DIFFERENT SIZE — a
gone host just shrinks the planned mesh and the oracle reshards the
resume.

Telemetry: the whole outage is one ``recovery.outage`` span with
``recovery.detected`` / ``recovery.replan`` / ``recovery.restart`` /
``recovery.resumed`` instants, plus a ``kind="recovery"`` goodput-gap
StepRecord (``Telemetry.record_recovery``) — the outage is measurable,
not just survived.  States are a frozen vocabulary
(:data:`RECOVERY_STATES`), linted against docs/ELASTICITY.md by
``tools/telemetry_check.py``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.elasticity.elastic_agent import (WorkerSpec, start_group,
                                                    stop_group)
from deepspeed_tpu.resilience.oracle import plan_mesh
from deepspeed_tpu.telemetry.flight import Watchdog, dump_bundle
from deepspeed_tpu.utils.logging import log_dist, logger

# frozen recovery state machine (docs/ELASTICITY.md table; linted by
# tools/telemetry_check.py like span names)
RECOVERY_STATES = ("running", "detected", "dumped", "stopped", "replanned",
                   "restarted", "resumed", "failed")


class RecoveryFailed(RuntimeError):
    """The supervisor ran out of recovery budget (max_recoveries) or the
    restarted group never produced progress."""


@dataclass
class RecoveryEvent:
    state: str
    time_unix: float
    detail: Dict[str, Any] = field(default_factory=dict)


@dataclass
class SupervisorResult:
    returncode: int
    recoveries: int
    outages: List[Dict[str, Any]]
    events: List[RecoveryEvent]
    progress_path: str
    mesh: Dict[str, int]


def read_progress(path: str) -> List[Dict[str, Any]]:
    """Parse a worker progress JSONL (tolerates a torn final line — the
    worker may have died mid-write)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


def loss_curve(path: str) -> Dict[int, float]:
    """step → loss, LAST incarnation wins (a step recomputed after a
    resume overwrites the pre-crash line — both should agree with the
    unkilled curve, which is what the chaos tests assert)."""
    return {int(r["step"]): float(r["loss"]) for r in read_progress(path)
            if "step" in r and "loss" in r}


class RecoverySupervisor:
    """Supervise a training worker group with automatic recovery.

    ``hosts_fn`` is the survivors census: called at launch and again at
    every re-plan; returning fewer hosts than before is how a dead host
    manifests, and shrinks the planned mesh.  Each host contributes
    ``devices_per_host`` devices to one planned mesh shared by every
    worker (the CPU harness simulates this with forced host devices;
    a real multi-host slice passes ``force_cpu=False`` and its own
    platform env).
    """

    def __init__(self, ckpt_dir: str, *,
                 worker_cmd: Optional[Sequence[str]] = None,
                 hosts_fn: Optional[Callable[[], Sequence[str]]] = None,
                 devices_per_host: int = 1,
                 mesh_template: Optional[Dict[str, int]] = None,
                 total_steps: int = 8,
                 deadline_s: float = 60.0,
                 poll_s: float = 0.25,
                 max_recoveries: int = 3,
                 stop_timeout_s: float = 10.0,
                 resume_deadline_s: float = 300.0,
                 telemetry: Any = None,
                 flight_dir: Optional[str] = None,
                 worker_env: Optional[Dict[str, str]] = None,
                 force_cpu: bool = True):
        self.ckpt_dir = ckpt_dir
        self.worker_cmd = list(worker_cmd or (
            sys.executable, "-m", "deepspeed_tpu.resilience.worker"))
        self._hosts_fn = hosts_fn or (lambda: ["localhost"])
        self.devices_per_host = int(devices_per_host)
        self.mesh_template = dict(mesh_template or {})
        self.total_steps = int(total_steps)
        self.deadline_s = float(deadline_s)
        self.poll_s = float(poll_s)
        self.max_recoveries = int(max_recoveries)
        self.stop_timeout_s = float(stop_timeout_s)
        self.resume_deadline_s = float(resume_deadline_s)
        self.telemetry = telemetry
        self.flight_dir = flight_dir or os.path.join(ckpt_dir, "flight")
        self.worker_env = dict(worker_env or {})
        self.force_cpu = bool(force_cpu)

        self.progress_path = os.path.join(ckpt_dir, "progress.jsonl")
        self.recoveries = 0
        self.events: List[RecoveryEvent] = []
        self.outages: List[Dict[str, Any]] = []
        self.mesh: Dict[str, int] = {}
        self._incarnation = 0
        self._hang = threading.Event()
        self._progress_mark = 0
        self._bundles_at_launch: set = set()
        if telemetry is not None:
            self._tracer = telemetry.tracer
            self._ring = telemetry.flight_ring
        else:
            from deepspeed_tpu.telemetry.tracing import NULL_TRACER

            self._tracer = NULL_TRACER
            self._ring = None
        self._trace_id = (self._tracer.new_trace_id()
                          if self._tracer.enabled else "")

    # -- bookkeeping -----------------------------------------------------
    def _event(self, state: str, **detail) -> None:
        assert state in RECOVERY_STATES, state
        self.events.append(RecoveryEvent(state, time.time(), detail))
        log_dist(f"recovery supervisor: {state} {detail}", level="info")

    def _progress_size(self) -> int:
        """Byte size of the progress JSONL — the heartbeat signal.  The
        workers only ever APPEND, so growth == new progress; polling the
        size keeps the watchdog feed O(1) instead of re-reading a file
        that grows one line per step for the whole run."""
        try:
            return os.path.getsize(self.progress_path)
        except OSError:
            return 0

    def _last_step(self) -> int:
        rows = read_progress(self.progress_path)
        return max((int(r.get("step", 0)) for r in rows), default=0)

    # -- group lifecycle -------------------------------------------------
    def _plan(self) -> Dict[str, int]:
        # ONE census snapshot shared with the _launch that follows: a host
        # vanishing between plan and launch must not hand a 4-device mesh
        # to a 1-worker group (the mismatch would burn a recovery round)
        hosts = list(self._hosts_fn())
        if not hosts:
            raise RecoveryFailed("no surviving hosts to plan a mesh over")
        self._planned_hosts = hosts
        n_dev = len(hosts) * self.devices_per_host
        mesh = plan_mesh(n_dev, template=self.mesh_template or self.mesh)
        return {ax: sz for ax, sz in mesh.items() if sz > 1} or {"data": 1}

    def _launch(self, mesh: Dict[str, int], resume: bool) -> list:
        n_workers = len(self._planned_hosts)
        env = {
            **self.worker_env,
            "DSTPU_MESH": json.dumps(mesh),
            "DSTPU_CKPT_DIR": self.ckpt_dir,
            "DSTPU_PROGRESS": self.progress_path,
            "DSTPU_TOTAL_STEPS": str(self.total_steps),
            "DSTPU_RESUME": "1" if resume else "0",
            "DSTPU_INCARNATION": str(self._incarnation),
            "DSTPU_FORCE_CPU": "1" if self.force_cpu else "0",
        }
        self._incarnation += 1
        self.mesh = dict(mesh)
        self._started_at = time.monotonic()
        self._mark_at_start = self._progress_size()
        # snapshot so an outage cross-links only bundles dumped DURING
        # this incarnation, not earlier outages' recovery bundles
        self._bundles_at_launch = set(os.listdir(self.flight_dir)) \
            if os.path.isdir(self.flight_dir) else set()
        return start_group(WorkerSpec(self.worker_cmd, env=env), n_workers)

    # -- heartbeat -------------------------------------------------------
    def _feed_watchdog(self, wd: Watchdog) -> None:
        n = self._progress_size()
        if n > self._progress_mark:
            self._progress_mark = n
            wd.beat()
        elif n <= self._mark_at_start and \
                time.monotonic() - self._started_at < self.resume_deadline_s:
            # compile grace: a fresh incarnation legitimately spends its
            # first step inside XLA compile — the same first-step skip
            # the train engine's own watchdog applies.  Once the
            # incarnation's first line lands (n > mark_at_start) the
            # grace ends; the grace itself is bounded by resume_deadline.
            wd.beat()

    # -- main loop -------------------------------------------------------
    def run(self) -> SupervisorResult:
        os.makedirs(self.ckpt_dir, exist_ok=True)
        os.makedirs(self.flight_dir, exist_ok=True)
        mesh = self._plan()
        self._event("running", mesh=mesh, workers=len(self._planned_hosts))
        procs = self._launch(mesh, resume=False)
        wd = Watchdog("recovery", deadline_s=self.deadline_s,
                      output_dir=self.flight_dir, ring=self._ring,
                      telemetry=self.telemetry, tracer=self._tracer,
                      poll_s=min(1.0, self.poll_s),
                      on_fire=lambda bundle: self._hang.set())
        wd.start()
        try:
            while True:
                time.sleep(self.poll_s)
                self._feed_watchdog(wd)
                codes = [p.poll() for p in procs]
                if all(c == 0 for c in codes):
                    return SupervisorResult(
                        0, self.recoveries, self.outages, self.events,
                        self.progress_path, self.mesh)
                crashed = [c for c in codes if c not in (None, 0)]
                if crashed or self._hang.is_set():
                    wd.pause()
                    reason = "crash" if crashed else "hang"
                    self._hang.clear()
                    procs = self._recover(procs, reason, codes)
                    wd.resume()
        finally:
            wd.stop()
            stop_group(procs, stop_timeout_s=self.stop_timeout_s)

    # -- the recovery transition ----------------------------------------
    def _recover(self, procs: list, reason: str, codes: list) -> list:
        t0 = time.monotonic()
        span = (self._tracer.span("recovery.outage", self._trace_id)
                .set(reason=reason) if self._tracer.enabled else None)
        self._event("detected", reason=reason, codes=list(codes))
        if self._tracer.enabled:
            self._tracer.instant("recovery.detected", self._trace_id,
                                 reason=reason, codes=repr(codes))

        known = set(os.listdir(self.flight_dir)) \
            if os.path.isdir(self.flight_dir) else set()
        bundle = dump_bundle(
            self.flight_dir, "recovery", ring=self._ring,
            telemetry=self.telemetry,
            # NOT "reason": extra keys merge over the manifest's own, and
            # "reason" must stay the frozen bundle vocabulary's `recovery`
            extra={"detect_reason": reason, "codes": codes,
                   "recoveries": self.recoveries,
                   # bundles dumped during THIS incarnation — the dying
                   # workers' own (engine_crash / their watchdog) —
                   # cross-linked so one outage reads as one incident;
                   # the launch-time snapshot keeps earlier outages'
                   # bundles out of this incident's manifest
                   "worker_bundles": sorted(known - self._bundles_at_launch)})
        self._event("dumped", bundle=bundle)

        while True:
            stop_group(procs, stop_timeout_s=self.stop_timeout_s)
            self._event("stopped")

            mesh = self._plan()
            resized = mesh != self.mesh
            self._event("replanned", mesh=mesh, resized=resized)
            if self._tracer.enabled:
                self._tracer.instant("recovery.replan", self._trace_id,
                                     mesh=json.dumps(mesh), resized=resized)

            self.recoveries += 1
            if self.recoveries > self.max_recoveries:
                self._event("failed", recoveries=self.recoveries)
                if span is not None:
                    span.end(outcome="failed")
                raise RecoveryFailed(
                    f"recovery budget exhausted "
                    f"({self.max_recoveries}); last reason: {reason}")

            self._progress_mark = self._progress_size()
            procs = self._launch(mesh, resume=True)
            self._event("restarted", workers=len(procs), mesh=mesh)
            if self._tracer.enabled:
                self._tracer.instant("recovery.restart", self._trace_id,
                                     workers=len(procs))

            deadline = time.monotonic() + self.resume_deadline_s
            while time.monotonic() < deadline:
                time.sleep(self.poll_s)
                codes2 = [p.poll() for p in procs]
                if self._progress_size() > self._progress_mark or \
                        all(c == 0 for c in codes2):
                    # new progress — OR the whole group exited 0 without
                    # writing a line: the job was already complete at
                    # resume (killed between its final save and exit).
                    # Both end the outage; run()'s loop then returns 0.
                    outage_s = time.monotonic() - t0
                    step = self._last_step()
                    self._event("resumed", outage_s=round(outage_s, 3),
                                step=step)
                    if self._tracer.enabled:
                        self._tracer.instant("recovery.resumed",
                                             self._trace_id, step=step,
                                             outage_s=round(outage_s, 3))
                    if span is not None:
                        span.end(outcome="resumed",
                                 outage_s=round(outage_s, 3))
                    if self.telemetry is not None:
                        self.telemetry.record_recovery(step, outage_s)
                    self.outages.append({"reason": reason,
                                         "outage_s": outage_s,
                                         "mesh": dict(mesh),
                                         "resized": resized,
                                         "bundle": bundle})
                    return procs
                if any(c not in (None, 0) for c in codes2):
                    break  # restarted group died before progressing
            logger.warning("recovery supervisor: restarted group produced "
                           "no progress; recovering again")
            reason = "restart_stalled"
