"""PartitionOracle: the single name-based partition-spec source.

This is the systematic-placement half of arXiv:2601.02311 applied to
recovery: every parameter **path** maps — by regex pattern + shape
heuristics (the SNIPPETS.md [3] idiom) — to a tuple of logical dims, and
logical dims map to mesh axes for whatever topology the oracle is built
over.  Because the mapping is a pure function of ``(path, shape,
topology, config)`` and never of the array's current placement, the SAME
oracle answers three different callers identically:

* **engine init** (``runtime/engine.py``) — parameter / optimizer /
  grad-accumulator shardings for the training mesh;
* **checkpoint save/load** (``checkpoint/universal.py``) — a flat
  ``{path: array}`` checkpoint re-lands on an ARBITRARY target mesh
  (different dp/fsdp/tp factorization, shrunk world) by asking the
  target engine's oracle for each path's spec;
* **serving replicas** (``inference/v2/engine_v2.py`` via
  ``serving/replica.py``) — the same weights shard onto each replica's
  mesh slice, which is what lets a :class:`ReplicaSet` grow/shrink live.

Any per-site spec derivation is a resharding bug waiting to happen —
two derivations that drift make a checkpoint saved by one unloadable by
the other.  ``parallel/sharding.py`` re-exports this class under its
historical name ``ShardingRules`` so existing callers keep working; the
implementation lives HERE only.

The logical-dim table and ZeRO/TP/hpZ/MiCS semantics are the TPU-native
core of what the reference spreads across
``runtime/zero/partition_parameters.py`` (ZeRO-3 param partitioning),
``runtime/zero/stage_1_and_2.py`` (optimizer/grad partitioning) and
``module_inject/auto_tp.py`` (AutoTP tensor-parallel sharding).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DATA_AXIS, EXPERT_AXIS, MESH_AXES,
                                             PIPE_AXIS, SEQ_AXIS, SUBDATA_AXIS,
                                             TENSOR_AXIS, MeshTopology)
from deepspeed_tpu.utils.logging import logger

# path-pattern → logical dims, one entry per array dim.
# Logical dim vocabulary:
#   layer   — stacked-layer scan axis (never sharded)
#   expert  — stacked-expert axis → "expert" mesh axis
#   embed   — hidden/residual dim  → fsdp candidate
#   mlp     — ffn intermediate dim → "tensor" (column-parallel)
#   heads   — attention projection out dim → "tensor" (column-parallel)
#   vocab   — vocabulary dim → "tensor"
#   norm    — layernorm vector → fsdp candidate (1-D, ZeRO-3 shards these too)
#   pos     — position-embedding rows
DEFAULT_RULES: List[Tuple[str, Tuple[Optional[str], ...]]] = [
    (r"embed/tokens$", ("vocab", "embed")),
    (r"embed/positions$", ("pos", "embed")),
    (r"embed/token_types$", ("pos", "embed")),
    (r"embed/norm/(scale|bias)$", ("norm",)),
    # BERT MLM head (transform dense + LN + vocab bias)
    (r"mlm_head/w$", ("embed", None)),
    (r"mlm_head/b$", ("embed",)),
    (r"mlm_head/ln/(scale|bias)$", ("norm",)),
    (r"mlm_head/bias$", ("vocab",)),
    (r"attn/w[qkv]$", ("layer", "embed", "heads")),
    (r"attn/b[qkv]$", ("layer", "heads")),
    (r"attn/wo$", ("layer", "heads", "embed")),
    (r"attn/bo$", ("layer", "embed")),
    (r"mlp/w[ig]$", ("layer", "embed", "mlp")),
    (r"mlp/bi$", ("layer", "mlp")),
    (r"mlp/wo$", ("layer", "mlp", "embed")),
    (r"mlp/bo$", ("layer", "embed")),
    (r"moe/router$", ("layer", "embed", None)),
    (r"moe/w[ig]$", ("layer", "expert", "embed", "mlp")),
    (r"moe/wo$", ("layer", "expert", "mlp", "embed")),
    # Qwen2-MoE shared expert: dense FFN shapes (no expert dim)
    (r"moe/shared/w[ig]$", ("layer", "embed", "mlp")),
    (r"moe/shared/wo$", ("layer", "mlp", "embed")),
    (r"moe/shared_gate$", ("layer", "embed", None)),
    # PR-MoE residual branch (ref moe/layer.py:83): dense FFN + Linear(h,2)
    (r"moe/residual/w[ig]$", ("layer", "embed", "mlp")),
    (r"moe/residual/wo$", ("layer", "mlp", "embed")),
    (r"moe/coef_w$", ("layer", "embed", None)),
    (r"moe/coef_b$", ("layer", None)),
    (r"ln\d/(scale|bias)$", ("layer", "norm")),
    (r"final_norm/(scale|bias)$", ("norm",)),
    (r"lm_head$", ("embed", "vocab")),
]


def path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def plan_mesh(n_devices: int,
              template: Optional[Mapping[str, int]] = None) -> Dict[str, int]:
    """Re-plan mesh axis sizes for a (possibly shrunk) device count.

    The recovery supervisor calls this when a host is gone and the
    surviving world must re-mesh before the universal-checkpoint resume:
    model-parallel axes from the previous run (``template``) are KEPT
    while they still divide the new world — their layouts are what the
    checkpoint's tensors expect to find useful — and the data axis
    absorbs whatever remains.  Axes that no longer fit are shed
    outermost-first (pipe, subdata, expert, seq, tensor): the innermost
    axes carry the highest-bandwidth collectives and the most intrusive
    layouts, so they are the last to fold into data parallelism.
    """
    if n_devices < 1:
        raise ValueError(f"plan_mesh: n_devices={n_devices}")
    template = dict(template or {})
    sizes = {ax: max(1, int(template.get(ax, 1)))
             for ax in MESH_AXES if ax != DATA_AXIS}
    shed_order = (PIPE_AXIS, SUBDATA_AXIS, EXPERT_AXIS, SEQ_AXIS, TENSOR_AXIS)
    prod = int(np.prod(list(sizes.values())))
    while prod > 1 and (n_devices % prod != 0 or prod > n_devices):
        for ax in shed_order:
            if sizes[ax] > 1:
                sizes[ax] = 1
                break
        prod = int(np.prod(list(sizes.values())))
    plan = dict(sizes)
    plan[DATA_AXIS] = n_devices // prod
    return {ax: int(plan.get(ax, 1)) for ax in MESH_AXES}


def secondary_mode_from_config(zero_config: Any) -> str:
    """hpZ / MiCS hierarchical-partitioning mode from a zero config block
    — shared by the engine (which factors the data axis BEFORE the mesh
    exists) and :meth:`PartitionOracle.from_config`."""
    if getattr(zero_config, "zero_hpz_partition_size", 1) > 1:
        return "hpz"
    if getattr(zero_config, "mics_shard_size", 0) > 0:
        return "mics"
    return "none"


class PartitionOracle:
    """Resolves param paths to PartitionSpecs/NamedShardings for a given
    topology + config.  See the module docstring for the single-source
    contract."""

    def __init__(self, topology: MeshTopology, zero_stage: int = 0,
                 rules: Optional[List[Tuple[str, Tuple[Optional[str], ...]]]] = None,
                 shard_norms: bool = True, secondary_mode: str = "none",
                 persist_threshold: int = 0):
        """``secondary_mode``: hierarchical partitioning over the factored
        (data=outer, subdata=inner) DP world —
          "hpz"  — ZeRO++ secondary partition: PARAMS shard only over the
                   inner axes (within-node gather rides ICI), optimizer/grad
                   state still shards over the full ZeRO world
                   (ref zero_hpz_partition_size, runtime/zero/config.py:300);
          "mics" — MiCS: params AND optimizer/grad state shard only within
                   the sub-group; the outer data axis is pure replication
                   with (XLA-inserted) hierarchical gradient allreduce
                   (ref MiCS_Init/MiCS_Optimizer, runtime/zero/mics.py).
        """
        self.topo = topology
        self.zero_stage = zero_stage
        self.rules = [(re.compile(pat), dims) for pat, dims in (rules or DEFAULT_RULES)]
        self.shard_norms = shard_norms
        if secondary_mode not in ("none", "hpz", "mics"):
            raise ValueError(f"secondary_mode {secondary_mode!r}")
        self.secondary_mode = secondary_mode
        # params with fewer elements than this stay gathered under ZeRO-3
        # (ref param_persistence_threshold, runtime/zero/config.py)
        self.persist_threshold = int(persist_threshold)

    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, topology: MeshTopology, config: Any,
                    **over) -> "PartitionOracle":
        """The engine-side construction recipe, in ONE place: zero stage,
        hpZ/MiCS secondary mode, and the persistence threshold (with the
        pinned ``step_schedule`` override winning over the static
        ``zero_optimization`` value) all come from a
        :class:`~deepspeed_tpu.runtime.config.DeepSpeedConfig`.  The
        recovery supervisor and the resumed engine both build their
        oracle through here, so a resume can never derive different
        specs than the run it resumes."""
        zc = config.zero_config
        persist = zc.param_persistence_threshold
        ss = getattr(config, "step_schedule", None)
        if ss is not None and ss.param_persistence_threshold is not None:
            persist = ss.param_persistence_threshold
        kw = dict(zero_stage=zc.stage,
                  secondary_mode=secondary_mode_from_config(zc),
                  persist_threshold=persist)
        kw.update(over)
        return cls(topology, **kw)

    # ------------------------------------------------------------------
    def _fsdp_axes(self, is_expert_param: bool,
                   param_style: bool) -> Tuple[str, ...]:
        if self.secondary_mode == "mics" or (self.secondary_mode == "hpz"
                                             and param_style):
            candidates = (SUBDATA_AXIS, EXPERT_AXIS, SEQ_AXIS)
        else:
            candidates = (DATA_AXIS, SUBDATA_AXIS, EXPERT_AXIS, SEQ_AXIS)
        axes = []
        for ax in candidates:
            if is_expert_param and ax == EXPERT_AXIS:
                continue  # expert dim already consumes the expert axis
            if self.topo.axis_size(ax) > 1:
                axes.append(ax)
        return tuple(axes)

    def _logical_dims(self, path: str, ndim: int) -> Optional[Tuple[Optional[str], ...]]:
        for pat, dims in self.rules:
            if pat.search(path):
                if len(dims) != ndim:
                    logger.warning(f"sharding rule for '{path}' has {len(dims)} dims, "
                                   f"array has {ndim}; replicating")
                    return None
                return dims
        return None

    def spec_for(self, path: str, shape: Tuple[int, ...],
                 param_style: bool = True) -> P:
        """PartitionSpec for a parameter array.

        ``param_style=True`` applies stage-3 fsdp sharding only when
        zero_stage == 3; pass False to get the always-fsdp spec used for
        optimizer state (stage>=1) and grad accumulators (stage>=2).
        """
        ndim = len(shape)
        dims = self._logical_dims(path, ndim)
        if dims is None:
            return P()
        is_expert = "expert" in dims
        fsdp_axes = self._fsdp_axes(is_expert, param_style)
        apply_fsdp = bool(fsdp_axes) and (not param_style or self.zero_stage >= 3)
        if apply_fsdp and param_style and self.persist_threshold:
            # persistent small params (ref param_persistence_threshold,
            # runtime/zero/parameter_offload.py persistent-param set):
            # keeping norms/biases gathered avoids a per-use all-gather
            # whose latency dwarfs its bytes; optimizer state
            # (param_style=False) stays partitioned like the reference.
            # The threshold is PER PARAMETER — divide out the stacked
            # layer dim, or every norm crosses it via L alone.
            elems = int(np.prod(shape)) if shape else 1
            if dims[0] == "layer" and shape:
                elems //= max(1, shape[0])
            if elems < self.persist_threshold:
                apply_fsdp = False
        tp = self.topo.tp_size > 1

        spec: List[Any] = [None] * ndim
        for i, d in enumerate(dims):
            if d == "layer" and self.topo.pp_size > 1:
                # stacked-layer axis → pipeline stages (ref PipelineModule
                # uniform partitioning, runtime/pipe/module.py:393)
                if shape[i] % self.topo.pp_size == 0:
                    spec[i] = PIPE_AXIS
            elif d == "expert" and self.topo.ep_size > 1:
                if shape[i] % self.topo.ep_size == 0:
                    spec[i] = EXPERT_AXIS
            elif d in ("mlp", "heads", "vocab") and tp:
                if shape[i] % self.topo.tp_size == 0:
                    spec[i] = TENSOR_AXIS

        if apply_fsdp:
            n_shard = int(np.prod([self.topo.axis_size(a) for a in fsdp_axes]))
            # Shape heuristic: prefer the designated fsdp dim
            # ("embed" / "norm" / "pos"), falling back to any unsharded
            # divisible dim.
            candidates = [i for i, d in enumerate(dims)
                          if d in ("embed", "norm", "pos") and spec[i] is None]
            if not self.shard_norms:
                candidates = [i for i in candidates if dims[i] != "norm"]
            candidates += [i for i, d in enumerate(dims)
                           if d in ("mlp", "heads", "vocab") and spec[i] is None]
            for i in candidates:
                if shape[i] % n_shard == 0:
                    spec[i] = fsdp_axes if len(fsdp_axes) > 1 else fsdp_axes[0]
                    break
        return P(*spec)

    # ------------------------------------------------------------------
    def audit_replicated(self, params, min_bytes: int = 1 << 20):
        """Large parameters that fall through ``spec_for``'s divisibility
        fallback and end up fully replicated despite a >1 shardable world.

        A big replicated tensor silently degrades ZeRO-3 to ZeRO-1 for
        that param (and AutoTP to no-op) — callers must surface this
        loudly rather than discover it as OOM at scale.  Returns
        ``[(path, shape, nbytes)]``; empty when every axis is size 1
        (nothing could shard) or all large params got a sharded dim.
        """
        fsdp_axes = self._fsdp_axes(False, param_style=True)
        fsdp_world = int(np.prod([self.topo.axis_size(a)
                                  for a in fsdp_axes])) if fsdp_axes else 1
        # pp deliberately excluded: pipeline shards only the stacked-layer
        # dim; embeds/head replicating across stages is by design
        shard_world = max(fsdp_world if self.zero_stage >= 3 else 1,
                          self.topo.tp_size)
        if shard_world <= 1:
            return []
        offenders = []

        def visit(path, leaf):
            shape = tuple(np.shape(leaf))
            dt = np.dtype(getattr(leaf, "dtype", np.float32))
            nbytes = int(np.prod(shape)) * dt.itemsize if shape else 0
            if nbytes < min_bytes:
                return
            spec = self.spec_for(path_str(path), shape, param_style=True)
            if all(s is None for s in spec):
                offenders.append((path_str(path), shape, nbytes))

        jax.tree_util.tree_map_with_path(visit, params)
        return offenders

    def tree_specs(self, params, param_style: bool = True):
        """Pytree of PartitionSpecs matching ``params``."""
        def leaf_spec(path, leaf):
            return self.spec_for(path_str(path), np.shape(leaf), param_style=param_style)

        return jax.tree_util.tree_map_with_path(leaf_spec, params)

    def tree_shardings(self, params, param_style: bool = True):
        specs = self.tree_specs(params, param_style=param_style)
        return jax.tree.map(lambda s: NamedSharding(self.topo.mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))

    def param_shardings(self, params):
        return self.tree_shardings(params, param_style=True)

    def optimizer_shardings(self, params):
        """Optimizer-state sharding: partitioned when stage >= 1 (ZeRO-1)."""
        return self.tree_shardings(params, param_style=self.zero_stage < 1)

    def grad_accum_shardings(self, params):
        """Grad-accumulator sharding: partitioned when stage >= 2 (ZeRO-2)."""
        return self.tree_shardings(params, param_style=self.zero_stage < 2)

    # -- flat (checkpoint) interface -----------------------------------
    def flat_specs(self, manifest: Mapping[str, Any],
                   param_style: bool = True) -> Dict[str, P]:
        """Specs for a FLAT ``{path: shape-or-array}`` manifest — the
        universal-checkpoint resharding entry: a saved flat checkpoint
        carries no pytree, only paths and shapes, and this is everything
        the oracle needs."""
        out: Dict[str, P] = {}
        for path, shp in manifest.items():
            shape = tuple(np.shape(shp)) if not isinstance(shp, (tuple, list)) \
                else tuple(int(s) for s in shp)
            out[path] = self.spec_for(path, shape, param_style=param_style)
        return out

    def flat_shardings(self, manifest: Mapping[str, Any],
                       param_style: bool = True) -> Dict[str, NamedSharding]:
        return {k: NamedSharding(self.topo.mesh, s)
                for k, s in self.flat_specs(manifest,
                                            param_style=param_style).items()}


# Historical name: the class predates the resilience subsystem.  It is
# the SAME object — parallel/sharding.py re-exports it — so there is
# exactly one spec derivation in the tree.
ShardingRules = PartitionOracle
