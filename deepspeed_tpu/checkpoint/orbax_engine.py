"""Orbax/tensorstore checkpoint engine: sharded, async, multi-host.

Analog of the reference's pluggable high-performance checkpoint engines —
``FastCheckpointEngine`` (double-buffered pinned I/O via
deepspeed/io/fast_file_writer.py) and ``DecoupledCheckpointEngine`` (async
commit in a separate process): orbax writes each shard from the process
that owns it through tensorstore with async commit, which is the
TPU-native equivalent of both.

Selected via ``"checkpoint": {"writer": {"type": "orbax"}, "async_save": true}``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


class OrbaxCheckpointEngine:
    def __init__(self, async_save: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.async_save = async_save
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler()) \
            if async_save else ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        self._pending = None

    def _reject_superoffload(self, engine) -> None:
        # SuperOffload keeps fp32 masters/moments host-side in _super_opt;
        # this writer's pytree contains only engine.opt_state, so a save
        # would silently drop them (and a load would be reverted by the
        # stale masters at the next push_params).  Refuse loudly; the
        # pickle/fast/decoupled writers round-trip SuperOffload state.
        if getattr(engine, "_super_opt", None) is not None:
            from deepspeed_tpu.runtime.config import DeepSpeedConfigError

            raise DeepSpeedConfigError(
                "offload_optimizer.super_offload is not supported by the "
                "orbax checkpoint writer — use writer type 'fast', "
                "'decoupled', or the default pickle engine")

    def save(self, engine, save_dir: str, tag: str,
             client_state: Optional[Dict[str, Any]] = None) -> None:
        self._reject_superoffload(engine)
        path = os.path.abspath(os.path.join(save_dir, str(tag), "orbax"))
        meta = {
            "global_steps": engine.global_steps,
            "micro_steps": engine.micro_steps,
            "lr_scheduler": engine.lr_scheduler.state_dict(),
            "client_state": client_state or {},
            "mesh_sizes": dict(engine.topology.sizes),
        }
        tree = {
            "params": engine.params,
            # offload-store mode: opt_state lives in the store, not on engine
            "opt_state": engine._opt_state_template(),
            "loss_scale_state": engine.loss_scale_state,
        }
        self.wait()  # one in-flight save at a time (double buffering)
        self._ckptr.save(path, tree, force=True)
        if self.async_save:
            # crash-atomic commit: meta.json + the `latest` pointer are
            # the COMPLETION markers — deferring them to wait() means a
            # process killed while tensorstore is still streaming shards
            # leaves `latest` pointing at the previous good tag, and a
            # recovery resume never reads a torn save
            self._pending = (path, save_dir, str(tag), meta)
            log_dist(f"orbax checkpoint queued: {path}")
            return
        self._commit(save_dir, str(tag), meta)
        log_dist(f"orbax checkpoint saved: {path}")

    @staticmethod
    def _commit(save_dir: str, tag: str, meta) -> None:
        import json

        if jax.process_index() == 0:
            os.makedirs(os.path.join(save_dir, tag), exist_ok=True)
            with open(os.path.join(save_dir, tag, "meta.json"), "w") as f:
                json.dump(meta, f)
            with open(os.path.join(save_dir, "latest"), "w") as f:
                f.write(tag)

    def wait(self) -> None:
        """Block until the in-flight async save commits, then publish its
        meta.json + `latest` pointer (the commit point)."""
        if self._pending is not None:
            path, save_dir, tag, meta = self._pending
            self._ckptr.wait_until_finished()
            self._commit(save_dir, tag, meta)
            self._pending = None
            log_dist(f"orbax checkpoint committed: {path}")

    def load(self, engine, load_dir: str, tag: Optional[str] = None,
             load_optimizer_states: bool = True,
             load_lr_scheduler_states: bool = True):
        import json

        self._reject_superoffload(engine)
        self.wait()  # an uncommitted in-flight save is invisible until it lands
        if tag is None:
            with open(os.path.join(load_dir, "latest")) as f:
                tag = f.read().strip()
        path = os.path.abspath(os.path.join(load_dir, str(tag), "orbax"))
        opt_shardings = (engine._opt_device_shardings if engine._opt_store is not None
                         else engine.opt_shardings)
        template = {
            "params": engine.params,
            "opt_state": engine._opt_state_template(),
            "loss_scale_state": engine.loss_scale_state,
        }
        shardings = {
            "params": engine.param_shardings,
            "opt_state": opt_shardings,
            "loss_scale_state": jax.tree.map(lambda _: engine._replicated,
                                             engine.loss_scale_state),
        }
        restore_args = jax.tree.map(
            lambda t, s: self._ocp.ArrayRestoreArgs(sharding=s, dtype=t.dtype),
            template, shardings)
        tree = self._ckptr.restore(
            path, args=self._ocp.args.PyTreeRestore(
                item=template,
                restore_args=restore_args))
        engine.params = tree["params"]
        if load_optimizer_states:
            engine.opt_state = tree["opt_state"]
        engine.loss_scale_state = tree["loss_scale_state"]
        with open(os.path.join(load_dir, str(tag), "meta.json")) as f:
            meta = json.load(f)
        if load_lr_scheduler_states and meta.get("lr_scheduler") is not None:
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        engine.global_steps = int(meta["global_steps"])
        engine.micro_steps = int(meta["micro_steps"])
        log_dist(f"orbax checkpoint loaded: {path}")
        return path, meta.get("client_state", {})
