"""Checkpoint save/load.

Analog of ``runtime/engine.py:3610/3262`` (save_checkpoint/load_checkpoint)
plus the pluggable CheckpointEngine (ref runtime/checkpoint_engine/).  The
default format stores each leaf as a ``.npy``-style entry inside one pickle
per checkpoint tag, with sharded arrays gathered to host (single-controller
JAX owns all shards in-process, so this is addressable-shard I/O, not a
network gather).  The universal-checkpoint converter lives in
``deepspeed_tpu/checkpoint/universal.py``.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"


def _to_host(tree):
    """Gather arrays to host. Multi-host fully-sharded arrays are gathered
    via process_allgather so every process can serialize a full copy."""
    def get(x):
        if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(get, tree)


def _ckpt_path(save_dir: str, tag: str) -> str:
    # one state file per process (multi-host writes its own shard file)
    return os.path.join(save_dir, str(tag),
                        f"mp_rank_{jax.process_index():02d}_model_states.pt")


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict[str, Any]] = None) -> None:
    tag = tag if tag is not None else f"global_step{engine.global_steps}"
    os.makedirs(os.path.join(save_dir, str(tag)), exist_ok=True)
    if getattr(engine, "_super_opt", None) is not None:
        # SuperOffload: masters/moments live in the host optimizer
        opt_tree = {"superoffload": engine._super_opt.state_dict()}
    elif getattr(engine, "_opt_store", None) is not None:
        # join any pipelined prefetch first (single-owner AIO handle)
        read = getattr(engine, "_opt_store_read", engine._opt_store.swap_in)
        opt_tree = read()
    else:
        opt_tree = engine.opt_state
    state = {
        "module": _to_host(engine.params),
        "optimizer": _to_host(opt_tree),
        "loss_scale_state": _to_host(engine.loss_scale_state),
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "client_state": client_state or {},
        "ds_config": engine.config.to_dict(),
        "mesh_sizes": dict(engine.topology.sizes),
    }
    path = _ckpt_path(save_dir, tag)
    with open(path, "wb") as f:
        pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
    if jax.process_index() == 0:
        with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
            f.write(str(tag))
    log_dist(f"saved checkpoint: {path}")


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True):
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            logger.warning(f"no '{LATEST_FILE}' file in {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = _ckpt_path(load_dir, tag)
    if not os.path.exists(path):
        logger.warning(f"checkpoint {path} not found")
        return None, {}
    with open(path, "rb") as f:
        state = pickle.load(f)

    engine.params = jax.device_put(state["module"], engine.param_shardings)
    opt = state.get("optimizer")
    opt_is_super = isinstance(opt, dict) and "superoffload" in opt
    engine_is_super = getattr(engine, "_super_opt", None) is not None
    if load_optimizer_states and opt is not None \
            and opt_is_super != engine_is_super:
        raise ValueError(
            "checkpoint optimizer mode mismatch: the checkpoint was saved "
            + ("with" if opt_is_super else "without")
            + " SuperOffload but this engine is configured "
            + ("without" if opt_is_super else "with")
            + " it — match offload_optimizer.super_offload, or pass "
            "load_optimizer_states=False to resume weights only")
    if engine_is_super and not (load_optimizer_states and opt_is_super):
        # weights-only resume: re-seed the host masters or the next
        # push_params would revert the freshly loaded params
        engine._super_opt.reset_masters(engine.params)
    if load_optimizer_states and opt_is_super and engine_is_super:
        engine._super_opt.load_state_dict(opt["superoffload"])
    elif load_optimizer_states and opt is not None:
        # store-mode engines rely on this device placement too:
        # _sync_store_after_load pushes it into the host/NVMe store
        engine.opt_state = jax.device_put(opt, engine.opt_shardings)
    if "loss_scale_state" in state:
        engine.loss_scale_state = jax.device_put(state["loss_scale_state"],
                                                 engine._replicated)
    if load_lr_scheduler_states and state.get("lr_scheduler") is not None:
        engine.lr_scheduler.load_state_dict(state["lr_scheduler"])
    engine.global_steps = int(state.get("global_steps", 0))
    engine.micro_steps = int(state.get("micro_steps", 0))
    log_dist(f"loaded checkpoint: {path} (step {engine.global_steps})")
    return path, state.get("client_state", {})
