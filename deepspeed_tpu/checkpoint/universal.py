"""Universal checkpoint: per-parameter atomic format + any-topology reload.

Re-design of the reference's UCP (``deepspeed/checkpoint/ds_to_universal.py``
:112/:152/:232, loader ``universal_checkpoint.py:22``, offline consolidation
``utils/zero_to_fp32.py``): the reference must merge per-rank ZeRO shards and
TP slices into atomic per-param files; here global arrays are already
logical wholes (single-controller JAX), so the converter writes one ``.npy``
per parameter path and reload re-shards onto whatever mesh the new engine
has — the target engine's :class:`~deepspeed_tpu.resilience.oracle.
PartitionOracle` supplies every leaf's spec, so world-size elasticity
(different dp/fsdp/tp factorizations, shrunk worlds) falls out of the
name-based derivation rather than any saved placement.

Crash atomicity (docs/ELASTICITY.md): the converter writes into a
``universal.tmp-<pid>`` staging directory, stamps a completion marker
(:data:`COMMIT_MARKER`) as its LAST file, and ``os.replace``s the staged
dir into place — the final path either does not exist or is complete.  A
recovery supervisor resuming from "the latest checkpoint" therefore
never reads a torn save: :func:`resolve_universal_dir` requires the
marker and falls back to the newest committed tag when the ``latest``
pointer names an uncommitted one (the exact state a worker killed
mid-save leaves behind).

Layout:
    <dir>/universal/
        meta.json                 # step counters, config, param manifest
        .committed                # completion marker (written last)
        params/<path>.npy         # fp32 master weights
        optimizer/<path>.npy      # flattened optimizer state leaves
"""

from __future__ import annotations

import glob
import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

COMMIT_MARKER = ".committed"


class _SizesOnlyTopology:
    """Duck-typed stand-in for MeshTopology when only axis SIZES matter:
    ``PartitionOracle.flat_specs`` never touches ``.mesh``, so the
    converter can record the source run's specs without owning that many
    devices (it may run on a one-chip head node)."""

    def __init__(self, sizes: Dict[str, int]):
        from deepspeed_tpu.parallel.topology import MESH_AXES

        self.sizes = {ax: int(sizes.get(ax, 1)) for ax in MESH_AXES}

    def axis_size(self, axis: str) -> int:
        return self.sizes[axis]

    @property
    def tp_size(self) -> int:
        return self.sizes["tensor"]

    @property
    def pp_size(self) -> int:
        return self.sizes["pipe"]

    @property
    def ep_size(self) -> int:
        return self.sizes["expert"]

    @property
    def sp_size(self) -> int:
        return self.sizes["seq"]


def _source_specs(mesh_sizes: Dict[str, int], ds_config: Dict[str, Any],
                  manifest: Dict[str, Tuple[int, ...]]) -> Dict[str, str]:
    """The source run's oracle-derived param specs, recorded for
    forensics: a resumed engine (or ``graft_lint --rows``) can diff its
    own oracle's answers against what the saving run intended."""
    from deepspeed_tpu.resilience.oracle import PartitionOracle

    topo = _SizesOnlyTopology(mesh_sizes or {})
    try:
        # the engine's own construction recipe — hpZ/MiCS secondary mode
        # and the pinned step_schedule persistence override included —
        # so the recorded specs are what the saving run ACTUALLY used
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        oracle = PartitionOracle.from_config(topo, DeepSpeedConfig(ds_config))
    except Exception as e:
        # a partial/legacy ds_config must not make the checkpoint
        # unconvertible — degrade to the static zero block
        logger.warning(f"source_specs: ds_config no longer parses, "
                       f"falling back to the static zero block ({e})")
        zc = (ds_config or {}).get("zero_optimization", {}) or {}
        oracle = PartitionOracle(
            topo, zero_stage=int(zc.get("stage", 0)),
            persist_threshold=int(zc.get("param_persistence_threshold", 0) or 0))
    return {k: str(v) for k, v in oracle.flat_specs(manifest).items()}


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        from deepspeed_tpu.resilience.oracle import path_str

        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            # ds_to_universal runs on process 0 only, so a cross-process
            # gather here would hang — the converter's inputs must already
            # be host-complete (the pickle engine allgathers at save time)
            raise ValueError(
                "universal converter got a non-fully-addressable array; "
                "convert from a saved checkpoint (engine.save_checkpoint), "
                "not from live multi-host state")
        flat[path_str(path)] = np.asarray(leaf)
    return flat


def _save_flat(flat: Dict[str, np.ndarray], root: str) -> None:
    for path, arr in flat.items():
        fname = os.path.join(root, path.replace("/", "__") + ".npy")
        np.save(fname, arr)


def _load_flat(root: str) -> Dict[str, np.ndarray]:
    out = {}
    for fname in sorted(os.listdir(root)):
        if fname.endswith(".npy"):
            out[fname[:-4].replace("__", "/")] = np.load(os.path.join(root, fname))
    return out


def is_committed(universal_dir: str) -> bool:
    """A universal dir is readable iff its completion marker exists —
    the staging-dir rename makes this redundant for the FINAL path, but
    a crashed ``os.replace``-capable filesystem is not guaranteed
    everywhere the bundle may be rsynced to."""
    return (os.path.exists(os.path.join(universal_dir, "meta.json"))
            and os.path.exists(os.path.join(universal_dir, COMMIT_MARKER)))


def ds_to_universal(ckpt_dir: str, tag: Optional[str] = None,
                    output_dir: Optional[str] = None) -> str:
    """Convert a saved checkpoint to the universal per-param format.
    Ref: ds_to_universal.py main flow (extract shards → merge → per-param).

    Crash-atomic: everything lands in a staging dir that is renamed into
    place only after the completion marker is written."""
    from deepspeed_tpu.checkpoint.engine import LATEST_FILE, _ckpt_path

    if tag is None:
        with open(os.path.join(ckpt_dir, LATEST_FILE)) as f:
            tag = f.read().strip()

    out = output_dir or os.path.join(ckpt_dir, str(tag), "universal")
    if jax.process_count() > 1 and jax.process_index() != 0:
        # each process's pickle holds the full (allgathered) state; one
        # writer suffices on a shared FS — wait for process 0 to finish,
        # and surface its failure instead of returning a broken dir
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(np.array([1], np.int32))
        if not bool(flags.min()):
            raise RuntimeError("universal conversion failed on process 0")
        return out

    staging = f"{out}.tmp-{os.getpid()}"
    ok = False
    try:
        with open(_ckpt_path(ckpt_dir, tag), "rb") as f:
            state = pickle.load(f)

        # sweep debris from earlier killed conversions (any pid): torn
        # staging dirs and aside dirs a swap never finished deleting
        for stale in glob.glob(f"{out}.tmp-*") + glob.glob(f"{out}.old-*"):
            shutil.rmtree(stale, ignore_errors=True)
        if os.path.exists(staging):
            shutil.rmtree(staging)
        os.makedirs(os.path.join(staging, "params"))
        os.makedirs(os.path.join(staging, "optimizer"))

        params_flat = _flatten_with_paths(state["module"])
        _save_flat(params_flat, os.path.join(staging, "params"))
        opt_flat = _flatten_with_paths(state["optimizer"])
        _save_flat(opt_flat, os.path.join(staging, "optimizer"))

        manifest = {k: tuple(v.shape) for k, v in params_flat.items()}
        meta = {
            "global_steps": state.get("global_steps", 0),
            "micro_steps": state.get("micro_steps", 0),
            "lr_scheduler": state.get("lr_scheduler"),
            "loss_scale_state": {k: float(np.asarray(v))
                                 for k, v in state.get("loss_scale_state",
                                                       {}).items()},
            "param_manifest": {k: list(v) for k, v in manifest.items()},
            "param_dtypes": {k: str(v.dtype) for k, v in params_flat.items()},
            "opt_treedef_leaves": len(opt_flat),
            "ds_config": state.get("ds_config", {}),
            "source_mesh": state.get("mesh_sizes", {}),
            "source_specs": _source_specs(state.get("mesh_sizes", {}),
                                          state.get("ds_config", {}),
                                          manifest),
        }
        with open(os.path.join(staging, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
        # marker LAST, then the atomic publish: the final path either
        # doesn't exist or is complete (mid-save kill leaves only a
        # .tmp-* dir, which resolve_universal_dir never reads)
        with open(os.path.join(staging, COMMIT_MARKER), "w") as f:
            json.dump({"time_unix": time.time(), "pid": os.getpid()}, f)
        old = None
        if os.path.exists(out):
            # swap the previously committed conversion ASIDE (atomic
            # rename) instead of rmtree'ing it first: a kill during a
            # tree delete would destroy the only committed copy of this
            # tag while the replacement sits unpublished in staging —
            # two renames shrink that window to microseconds and keep
            # the old bytes recoverable at .old-* until the new dir is
            # live
            old = f"{out}.old-{os.getpid()}"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.replace(out, old)
        os.replace(staging, out)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
        ok = True
    finally:
        if not ok and os.path.isdir(staging):
            shutil.rmtree(staging, ignore_errors=True)
        if jax.process_count() > 1:
            # ALWAYS release the non-writer processes — a writer exception
            # must raise on process 0, not hang processes 1..N — and tell
            # them whether the conversion actually succeeded
            from jax.experimental import multihost_utils

            multihost_utils.process_allgather(
                np.array([1 if ok else 0], np.int32))
    log_dist(f"universal checkpoint written: {out}")
    return out


def _scan_committed(load_dir: str) -> Optional[str]:
    """Newest committed ``<load_dir>/<tag>/universal`` by step count
    (mtime breaks ties) — the fall-back when the ``latest`` pointer
    names a tag whose conversion never committed."""
    best = None
    best_key = None
    try:
        tags = sorted(os.listdir(load_dir))
    except OSError:
        return None
    for t in tags:
        cand = os.path.join(load_dir, t, "universal")
        if not is_committed(cand):
            continue
        try:
            with open(os.path.join(cand, "meta.json")) as f:
                steps = int(json.load(f).get("global_steps", 0))
        except (OSError, ValueError):
            continue
        key = (steps, os.path.getmtime(os.path.join(cand, COMMIT_MARKER)))
        if best_key is None or key > best_key:
            best, best_key = cand, key
    return best


def resolve_universal_dir(load_dir: str, tag: Optional[str] = None) -> str:
    """Accept either the universal dir itself, a checkpoint root (+tag), or a
    checkpoint root with a ``latest`` file.  Uncommitted dirs (no
    completion marker — a save died mid-write) are SKIPPED: when the
    ``latest`` pointer names a torn tag, the newest committed tag under
    the root wins, so a supervisor restart after a mid-save kill resumes
    from the last good checkpoint instead of crashing on a torn one.  An
    explicitly requested ``tag`` never falls back — a missing requested
    tag raises."""
    if os.path.exists(os.path.join(load_dir, "meta.json")):
        if not is_committed(load_dir):
            raise FileNotFoundError(
                f"universal checkpoint {load_dir} is uncommitted "
                f"(missing {COMMIT_MARKER}) — either the save died "
                f"mid-write, or the dir predates the crash-atomic commit "
                f"protocol; re-run ds_to_universal on the source "
                f"checkpoint to regenerate it")
        return load_dir
    explicit_tag = tag is not None
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
    if tag is not None:
        cand = os.path.join(load_dir, str(tag), "universal")
        if is_committed(cand):
            return cand
        if explicit_tag:
            # a caller-requested tag is a contract: silently resuming
            # from some OTHER (older) committed tag would load the wrong
            # checkpoint — the fallback is only for the tag the `latest`
            # pointer named (the mid-save-kill recovery case)
            raise FileNotFoundError(
                f"universal checkpoint for requested tag {tag!r} is "
                f"missing or uncommitted under {load_dir}")
        fallback = _scan_committed(load_dir)
        if fallback is not None:
            logger.warning(
                f"universal checkpoint for tag {tag!r} is missing or "
                f"uncommitted; resuming from {fallback} instead")
            return fallback
    else:
        fallback = _scan_committed(load_dir)
        if fallback is not None:
            return fallback
    raise FileNotFoundError(f"no committed universal checkpoint under "
                            f"{load_dir} (tag={tag})")


def load_universal(engine, universal_dir: str) -> None:
    """Load a universal checkpoint into an engine with ANY mesh topology
    (ref load_hp_checkpoint_state, universal_checkpoint.py:22).

    Resharding is the oracle's job: ``engine.param_shardings`` /
    ``engine.opt_shardings`` are the target engine's
    :class:`~deepspeed_tpu.resilience.oracle.PartitionOracle` output
    (plus any engine-side memory-kind placement), so ``device_put``
    lands every leaf on the new mesh regardless of the dp/fsdp/tp
    factorization — or world size — the checkpoint was saved under.
    Every leaf is shape- and dtype-validated against the engine's
    template before any state is mutated."""
    universal_dir = resolve_universal_dir(universal_dir)
    with open(os.path.join(universal_dir, "meta.json")) as f:
        meta = json.load(f)

    params_flat = _load_flat(os.path.join(universal_dir, "params"))
    params = _unflatten_like(engine.params, params_flat, what="params")

    opt_flat = _load_flat(os.path.join(universal_dir, "optimizer"))
    template = engine._opt_state_template()
    opt_state = None
    if opt_flat and template is not None:
        opt_state = _unflatten_like(template, opt_flat, what="optimizer")

    # both trees validated — only now mutate the engine
    engine.params = jax.device_put(params, engine.param_shardings)
    if opt_state is not None:
        # store mode: device placement is transient (engine pushes to the
        # store right after); stream mode: resident (possibly host) shardings
        target = (engine._opt_device_shardings if engine._opt_store is not None
                  else engine.opt_shardings)
        engine.opt_state = jax.device_put(opt_state, target)

    if meta.get("loss_scale_state"):
        import jax.numpy as jnp

        ls = meta["loss_scale_state"]
        engine.loss_scale_state = jax.device_put(
            {"scale": jnp.float32(ls.get("scale", 1.0)),
             "good_steps": jnp.int32(int(ls.get("good_steps", 0))),
             "skipped": jnp.int32(int(ls.get("skipped", 0)))},
            engine._replicated)
    if meta.get("lr_scheduler"):
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    engine.global_steps = int(meta.get("global_steps", 0))
    engine.micro_steps = int(meta.get("micro_steps", 0))
    log_dist(f"universal checkpoint loaded from {universal_dir} "
             f"(source mesh {meta.get('source_mesh')} → {engine.topology.sizes})")


def _unflatten_like(template, flat: Dict[str, np.ndarray],
                    what: str = "checkpoint"):
    """Rebuild a pytree with ``template``'s structure from path→array dict,
    validating every leaf's shape and dtype compatibility first."""
    from deepspeed_tpu.resilience.oracle import path_str

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = path_str(path)
        if key not in flat:
            raise KeyError(f"universal {what} missing entry '{key}'")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"shape mismatch for '{key}': "
                             f"checkpoint {arr.shape} vs model {np.shape(leaf)}")
        target_dt = np.dtype(getattr(leaf, "dtype", arr.dtype))
        if target_dt != arr.dtype and not np.can_cast(
                arr.dtype, target_dt, casting="same_kind"):
            raise ValueError(
                f"dtype mismatch for '{key}': checkpoint {arr.dtype} is "
                f"not same-kind castable to model {target_dt} — the "
                "checkpoint belongs to a differently-typed model")
        new_leaves.append(arr.astype(target_dt))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def zero_to_fp32(ckpt_dir: str, output_file: str, tag: Optional[str] = None) -> str:
    """Offline consolidation to a single fp32 state dict file
    (ref utils/zero_to_fp32.py). Master params are fp32 already; this writes
    a flat ``{path: np.float32 array}`` pickle loadable without the engine."""
    from deepspeed_tpu.checkpoint.engine import LATEST_FILE, _ckpt_path

    if tag is None:
        with open(os.path.join(ckpt_dir, LATEST_FILE)) as f:
            tag = f.read().strip()
    with open(_ckpt_path(ckpt_dir, tag), "rb") as f:
        state = pickle.load(f)
    flat = {k: v.astype(np.float32)
            for k, v in _flatten_with_paths(state["module"]).items()}
    with open(output_file, "wb") as f:
        pickle.dump(flat, f, protocol=pickle.HIGHEST_PROTOCOL)
    log_dist(f"fp32 consolidated state dict: {output_file} ({len(flat)} tensors)")
    return output_file
