"""Fast + decoupled checkpoint engines.

Analogs of ``deepspeed/runtime/checkpoint_engine/``:
``FastCheckpointEngine`` (FastFileWriter-backed, double-buffered pinned
I/O) and ``DecoupledCheckpointEngine`` (async save on a worker with a
commit protocol — ref ``CheckpointCommitInfo`` :15: the ``latest`` pointer
only advances after every file of the tag has landed, so a crash mid-save
never leaves a half checkpoint as the resume target).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.checkpoint.engine import LATEST_FILE
from deepspeed_tpu.io.fast_file_writer import (FastFileWriter,
                                               read_tensor_file,
                                               write_tensor_file)
from deepspeed_tpu.utils.logging import log_dist, logger


def _leaf_name(prefix: str, path) -> str:
    return prefix + "/" + "/".join(
        str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def _shard_bounds(index, shape):
    """Concrete [start, stop) bounds per dim from a shard's index slices."""
    bounds = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        bounds.append([start, stop])
    return bounds


def _flatten(tree, prefix: str):
    """Flatten a pytree into (entries, shard_index) writing only THIS
    process's addressable data.  Multi-host rule: each process writes its
    replica-0 addressable shards with their global bounding boxes; arrays
    with no device shards (host numpy) are written whole by process 0.
    Single-process, this degenerates to one full entry per leaf."""
    entries: Dict[str, np.ndarray] = {}
    index: Dict[str, Dict] = {}
    proc = jax.process_index()
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = _leaf_name(prefix, path)
        if isinstance(leaf, jax.Array):
            shape = leaf.shape
            full = None
            for k, sh in enumerate(leaf.addressable_shards):
                if sh.replica_id != 0:
                    continue
                data = np.asarray(sh.data)
                if data.shape == tuple(shape):
                    full = data  # replicated / single-shard: one full entry
                    break
                ename = f"{name}@p{proc}s{k}"
                entries[ename] = data
                index[ename] = {"leaf": name, "shape": list(shape),
                                "slices": _shard_bounds(sh.index, shape)}
            if full is not None:
                entries[name] = full
        elif proc == 0:
            entries[name] = np.asarray(leaf)
    return entries, index


def _opt_tree_for_save(engine):
    """Optimizer tree to serialize.  SuperOffload keeps the fp32 masters and
    moments in the host optimizer (``engine.opt_state`` is None), so saves
    must round-trip ``_super_opt.state_dict()`` — mirroring the pickle
    engine (checkpoint/engine.py) — or the restore silently loses them."""
    if getattr(engine, "_super_opt", None) is not None:
        return {"superoffload": engine._super_opt.state_dict()}
    if getattr(engine, "_opt_store", None) is not None:
        # join any pipelined prefetch first (single-owner AIO handle)
        read = getattr(engine, "_opt_store_read", engine._opt_store.swap_in)
        return read()
    return engine.opt_state


class _CheckpointReader:
    """Lazy view over every process's tensor file + shard index in a
    checkpoint dir: only the small JSON indices are read up front; entry
    bytes are fetched on demand so a host never materializes more than one
    leaf beyond what it keeps."""

    def __init__(self, d: str):
        import glob

        from deepspeed_tpu.io.fast_file_writer import read_tensor_index

        bins = sorted(glob.glob(os.path.join(d, "model_states*.bin")))
        if not bins:
            raise FileNotFoundError(f"no model_states*.bin under {d}")
        # entry → (file, base offset, index record); headers are parsed
        # ONCE here, fetches are targeted seeks via read_tensor_entry
        self.entry_meta: Dict[str, tuple] = {}
        for b in bins:
            index, base = read_tensor_index(b)
            for name, m in index.items():
                self.entry_meta[name] = (b, base, m)
        self.shard_index: Dict[str, Dict] = {}
        for j in sorted(glob.glob(os.path.join(d, "shard_index*.json"))):
            with open(j) as f:
                self.shard_index.update(json.load(f))
        self.by_leaf: Dict[str, list] = {}
        for ename, info in self.shard_index.items():
            self.by_leaf.setdefault(info["leaf"], []).append((ename, info))

    def has_prefix(self, prefix: str) -> bool:
        p = prefix + "/"
        return any(n.startswith(p) for n in self.entry_meta) or any(
            i["leaf"].startswith(p) for i in self.shard_index.values())

    def _fetch(self, ename: str) -> np.ndarray:
        from deepspeed_tpu.io.fast_file_writer import read_tensor_entry

        path, base, meta = self.entry_meta[ename]
        return read_tensor_entry(path, base, meta)

    def read_leaf(self, name: str) -> np.ndarray:
        if name in self.entry_meta and name not in self.shard_index:
            return self._fetch(name)
        if name in self.by_leaf:
            pieces = self.by_leaf[name]
            shape = tuple(pieces[0][1]["shape"])
            first = self._fetch(pieces[0][0])
            arr = np.empty(shape, first.dtype)
            covered = 0
            for k, (ename, info) in enumerate(pieces):
                data = first if k == 0 else self._fetch(ename)
                sl = tuple(slice(a, b) for a, b in info["slices"])
                arr[sl] = data
                covered += data.size
            if covered < arr.size:
                raise ValueError(f"incomplete shards for '{name}': "
                                 f"{covered}/{arr.size} elements")
            return arr
        raise KeyError(f"checkpoint missing entry '{name}'")


def _load_tree(template, shardings, reader: _CheckpointReader, prefix: str):
    """Rebuild ``template``'s structure, device_put-ting one leaf at a time
    (host residency stays O(largest leaf), not O(model))."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    sh_leaves = jax.tree_util.tree_flatten(shardings)[0]
    leaves = []
    for (path, leaf), sh in zip(paths, sh_leaves):
        arr = reader.read_leaf(_leaf_name(prefix, path))
        arr = arr.astype(leaf.dtype).reshape(np.shape(leaf))
        leaves.append(jax.device_put(arr, sh))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _load_host_tree(template, reader: _CheckpointReader, prefix: str):
    """Rebuild ``template`` as host numpy (no device placement) — for
    host-resident optimizer state (SuperOffload masters/moments)."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        arr = reader.read_leaf(_leaf_name(prefix, path))
        tl = np.asarray(leaf)
        leaves.append(arr.astype(tl.dtype).reshape(tl.shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


class FastCheckpointEngine:
    """Indexed-binary checkpoint via FastFileWriter (ref
    FastCheckpointEngine): one ``model_states.bin`` per tag holding params
    + optimizer + a JSON meta sidecar."""

    name = "fast"

    def __init__(self, buffer_bytes: int = 32 << 20):
        self.buffer_bytes = buffer_bytes

    def _paths(self, save_dir: str, tag: str):
        d = os.path.join(save_dir, str(tag))
        # per-process files: multi-host processes on a shared FS must not
        # clobber each other (only 'latest' and meta.json are rank-gated)
        proc, nproc = jax.process_index(), jax.process_count()
        stem = "model_states" if nproc == 1 else f"model_states_p{proc:03d}"
        return (d, os.path.join(d, stem + ".bin"),
                os.path.join(d, "meta.json"),
                os.path.join(d, "shard_index.json" if nproc == 1
                             else f"shard_index_p{proc:03d}.json"))

    def save(self, engine, save_dir: str, tag: str,
             client_state: Optional[Dict[str, Any]] = None) -> str:
        import glob

        d, bin_path, meta_path, idx_path = self._paths(save_dir, tag)
        os.makedirs(d, exist_ok=True)
        # clear a previous save of this tag (possibly from a DIFFERENT
        # process count — stale per-process files would otherwise be merged
        # back in on load); process 0 cleans, everyone else waits
        if jax.process_index() == 0:
            for stale in (glob.glob(os.path.join(d, "model_states*.bin"))
                          + glob.glob(os.path.join(d, "shard_index*.json"))):
                os.unlink(stale)
        if jax.process_count() > 1:
            from deepspeed_tpu.comm import comm

            comm.barrier()
        opt_tree = _opt_tree_for_save(engine)
        ok = False
        all_ok = True
        try:
            tensors, shard_idx = _flatten(engine.params, "module")
            if opt_tree is not None:
                t, i = _flatten(opt_tree, "optimizer")
                tensors.update(t)
                shard_idx.update(i)
            t, i = _flatten(engine.loss_scale_state, "loss_scale")
            tensors.update(t)
            shard_idx.update(i)
            stats = write_tensor_file(bin_path, tensors, FastFileWriter,
                                      buffer_bytes=self.buffer_bytes)
            if shard_idx or jax.process_count() > 1:
                with open(idx_path, "w") as f:
                    json.dump(shard_idx, f)
            if jax.process_index() == 0:
                meta = {"global_steps": engine.global_steps,
                        "micro_steps": engine.micro_steps,
                        "lr_scheduler": engine.lr_scheduler.state_dict(),
                        "client_state": client_state or {},
                        "mesh_sizes": dict(engine.topology.sizes),
                        "process_count": jax.process_count(),
                        "io_stats": stats}
                with open(meta_path, "w") as f:
                    json.dump(meta, f)
            ok = True
        finally:
            if jax.process_count() > 1:
                # every process's file must land before the commit — the
                # rendezvous must be reached even if THIS process's write
                # threw (or the healthy processes hang forever), and it
                # carries a success flag so 'latest' is only advanced when
                # EVERY process's shard landed
                from jax.experimental import multihost_utils

                flags = multihost_utils.process_allgather(
                    np.array([1 if ok else 0], np.int32))
                all_ok = bool(flags.min())
        if not all_ok:
            raise RuntimeError(
                f"fast checkpoint save of tag '{tag}' failed on a peer "
                f"process; 'latest' not advanced")
        if jax.process_index() == 0:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
        log_dist(f"fast checkpoint saved: {bin_path} "
                 f"({stats['bytes_written']} bytes)")
        return bin_path

    def load(self, engine, load_dir: str, tag: Optional[str] = None,
             load_optimizer_states: bool = True,
             load_lr_scheduler_states: bool = True):
        if tag is None:
            latest = os.path.join(load_dir, LATEST_FILE)
            if not os.path.exists(latest):
                logger.warning(f"no {LATEST_FILE} in {load_dir}")
                return None, {}
            tag = open(latest).read().strip()
        d, bin_path, meta_path, _ = self._paths(load_dir, tag)
        reader = _CheckpointReader(d)
        engine.params = _load_tree(engine.params, engine.param_shardings,
                                   reader, "module")
        ckpt_is_super = reader.has_prefix("optimizer/superoffload")
        engine_is_super = getattr(engine, "_super_opt", None) is not None
        if load_optimizer_states and reader.has_prefix("optimizer") \
                and ckpt_is_super != engine_is_super:
            raise ValueError(
                "checkpoint optimizer mode mismatch: the checkpoint was saved "
                + ("with" if ckpt_is_super else "without")
                + " SuperOffload but this engine is configured "
                + ("without" if ckpt_is_super else "with")
                + " it — match offload_optimizer.super_offload, or pass "
                "load_optimizer_states=False to resume weights only")
        if engine_is_super and not (load_optimizer_states and ckpt_is_super):
            # weights-only resume: re-seed the host masters or the next
            # push_params would revert the freshly loaded params
            engine._super_opt.reset_masters(engine.params)
        if load_optimizer_states and ckpt_is_super and engine_is_super:
            engine._super_opt.load_state_dict(
                _load_host_tree(engine._super_opt.state_dict(), reader,
                                "optimizer/superoffload"))
        elif load_optimizer_states and engine.opt_state is not None \
                and reader.has_prefix("optimizer"):
            engine.opt_state = _load_tree(engine.opt_state,
                                          engine.opt_shardings, reader,
                                          "optimizer")
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = int(meta["global_steps"])
        engine.micro_steps = int(meta["micro_steps"])
        if load_lr_scheduler_states and meta.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        log_dist(f"fast checkpoint loaded: {d}")
        # return the tag DIRECTORY: per-process bin names depend on the
        # process count at save time, which may differ from now
        return d, meta.get("client_state", {})

    def wait(self) -> None:  # synchronous engine
        pass


class DecoupledCheckpointEngine:
    """Async save with commit protocol (ref DecoupledCheckpointEngine):
    ``save`` snapshots host copies and returns; a worker writes them and
    commits ``latest`` last.  ``wait()`` blocks until the commit."""

    name = "decoupled"

    def __init__(self, inner: Optional[FastCheckpointEngine] = None):
        self.inner = inner or FastCheckpointEngine()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, engine, save_dir: str, tag: str,
             client_state: Optional[Dict[str, Any]] = None) -> str:
        self.wait()
        if jax.process_count() > 1:
            # multi-host: the inner save runs collectives (cleanup barrier,
            # commit barrier) that must not execute on a side thread racing
            # the training stream, and the numpy snapshot below cannot hold
            # non-addressable arrays — save synchronously instead
            logger.warning("decoupled checkpointing is single-host only; "
                           "falling back to a synchronous save")
            return self.inner.save(engine, save_dir, tag, client_state)

        # Snapshot NOW (host copies) so training can mutate params while
        # the write is in flight — the decoupled contract.
        class _Snapshot:
            pass

        snap = _Snapshot()
        snap.params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)),
                                   engine.params)
        snap._super_opt = None
        if getattr(engine, "_super_opt", None) is not None:
            # deep-copy: the SuperOffload host thread mutates these buffers
            # in place while the write is in flight
            frozen_sd = jax.tree.map(np.copy, engine._super_opt.state_dict())

            class _FrozenSuper:
                def state_dict(self):
                    return frozen_sd

            snap._super_opt = _FrozenSuper()
            opt_tree = None
        else:
            opt_tree = _opt_tree_for_save(engine)
        snap.opt_state = None if opt_tree is None else jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), opt_tree)
        snap.loss_scale_state = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), engine.loss_scale_state)
        snap.global_steps = engine.global_steps
        snap.micro_steps = engine.micro_steps

        class _FrozenSched:  # state_dict captured now, not at write time
            def __init__(self, sd):
                self._sd = sd

            def state_dict(self):
                return self._sd

        snap.lr_scheduler = _FrozenSched(engine.lr_scheduler.state_dict())
        snap.topology = engine.topology
        snap._opt_store = None

        def work():
            try:
                self.inner.save(snap, save_dir, tag, client_state)
            except BaseException as e:  # surfaced on next wait()
                self._error = e

        self._worker = threading.Thread(target=work, daemon=True)
        self._worker.start()
        return os.path.join(save_dir, str(tag))

    def load(self, engine, load_dir: str, tag: Optional[str] = None,
             **kw):
        self.wait()
        return self.inner.load(engine, load_dir, tag, **kw)

    def wait(self) -> None:
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"decoupled checkpoint save failed: {err}")
