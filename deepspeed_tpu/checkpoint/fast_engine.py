"""Fast + decoupled checkpoint engines.

Analogs of ``deepspeed/runtime/checkpoint_engine/``:
``FastCheckpointEngine`` (FastFileWriter-backed, double-buffered pinned
I/O) and ``DecoupledCheckpointEngine`` (async save on a worker with a
commit protocol — ref ``CheckpointCommitInfo`` :15: the ``latest`` pointer
only advances after every file of the tag has landed, so a crash mid-save
never leaves a half checkpoint as the resume target).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.checkpoint.engine import LATEST_FILE
from deepspeed_tpu.io.fast_file_writer import (FastFileWriter,
                                               read_tensor_file,
                                               write_tensor_file)
from deepspeed_tpu.utils.logging import log_dist, logger


def _flatten(tree, prefix: str) -> Dict[str, np.ndarray]:
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = prefix + "/" + "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        out[name] = np.asarray(jax.device_get(leaf))
    return out


def _unflatten_into(template, flat: Dict[str, np.ndarray], prefix: str):
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        name = prefix + "/" + "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        leaves.append(flat[name].astype(leaf.dtype).reshape(np.shape(leaf)))
    return jax.tree_util.tree_unflatten(treedef, leaves)


class FastCheckpointEngine:
    """Indexed-binary checkpoint via FastFileWriter (ref
    FastCheckpointEngine): one ``model_states.bin`` per tag holding params
    + optimizer + a JSON meta sidecar."""

    name = "fast"

    def __init__(self, buffer_bytes: int = 32 << 20):
        self.buffer_bytes = buffer_bytes

    def _paths(self, save_dir: str, tag: str):
        d = os.path.join(save_dir, str(tag))
        return d, os.path.join(d, "model_states.bin"), os.path.join(d, "meta.json")

    def save(self, engine, save_dir: str, tag: str,
             client_state: Optional[Dict[str, Any]] = None) -> str:
        d, bin_path, meta_path = self._paths(save_dir, tag)
        os.makedirs(d, exist_ok=True)
        opt_tree = (engine.opt_state if getattr(engine, "_opt_store", None) is None
                    else engine._opt_store.swap_in())
        tensors = _flatten(engine.params, "module")
        if opt_tree is not None:
            tensors.update(_flatten(opt_tree, "optimizer"))
        tensors.update(_flatten(engine.loss_scale_state, "loss_scale"))
        stats = write_tensor_file(bin_path, tensors, FastFileWriter,
                                  buffer_bytes=self.buffer_bytes)
        meta = {"global_steps": engine.global_steps,
                "micro_steps": engine.micro_steps,
                "lr_scheduler": engine.lr_scheduler.state_dict(),
                "client_state": client_state or {},
                "mesh_sizes": dict(engine.topology.sizes),
                "io_stats": stats}
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        if jax.process_index() == 0:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
        log_dist(f"fast checkpoint saved: {bin_path} "
                 f"({stats['bytes_written']} bytes)")
        return bin_path

    def load(self, engine, load_dir: str, tag: Optional[str] = None,
             load_optimizer_states: bool = True,
             load_lr_scheduler_states: bool = True):
        if tag is None:
            latest = os.path.join(load_dir, LATEST_FILE)
            if not os.path.exists(latest):
                logger.warning(f"no {LATEST_FILE} in {load_dir}")
                return None, {}
            tag = open(latest).read().strip()
        d, bin_path, meta_path = self._paths(load_dir, tag)
        flat = read_tensor_file(bin_path)
        engine.params = jax.device_put(
            _unflatten_into(engine.params, flat, "module"),
            engine.param_shardings)
        if load_optimizer_states and engine.opt_state is not None and any(
                k.startswith("optimizer/") for k in flat):
            engine.opt_state = jax.device_put(
                _unflatten_into(engine.opt_state, flat, "optimizer"),
                engine.opt_shardings)
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = int(meta["global_steps"])
        engine.micro_steps = int(meta["micro_steps"])
        if load_lr_scheduler_states and meta.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        log_dist(f"fast checkpoint loaded: {bin_path}")
        return bin_path, meta.get("client_state", {})

    def wait(self) -> None:  # synchronous engine
        pass


class DecoupledCheckpointEngine:
    """Async save with commit protocol (ref DecoupledCheckpointEngine):
    ``save`` snapshots host copies and returns; a worker writes them and
    commits ``latest`` last.  ``wait()`` blocks until the commit."""

    name = "decoupled"

    def __init__(self, inner: Optional[FastCheckpointEngine] = None):
        self.inner = inner or FastCheckpointEngine()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, engine, save_dir: str, tag: str,
             client_state: Optional[Dict[str, Any]] = None) -> str:
        self.wait()

        # Snapshot NOW (host copies) so training can mutate params while
        # the write is in flight — the decoupled contract.
        class _Snapshot:
            pass

        snap = _Snapshot()
        snap.params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)),
                                   engine.params)
        opt_tree = (engine.opt_state if getattr(engine, "_opt_store", None) is None
                    else engine._opt_store.swap_in())
        snap.opt_state = None if opt_tree is None else jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), opt_tree)
        snap.loss_scale_state = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), engine.loss_scale_state)
        snap.global_steps = engine.global_steps
        snap.micro_steps = engine.micro_steps

        class _FrozenSched:  # state_dict captured now, not at write time
            def __init__(self, sd):
                self._sd = sd

            def state_dict(self):
                return self._sd

        snap.lr_scheduler = _FrozenSched(engine.lr_scheduler.state_dict())
        snap.topology = engine.topology
        snap._opt_store = None

        def work():
            try:
                self.inner.save(snap, save_dir, tag, client_state)
            except BaseException as e:  # surfaced on next wait()
                self._error = e

        self._worker = threading.Thread(target=work, daemon=True)
        self._worker.start()
        return os.path.join(save_dir, str(tag))

    def load(self, engine, load_dir: str, tag: Optional[str] = None,
             **kw):
        self.wait()
        return self.inner.load(engine, load_dir, tag, **kw)

    def wait(self) -> None:
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"decoupled checkpoint save failed: {err}")
