"""Static graph auditing: jaxpr/HLO-level sharding, donation, and
collective lint (docs/STATIC_ANALYSIS.md).

``audit()`` lowers a jitted step function and — without executing it —
emits a typed frozen-schema :class:`GraphAuditReport`: a collective
census diffed against declared intent, a donation audit against the
aliases XLA actually assigned, and hot-path hygiene findings.  Shipped
three ways: the ``tools/graft_lint.py`` CLI, a tier-1 pytest hook over
every bench-row step config (``analysis/targets.py``), and the
``analysis.audit()``/``collective_census_engine()`` API the overlap
scheduler consumes for pinned-schedule evidence.

Importing this package stays jax-free (``report``/``vocab``/``seam``
are plain data + stdlib); the auditor itself loads lazily on first use,
mirroring how ``serving/`` avoids a jax taint.
"""

from deepspeed_tpu.analysis.report import (AUDIT_REPORT_KEYS,  # noqa: F401
                                           AUDIT_SCHEMA_VERSION,
                                           BUDGET_KEYS, BUFFER_KEYS,
                                           CALIBRATION_KEYS, CENSUS_KEYS,
                                           DONATION_KEYS, FINDING_KEYS,
                                           FINDING_KINDS, MEMORY_CLASSES,
                                           MEMORY_REPORT_KEYS,
                                           MEMORY_TOTALS_KEYS, SEVERITIES,
                                           CollectiveStat, Finding,
                                           GraphAuditReport,
                                           MemoryAuditReport, bucket_bytes,
                                           load_baseline,
                                           load_memory_baseline)

_LAZY = {
    "AuditIntent": "auditor", "audit": "auditor",
    "audit_artifacts": "auditor", "lower_step": "auditor",
    "LoweredStep": "auditor",
    "audit_engine": "auditor", "audit_v2_engine": "auditor",
    "collective_census_engine": "auditor",
    "census_and_memory_engine": "auditor", "intent_for_engine": "auditor",
    "MemoryIntent": "memory", "audit_memory": "memory",
    "memory_intent_for_engine": "memory", "memory_intent_for_v2": "memory",
    "lint_repo": "seam", "lint_source": "seam",
    "VocabSpec": "vocab", "check_all": "vocab",
    "BENCH_AUDIT_TARGETS": "targets", "run_audit_target": "targets",
    "run_target_audits": "targets",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)


__all__ = sorted([
    "AUDIT_REPORT_KEYS", "AUDIT_SCHEMA_VERSION", "BUDGET_KEYS",
    "BUFFER_KEYS", "CALIBRATION_KEYS", "CENSUS_KEYS", "DONATION_KEYS",
    "FINDING_KEYS", "FINDING_KINDS", "MEMORY_CLASSES",
    "MEMORY_REPORT_KEYS", "MEMORY_TOTALS_KEYS", "SEVERITIES",
    "CollectiveStat", "Finding", "GraphAuditReport", "MemoryAuditReport",
    "bucket_bytes", "load_baseline", "load_memory_baseline",
] + list(_LAZY))
