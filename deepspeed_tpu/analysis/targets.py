"""Bench-row audit targets: every step configuration ``bench.py`` times
gets a statically auditable twin here, scaled to the virtual 8-device
CPU mesh so the tier-1 suite and ``tools/graft_lint.py --rows/--memory``
can lower + audit each one WITHOUT running a step.

The mapping (see bench.py's row table):

=====================  ==============================================
target                 bench row(s) whose step it audits
=====================  ==============================================
``train_zero1``        gpt2_350m (primary ZeRO-1 train step)
``train_zero3``        llama8b_class_zero3 / peak_params base rungs
``train_commquant``    gpt2_350m_commquant (int8 quantized DP reduce)
``train_autosched``    gpt2_350m_autosched (pinned zero3_prefetch)
``train_fused_rs``     gpt2_350m_autosched fused A/B (decomposed +
                       fused reduce-scatter epilogue)
``train_fused_gather`` gpt2_350m_autosched fused A/B (stage-3 fused
                       gather-matmul MLP)
``ring_attention``     longseq_ring (ring fwd+bwd on the 2×4 mesh)
``ring_attention_quant``  longseq_ring quantized-wire A/B (int8
                       ring_rotation)
``v2_decode``          v2_decode / serve_load* (16-token decode step)
``v2_prefill``         v2_decode / serve_load* (full-budget prefill)
``v2_verify``          serve_disagg (speculative target verify-k step)
``v2_spec_draft``      serve_disagg (draft-model propose/decode step)
=====================  ==============================================

Each target PREPARES once — build its engine, read the step fn +
example args + both audit intents off it — and every audit family
(collective census, donation, memory plan) then runs off ONE shared
:class:`~deepspeed_tpu.analysis.auditor.LoweredStep`: with the registry
at 12+ rows and each lowering ~2s, re-lowering per audit would double
the lint's wall time for nothing.  Geometry is tiny (gpt2-tiny class)
because the lint checks graph *structure*; byte volumes scale with the
real config but kind/dtype/alias/shape findings do not.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

from deepspeed_tpu.analysis.report import (GraphAuditReport,
                                           MemoryAuditReport)


def _reset_topology():
    from deepspeed_tpu.parallel import topology

    topology._GLOBAL_TOPOLOGY = None


@dataclass
class PreparedTarget:
    """One target, ready to lower: the jitted step + example args, both
    audit intents (read off the live engine), and the teardown that
    releases the engine/topology.  ``cleanup()`` runs AFTER lowering —
    the AOT artifacts outlive the engine."""
    label: str
    fn: Any
    args: Tuple[Any, ...]
    intent: Any                 # AuditIntent
    memory_intent: Any          # MemoryIntent
    cleanup: Callable[[], None]


def _train_config(n: int, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
        "mesh": {"data": n},
    }
    cfg.update(over)
    return cfg


def _prep_engine(engine, label: str,
                 extra_cleanup: Optional[Callable[[], None]] = None
                 ) -> PreparedTarget:
    from deepspeed_tpu.analysis.auditor import intent_for_engine
    from deepspeed_tpu.analysis.memory import memory_intent_for_engine

    fn, args = engine.audit_step_args()

    def cleanup():
        try:
            engine.destroy()
        finally:
            _reset_topology()
            if extra_cleanup is not None:
                extra_cleanup()

    return PreparedTarget(label=label, fn=fn, args=args,
                          intent=intent_for_engine(engine),
                          memory_intent=memory_intent_for_engine(engine),
                          cleanup=cleanup)


def _prep_train(label: str, **over) -> PreparedTarget:
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny", max_seq_len=64)
    engine, _, _, _ = ds.initialize(
        model=model, config=_train_config(jax.device_count(), **over))
    return _prep_engine(engine, label)


def prep_train_zero1() -> PreparedTarget:
    return _prep_train("train_zero1", bf16={"enabled": True})


def prep_train_zero3() -> PreparedTarget:
    return _prep_train("train_zero3", bf16={"enabled": True},
                       zero_optimization={"stage": 3})


def prep_train_commquant() -> PreparedTarget:
    return _prep_train(
        "train_commquant",
        comm_quantization={"enabled": True, "grad_reduce": "int8"})


def prep_train_autosched() -> PreparedTarget:
    # the pinned shape the autosched row converges to on a ZeRO-3 probe
    return _prep_train(
        "train_autosched", bf16={"enabled": True},
        zero_optimization={"stage": 3},
        step_schedule={"mode": "pinned", "gather_prefetch_depth": 2,
                       "param_persistence_threshold": 100_000})


def prep_train_fused_rs() -> PreparedTarget:
    """Fused reduce-scatter twin (step_schedule.fused_reduce_scatter +
    decomposed update at stage 1): the explicit per-leaf psum_scatter in
    the grad-accumulator epilogue must audit clean — reduce-scatter is
    declared intent on the decomposed path."""
    return _prep_train(
        "train_fused_rs",
        step_schedule={"weight_update": "decomposed",
                       "fused_reduce_scatter": True})


def prep_train_fused_gather() -> PreparedTarget:
    """Fused gather-matmul twin (step_schedule.fused_gather_matmul at
    stage 3, persistence off so the tiny MLP weights actually shard):
    the explicit in-region all-gathers must audit clean — all-gather is
    declared stage-3 intent either way; this pins that the fused path
    introduces nothing unexplained."""
    return _prep_train(
        "train_fused_gather", bf16={"enabled": True},
        zero_optimization={"stage": 3, "param_persistence_threshold": 0},
        step_schedule={"fused_gather_matmul": True})


def prep_train_offload_cpu() -> PreparedTarget:
    """Chunked host-optimizer twin (peak_params cpu-chunked rung):
    working_set_bytes forces the ChunkedHostOptimizer, so the audited
    program is the fwd+bwd grads batch — params and moments never enter
    the device program, which is the memory claim the chunked tier
    makes.  The frozen budget pins that the device footprint stays
    params+activations-sized."""
    return _prep_train(
        "train_offload_cpu",
        zero_optimization={"stage": 2,
                           "offload_optimizer": {"device": "cpu",
                                                 "working_set_bytes": 1}})


def prep_train_resumed() -> PreparedTarget:
    """Self-healing resume twin (chaos_recovery row): state saved under
    a pure-data mesh is universally reloaded onto a data×tensor
    factorization through the PartitionOracle, and the RESUMED engine's
    train step is audited.  Zero unbaselined highs means the
    oracle-derived shardings census-match the declared intent — the
    resharding resume introduced no implicit reshard, no dropped
    donation, no unexplained collective — which is the static half of
    the chaos e2e's loss-continuity assertion."""
    import shutil
    import tempfile

    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.checkpoint.universal import (ds_to_universal,
                                                    load_universal)
    from deepspeed_tpu.models import get_model_config

    model = get_model_config("gpt2-tiny", max_seq_len=64)
    n = jax.device_count()
    ckdir = tempfile.mkdtemp(prefix="dstpu_audit_resume_")
    try:
        engine, _, _, _ = ds.initialize(
            model=model,
            config=_train_config(n, zero_optimization={"stage": 2}))
        try:
            engine.save_checkpoint(ckdir, tag="seed")
            udir = ds_to_universal(ckdir, tag="seed")
        finally:
            engine.destroy()
            _reset_topology()
        cfg = _train_config(n, zero_optimization={"stage": 2})
        cfg["mesh"] = ({"data": n // 2, "tensor": 2} if n >= 2
                       else {"data": 1})
        engine2, _, _, _ = ds.initialize(model=model, config=cfg)
        load_universal(engine2, udir)
    except BaseException:
        shutil.rmtree(ckdir, ignore_errors=True)
        raise
    return _prep_engine(
        engine2, "train_resumed",
        extra_cleanup=lambda: shutil.rmtree(ckdir, ignore_errors=True))


def _prep_ring(label: str, wire_dtype: str, intent) -> PreparedTarget:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.analysis.memory import MemoryIntent
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.sequence.ring import ring_attention

    topo = MeshTopology({"seq": 4, "data": 2})
    set_topology(topo)
    b, s, nh, d = 2, 64, 4, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, s, nh, d)), jnp.float32)

    def fwd_bwd(q, k, v):
        def loss(q, k, v):
            return ring_attention(
                q, k, v, topo, wire_dtype=wire_dtype).astype(
                    jnp.float32).sum()
        l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return l, grads

    def cleanup():
        set_topology(None)
        _reset_topology()

    return PreparedTarget(
        label=label, fn=jax.jit(fwd_bwd), args=(q, q, q), intent=intent,
        memory_intent=MemoryIntent(
            arg_categories=("activations",) * 3,
            seq_len=s // topo.sp_size),
        cleanup=cleanup)


def prep_ring_attention() -> PreparedTarget:
    """longseq_ring twin: jitted ring fwd+bwd on the 2(data)×4(seq)
    mesh — the census must carry the ring's collective-permute hops and
    nothing unexplained."""
    from deepspeed_tpu.analysis.auditor import AuditIntent

    intent = AuditIntent(
        expected=frozenset({"collective-permute", "all-reduce",
                            "all-gather", "reduce-scatter"}),
        required={"collective-permute": ()})
    return _prep_ring("ring_attention", "fp32", intent)


def prep_ring_attention_quant() -> PreparedTarget:
    """Quantized-wire longseq_ring twin (comm_quantization.ring_rotation
    = int8): the rotation's collective-permutes must move s8 payloads —
    the fp32-wire u32 word-packing is BANNED at volume, and an s8
    permute is required (the fused-wire declaration the auditor's
    intent_for_engine derives for quantized ring engines)."""
    from deepspeed_tpu.analysis.auditor import AuditIntent

    intent = AuditIntent(
        expected=frozenset({"collective-permute", "all-reduce",
                            "all-gather", "reduce-scatter"}),
        required={"collective-permute": ("s8",)},
        banned={"collective-permute": ("u32",)})
    return _prep_ring("ring_attention_quant", "int8", intent)


def _prep_v2(phase: str, model_name: str = "gpt2-tiny",
             label: Optional[str] = None, **model_over) -> PreparedTarget:
    from deepspeed_tpu.analysis.auditor import intent_for_v2
    from deepspeed_tpu.analysis.memory import memory_intent_for_v2
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import get_model_config

    model = get_model_config(model_name, max_seq_len=128, **model_over)
    eng = InferenceEngineV2(model, {
        "state_manager": {"max_tracked_sequences": 4,
                          "max_ragged_batch_size": 64},
        "memory_config": {"num_blocks": 16, "block_size": 16},
        "max_context": 128})
    # the point of the target is the CONFIGURED tiny geometry — a config
    # nesting drift that silently fell back to defaults would audit a
    # 512-block step instead of the bench row's twin
    assert eng.cfg.num_blocks == 16 and eng.state_manager.max_seqs == 4, \
        (eng.cfg.num_blocks, eng.state_manager.max_seqs)
    fn, args = eng.audit_step_args(phase)
    return PreparedTarget(
        label=label or f"v2_{phase}", fn=fn, args=args,
        intent=intent_for_v2(eng),
        memory_intent=memory_intent_for_v2(eng),
        cleanup=_reset_topology)


def prep_v2_spec_draft() -> PreparedTarget:
    """serve_disagg draft-propose twin: the draft model's decode-phase
    step (speculative proposals are plain greedy decode dispatches of a
    SMALLER model sharing the target's vocabulary — serving/disagg.py
    SpeculativeDecoder)."""
    return _prep_v2("decode", model_name="llama-tiny", num_layers=1,
                    label="v2_spec_draft")


TARGET_PREPARERS: Dict[str, Callable[[], PreparedTarget]] = {
    "train_zero1": prep_train_zero1,
    "train_zero3": prep_train_zero3,
    "train_commquant": prep_train_commquant,
    "train_autosched": prep_train_autosched,
    "train_fused_rs": prep_train_fused_rs,
    "train_fused_gather": prep_train_fused_gather,
    "train_offload_cpu": prep_train_offload_cpu,
    "train_resumed": prep_train_resumed,
    "ring_attention": prep_ring_attention,
    "ring_attention_quant": prep_ring_attention_quant,
    "v2_decode": partial(_prep_v2, "decode"),
    "v2_prefill": partial(_prep_v2, "prefill"),
    "v2_verify": partial(_prep_v2, "verify"),
    "v2_spec_draft": prep_v2_spec_draft,
}


def run_target_audits(name: str, memory: bool = False,
                      budget: Optional[int] = None, graph: bool = True
                      ) -> Tuple[Optional[GraphAuditReport],
                                 Optional[MemoryAuditReport]]:
    """Prepare + lower ``name`` ONCE and run the requested audit
    families off the shared artifacts.  ``budget`` is the frozen
    per-target peak budget (``tools/memory_baseline.json``) the memory
    audit gates against; None audits with a no-budget warning.  A
    memory-only caller (``graft_lint --memory``) passes ``graph=False``
    and pays only lowering + the memory audit."""
    from deepspeed_tpu.analysis.auditor import audit_artifacts, lower_step

    try:
        prep_fn = TARGET_PREPARERS[name]
    except KeyError:
        raise KeyError(f"unknown audit target {name!r} "
                       f"(known: {sorted(TARGET_PREPARERS)})") from None
    prep = prep_fn()
    try:
        art = lower_step(prep.fn, *prep.args, label=prep.label)
    finally:
        prep.cleanup()
    graph_rep = audit_artifacts(art, intent=prep.intent) if graph else None
    mem = None
    if memory:
        from deepspeed_tpu.analysis.memory import audit_memory

        mem = audit_memory(art, intent=prep.memory_intent, budget=budget)
    return graph_rep, mem


def run_audit_target(name: str) -> GraphAuditReport:
    """Back-compat single-family entry: the graph audit only."""
    return run_target_audits(name)[0]


BENCH_AUDIT_TARGETS: Dict[str, Callable[[], GraphAuditReport]] = {
    name: partial(run_audit_target, name) for name in TARGET_PREPARERS}
