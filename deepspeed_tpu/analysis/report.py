"""Typed frozen-schema report for the static graph auditor.

The auditor (``analysis/auditor.py``) lowers a jitted step function and
emits ONE :class:`GraphAuditReport` per audited graph: a collective
census, a donation audit, and a list of typed :class:`Finding` records.
Like the telemetry StepRecord, the report schema is FROZEN — the key
sets below are linted against ``docs/STATIC_ANALYSIS.md`` by
``tools/telemetry_check.py`` (via the shared ``analysis/vocab`` checker),
so a drive-by key rename fails the tier-1 suite, not a downstream
consumer.  This module imports no jax: reports are plain data and safe
to load anywhere (the serving layer included).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

AUDIT_SCHEMA_VERSION = 1

# Frozen finding vocabulary — one entry per defect class the auditor can
# name.  Update EXPECTED_FINDING_KINDS in tools/telemetry_check.py and
# the docs/STATIC_ANALYSIS.md catalogue in the same commit as any change.
FINDING_KINDS = (
    "collective_mismatch",   # a declared collective is absent from the graph
    "donation_miss",         # donated buffer XLA did not alias to an output
    "dtype_promotion",       # bf16/fp16 tensor promoted to fp32 in the step
    "host_callback",         # host callback / infeed inside the hot path
    "implicit_resharding",   # GSPMD-inserted collective nobody declared
    "recompile_hazard",      # weak-type / python-scalar step argument
    "seam_violation",        # version-gated jax symbol outside jax_compat
    "wire_dtype_mismatch",   # fp32 wire on a path declared quantized
)

SEVERITIES = ("info", "warning", "high")

# Frozen top-level report keys (sorted, like the StepRecord schema).
AUDIT_REPORT_KEYS = [
    "backend", "census", "donation", "findings", "label",
    "num_partitions", "schema",
]

# Frozen per-census-row keys: one row per (collective kind, wire dtype).
CENSUS_KEYS = ["count", "dtype", "group_size", "kind", "payload_bytes",
               "wire_bytes"]

# Frozen per-finding keys.
FINDING_KEYS = ["detail", "fingerprint", "kind", "message", "severity",
                "where"]

# Frozen donation-block keys.
DONATION_KEYS = ["aliased", "declared", "missed", "missed_bytes"]


@dataclass
class Finding:
    """One named defect.

    ``where`` locates the finding (an op name, ``file:line``, or the
    audit label); ``detail`` carries kind-specific structured data and
    MUST include a ``key`` entry — a stable, count-free identifier (e.g.
    ``"all-to-all:f32"`` or ``"(64, 32):float32"``) so the fingerprint
    survives byte-count drift between runs and a ``--baseline`` file
    keeps suppressing the same defect.
    """
    kind: str
    severity: str
    message: str
    where: str = ""
    detail: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in FINDING_KINDS:
            raise ValueError(f"unknown finding kind {self.kind!r} "
                             f"(known: {list(FINDING_KINDS)})")
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r} "
                             f"(known: {list(SEVERITIES)})")

    def fingerprint(self) -> str:
        """Stable 12-hex id for baseline suppression: hashes the finding
        class and its stable ``detail['key']`` — never the message, whose
        byte counts and op ids drift run to run."""
        key = str(self.detail.get("key", ""))
        raw = f"{self.kind}|{self.where}|{key}"
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:12]

    def to_dict(self) -> Dict[str, Any]:
        return {"detail": dict(self.detail),
                "fingerprint": self.fingerprint(), "kind": self.kind,
                "message": self.message, "severity": self.severity,
                "where": self.where}


@dataclass
class CollectiveStat:
    """Census row: every lowered collective of one (kind, dtype) pair.

    ``payload_bytes`` is the summed result-shape footprint;
    ``wire_bytes`` applies the standard ring-algorithm cost model per
    kind (see ``analysis/hlo.py``) — the number to diff against the
    ``comm_quantization`` byte-reduction claims.
    """
    kind: str
    dtype: str
    count: int = 0
    payload_bytes: int = 0
    wire_bytes: int = 0
    group_size: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return {"count": self.count, "dtype": self.dtype,
                "group_size": self.group_size, "kind": self.kind,
                "payload_bytes": self.payload_bytes,
                "wire_bytes": self.wire_bytes}


@dataclass
class GraphAuditReport:
    """One audited graph: census + donation audit + findings."""
    label: str
    backend: str = "cpu"
    num_partitions: int = 1
    census: List[CollectiveStat] = field(default_factory=list)
    donation: Dict[str, Any] = field(default_factory=lambda: {
        "aliased": 0, "declared": 0, "missed": [], "missed_bytes": 0})
    findings: List[Finding] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "backend": self.backend,
            "census": [c.to_dict() for c in sorted(
                self.census, key=lambda c: (c.kind, c.dtype))],
            "donation": dict(self.donation),
            "findings": [f.to_dict() for f in self.findings],
            "label": self.label,
            "num_partitions": self.num_partitions,
            "schema": AUDIT_SCHEMA_VERSION,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    # ------------------------------------------------------------------
    def high_findings(self, baseline: Optional[Iterable[str]] = None
                      ) -> List[Finding]:
        """High-severity findings not suppressed by ``baseline``
        (an iterable of fingerprints)."""
        sup = frozenset(baseline or ())
        return [f for f in self.findings
                if f.severity == "high" and f.fingerprint() not in sup]

    def census_summary(self) -> Dict[str, Dict[str, Any]]:
        """Compact per-kind rollup — the shape that rides the overlap
        scheduler's pinned ``step_schedule`` evidence (``static_census``):
        ``{kind: {count, wire_bytes, dtypes}}``."""
        out: Dict[str, Dict[str, Any]] = {}
        for c in self.census:
            row = out.setdefault(c.kind, {"count": 0, "wire_bytes": 0,
                                          "dtypes": []})
            row["count"] += c.count
            row["wire_bytes"] += c.wire_bytes
            if c.dtype not in row["dtypes"]:
                row["dtypes"] = sorted(row["dtypes"] + [c.dtype])
        return out


def load_baseline(path: str) -> frozenset:
    """Read a ``--baseline`` suppression file: ``{"suppress": [fp, ...]}``
    (each entry a :meth:`Finding.fingerprint` value).  A missing file is
    an empty baseline — absence must not un-gate the lint."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return frozenset()
    return frozenset(str(s) for s in data.get("suppress", []))
