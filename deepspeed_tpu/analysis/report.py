"""Typed frozen-schema report for the static graph auditor.

The auditor (``analysis/auditor.py``) lowers a jitted step function and
emits ONE :class:`GraphAuditReport` per audited graph: a collective
census, a donation audit, and a list of typed :class:`Finding` records.
Like the telemetry StepRecord, the report schema is FROZEN — the key
sets below are linted against ``docs/STATIC_ANALYSIS.md`` by
``tools/telemetry_check.py`` (via the shared ``analysis/vocab`` checker),
so a drive-by key rename fails the tier-1 suite, not a downstream
consumer.  This module imports no jax: reports are plain data and safe
to load anywhere (the serving layer included).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

AUDIT_SCHEMA_VERSION = 1

# Frozen finding vocabulary — one entry per defect class the auditor can
# name.  Update EXPECTED_FINDING_KINDS in tools/telemetry_check.py and
# the docs/STATIC_ANALYSIS.md catalogue in the same commit as any change.
FINDING_KINDS = (
    "collective_mismatch",   # a declared collective is absent from the graph
    "donation_miss",         # donated buffer XLA did not alias to an output
    "dtype_promotion",       # bf16/fp16 tensor promoted to fp32 in the step
    "host_callback",         # host callback / infeed inside the hot path
    "implicit_resharding",   # GSPMD-inserted collective nobody declared
    "model_drift",           # analytic memory model diverged from XLA's plan
    "peak_regression",       # static peak grew past the frozen target budget
    "recompile_hazard",      # weak-type / python-scalar step argument
    "remat_miss",            # score-shaped transient under a flash config
    "seam_violation",        # version-gated jax symbol outside jax_compat
    "unsharded_transient",   # replicated buffer where a sharded layout exists
    "wire_dtype_mismatch",   # fp32 wire on a path declared quantized
)

SEVERITIES = ("info", "warning", "high")

# Frozen top-level report keys (sorted, like the StepRecord schema).
AUDIT_REPORT_KEYS = [
    "backend", "census", "donation", "findings", "label",
    "num_partitions", "schema",
]

# ----------------------------------------------------------------------
# memory-plan audit schema (analysis/memory.py) — frozen like the rest
# ----------------------------------------------------------------------
# Frozen top-level MemoryAuditReport keys.
MEMORY_REPORT_KEYS = [
    "backend", "budget", "buffers", "calibration", "class_bytes",
    "findings", "label", "num_partitions", "schema", "totals",
]

# Frozen per-device totals from ``compiled.memory_analysis()`` plus the
# derived static peak (argument + output + temp + generated_code − alias).
MEMORY_TOTALS_KEYS = ["alias_bytes", "argument_bytes",
                      "generated_code_bytes", "output_bytes", "peak_bytes",
                      "temp_bytes"]

def memory_totals_from_analysis(ma) -> Dict[str, int]:
    """:data:`MEMORY_TOTALS_KEYS` dict from a
    ``compiled.memory_analysis()`` result (None-safe) — the ONE place
    the static-peak derivation lives, shared by ``analysis/memory.py``
    and the engine's ``profile_compiled`` static-memory handshake so the
    two can never disagree about what "peak" means."""
    totals = {k: 0 for k in MEMORY_TOTALS_KEYS}
    if ma is not None:
        totals["temp_bytes"] = int(getattr(ma, "temp_size_in_bytes", 0))
        totals["argument_bytes"] = int(
            getattr(ma, "argument_size_in_bytes", 0))
        totals["output_bytes"] = int(getattr(ma, "output_size_in_bytes", 0))
        totals["alias_bytes"] = int(getattr(ma, "alias_size_in_bytes", 0))
        totals["generated_code_bytes"] = int(
            getattr(ma, "generated_code_size_in_bytes", 0))
    # static peak: everything resident across the step, aliased
    # (donated) outputs counted once
    totals["peak_bytes"] = max(0, totals["argument_bytes"]
                               + totals["output_bytes"]
                               + totals["temp_bytes"]
                               + totals["generated_code_bytes"]
                               - totals["alias_bytes"])
    return totals


# Frozen per-buffer census row keys (top-K ENTRY-computation buffers).
BUFFER_KEYS = ["bytes", "category", "dtype", "op", "shape"]

# Frozen buffer classification vocabulary (the oracle-manifest classes).
MEMORY_CLASSES = ("activations", "grads", "opt_state", "other", "params",
                  "transients")

# Frozen budget-block keys: the frozen per-target budget this audit was
# gated against (``budget_bytes`` is None when no budget is recorded for
# this target+backend — a warning, never a silent pass).
BUDGET_KEYS = ["bucketed_peak_bytes", "budget_bytes", "peak_bytes"]

# Frozen calibration-record keys (the ``model_drift`` cross-check the
# autotuner attaches to its tuning-space pruning).
CALIBRATION_KEYS = ["analytic_bytes", "measured_bytes", "ratio"]

# Frozen per-census-row keys: one row per (collective kind, wire dtype).
CENSUS_KEYS = ["count", "dtype", "group_size", "kind", "payload_bytes",
               "wire_bytes"]

# Frozen per-finding keys.
FINDING_KEYS = ["detail", "fingerprint", "kind", "message", "severity",
                "where"]

# Frozen donation-block keys.
DONATION_KEYS = ["aliased", "declared", "missed", "missed_bytes"]


@dataclass
class Finding:
    """One named defect.

    ``where`` locates the finding (an op name, ``file:line``, or the
    audit label); ``detail`` carries kind-specific structured data and
    MUST include a ``key`` entry — a stable, count-free identifier (e.g.
    ``"all-to-all:f32"`` or ``"(64, 32):float32"``) so the fingerprint
    survives byte-count drift between runs and a ``--baseline`` file
    keeps suppressing the same defect.
    """
    kind: str
    severity: str
    message: str
    where: str = ""
    detail: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in FINDING_KINDS:
            raise ValueError(f"unknown finding kind {self.kind!r} "
                             f"(known: {list(FINDING_KINDS)})")
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r} "
                             f"(known: {list(SEVERITIES)})")

    def fingerprint(self) -> str:
        """Stable 12-hex id for baseline suppression: hashes the finding
        class and its stable ``detail['key']`` — never the message, whose
        byte counts and op ids drift run to run."""
        key = str(self.detail.get("key", ""))
        raw = f"{self.kind}|{self.where}|{key}"
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:12]

    def to_dict(self) -> Dict[str, Any]:
        return {"detail": dict(self.detail),
                "fingerprint": self.fingerprint(), "kind": self.kind,
                "message": self.message, "severity": self.severity,
                "where": self.where}


@dataclass
class CollectiveStat:
    """Census row: every lowered collective of one (kind, dtype) pair.

    ``payload_bytes`` is the summed result-shape footprint;
    ``wire_bytes`` applies the standard ring-algorithm cost model per
    kind (see ``analysis/hlo.py``) — the number to diff against the
    ``comm_quantization`` byte-reduction claims.
    """
    kind: str
    dtype: str
    count: int = 0
    payload_bytes: int = 0
    wire_bytes: int = 0
    group_size: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return {"count": self.count, "dtype": self.dtype,
                "group_size": self.group_size, "kind": self.kind,
                "payload_bytes": self.payload_bytes,
                "wire_bytes": self.wire_bytes}


@dataclass
class GraphAuditReport:
    """One audited graph: census + donation audit + findings."""
    label: str
    backend: str = "cpu"
    num_partitions: int = 1
    census: List[CollectiveStat] = field(default_factory=list)
    donation: Dict[str, Any] = field(default_factory=lambda: {
        "aliased": 0, "declared": 0, "missed": [], "missed_bytes": 0})
    findings: List[Finding] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "backend": self.backend,
            "census": [c.to_dict() for c in sorted(
                self.census, key=lambda c: (c.kind, c.dtype))],
            "donation": dict(self.donation),
            "findings": [f.to_dict() for f in self.findings],
            "label": self.label,
            "num_partitions": self.num_partitions,
            "schema": AUDIT_SCHEMA_VERSION,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    # ------------------------------------------------------------------
    def high_findings(self, baseline: Optional[Iterable[str]] = None
                      ) -> List[Finding]:
        """High-severity findings not suppressed by ``baseline``
        (an iterable of fingerprints)."""
        sup = frozenset(baseline or ())
        return [f for f in self.findings
                if f.severity == "high" and f.fingerprint() not in sup]

    def census_summary(self) -> Dict[str, Dict[str, Any]]:
        """Compact per-kind rollup — the shape that rides the overlap
        scheduler's pinned ``step_schedule`` evidence (``static_census``):
        ``{kind: {count, wire_bytes, dtypes}}``."""
        out: Dict[str, Dict[str, Any]] = {}
        for c in self.census:
            row = out.setdefault(c.kind, {"count": 0, "wire_bytes": 0,
                                          "dtypes": []})
            row["count"] += c.count
            row["wire_bytes"] += c.wire_bytes
            if c.dtype not in row["dtypes"]:
                row["dtypes"] = sorted(row["dtypes"] + [c.dtype])
        return out


def load_baseline(path: str) -> frozenset:
    """Read a ``--baseline`` suppression file: ``{"suppress": [fp, ...]}``
    (each entry a :meth:`Finding.fingerprint` value).  A missing file is
    an empty baseline — absence must not un-gate the lint."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return frozenset()
    return frozenset(str(s) for s in data.get("suppress", []))


# ----------------------------------------------------------------------
# memory-plan audit report (analysis/memory.py)
# ----------------------------------------------------------------------
@dataclass
class MemoryAuditReport:
    """One audited graph's static memory plan: per-device totals from
    ``compiled.memory_analysis()``, a top-K buffer census off the
    optimized HLO classified into :data:`MEMORY_CLASSES`, the frozen
    per-target budget check, the analytic-model calibration record, and
    typed findings (same :class:`Finding` machinery as the graph audit).
    Plain data, no jax."""
    label: str
    backend: str = "cpu"
    num_partitions: int = 1
    totals: Dict[str, int] = field(default_factory=lambda: {
        k: 0 for k in MEMORY_TOTALS_KEYS})
    buffers: List[Dict[str, Any]] = field(default_factory=list)
    class_bytes: Dict[str, int] = field(default_factory=dict)
    budget: Dict[str, Any] = field(default_factory=lambda: {
        "bucketed_peak_bytes": 0, "budget_bytes": None, "peak_bytes": 0})
    calibration: Dict[str, Any] = field(default_factory=lambda: {
        "analytic_bytes": None, "measured_bytes": 0, "ratio": None})
    findings: List[Finding] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "backend": self.backend,
            "budget": dict(self.budget),
            "buffers": [dict(b) for b in self.buffers],
            "calibration": dict(self.calibration),
            "class_bytes": dict(self.class_bytes),
            "findings": [f.to_dict() for f in self.findings],
            "label": self.label,
            "num_partitions": self.num_partitions,
            "schema": AUDIT_SCHEMA_VERSION,
            "totals": {k: int(self.totals.get(k, 0))
                       for k in MEMORY_TOTALS_KEYS},
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def high_findings(self, baseline: Optional[Iterable[str]] = None
                      ) -> List[Finding]:
        """High-severity findings not suppressed by ``baseline``."""
        sup = frozenset(baseline or ())
        return [f for f in self.findings
                if f.severity == "high" and f.fingerprint() not in sup]

    def summary(self) -> Dict[str, Any]:
        """Compact rollup for the overlap scheduler's pinned
        ``static_memory`` evidence: the per-device totals plus the
        per-class byte rollup — small enough to freeze into a pinned
        ``step_schedule`` next to ``static_census``."""
        return {**{k: int(self.totals.get(k, 0))
                   for k in MEMORY_TOTALS_KEYS},
                "class_bytes": dict(self.class_bytes)}


def bucket_bytes(n: int) -> int:
    """Round ``n`` UP to a coarse bucket (granularity = 2^(L−5) for an
    L-bit value, floored at 4 KiB — ≤ ~6.25% quantization).  Frozen
    per-target budgets are stored bucketed so layout/padding jitter
    between compiler versions and CPU-vs-TPU backends does not churn the
    committed baseline, while a real >10% peak regression still lands in
    a higher bucket."""
    n = int(n)
    if n <= 0:
        return 0
    gran = max(1 << 12, 1 << max(0, n.bit_length() - 5))
    return ((n + gran - 1) // gran) * gran


def load_memory_baseline(path: str) -> Dict[str, Any]:
    """Read ``tools/memory_baseline.json``: ``{"budgets": {target:
    {backend: bucketed_bytes}}, "calibration": {backend: ratio}}``.
    A missing file is an empty baseline — every target then carries a
    ``peak_regression`` *warning* (no frozen budget), never a silent
    pass."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {"budgets": {}, "calibration": {}}
    return {"budgets": dict(data.get("budgets", {})),
            "calibration": dict(data.get("calibration", {}))}
