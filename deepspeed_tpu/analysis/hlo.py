"""Post-SPMD HLO text parsing for the static graph auditor.

Works on the text of an *optimized* (post-partitioner) HLO module —
``jitted.lower(*args).compile().as_text()`` — because that is the first
artifact where GSPMD's implicitly inserted collectives exist: the
StableHLO from ``lower()`` still carries sharding as annotations, and a
resharding nobody asked for only becomes an ``all-to-all`` once the SPMD
partitioner has run.  Pure text processing, no jax import: the parser is
exercisable on checked-in HLO fixtures.
"""

from __future__ import annotations

import re
from math import prod
from typing import Any, Dict, List, Optional

from deepspeed_tpu.analysis.report import CollectiveStat

# Async collectives lower as a `-start`/`-done` pair; each pair is
# counted ONCE, via the `-done` op, whose result type is exactly the
# collective's result — the `-start` op's tuple type also contains the
# operand buffer(s), which would inflate payload/wire bytes.
COLLECTIVE_KINDS = ("all-gather", "all-reduce", "all-to-all",
                    "collective-permute", "reduce-scatter")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "c64": 8, "c128": 16,
}

# `f32[8,16]{1,0}` / `bf16[2]` / `s8[]` — one typed buffer in an HLO
# shape string.  Layout braces and dims are optional (scalars).
_SHAPE_RE = re.compile(r"\b([a-z]u?\d*[a-z0-9]*)\[([\d,]*)\]")

_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.+?)\s+"
    r"(" + "|".join(COLLECTIVE_KINDS) + r")(-start|-done)?\(",
)

# `replica_groups=[4,2]<=[8]` (iota form: 4 groups of 2) or the explicit
# `replica_groups={{0,1},{2,3}}` form.
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_GROUPS_EXPL_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")

_ALIAS_RE = re.compile(
    r"input_output_alias=\{(.*?)\}(?:,\s*\w+=|\s*$)",
    re.DOTALL | re.MULTILINE)
_ALIAS_PAIR_RE = re.compile(r"\{([\d,\s]*)\}:\s*\((\d+)")

_CUSTOM_CALL_RE = re.compile(r'custom_call_target="([^"]+)"')


def shape_bytes(type_str: str) -> int:
    """Total byte footprint of every typed buffer in an HLO type string
    (handles tuples: ``(f32[4,4], bf16[2,2])``)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _group_size(line: str, num_partitions: int) -> int:
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return max(1, int(m.group(2)))
    m = _GROUPS_EXPL_RE.search(line)
    if m:
        return max(1, len([x for x in m.group(1).split(",") if x]))
    return max(1, num_partitions)


def wire_bytes(kind: str, payload: int, n: int) -> int:
    """Ring-algorithm wire-byte model per device for one collective,
    priced off the op's RESULT bytes (``payload``).

    all-gather / all-to-all move (n−1)/n of the (already full-sized)
    result; reduce-scatter's result is the 1/n shard, so its ring cost
    is (n−1)× the result; all-reduce is reduce-scatter + all-gather
    over an equal-sized result (2×(n−1)/n); a collective-permute ships
    its whole buffer one hop.
    """
    if n <= 1:
        return 0
    if kind == "collective-permute":
        return payload
    if kind == "reduce-scatter":
        return int(payload * (n - 1))
    frac = (n - 1) / n
    if kind == "all-reduce":
        return int(2 * payload * frac)
    return int(payload * frac)


def parse_collectives(hlo_text: str,
                      num_partitions: int = 1) -> List[Dict[str, Any]]:
    """Every collective op in the module text → one record with kind,
    wire dtype(s), payload/wire bytes, group size, and the op_name
    metadata XLA carried from the jaxpr (attribution)."""
    lines = hlo_text.splitlines()
    # async pairs split their information: the `-start` line carries
    # replica_groups + metadata, the `-done` line carries the true
    # result type — collect the starts first, then price each `-done`
    # with its own type but its start's attributes
    start_lines: Dict[str, str] = {}
    for line in lines:
        m = _OP_RE.match(line)
        if m is not None and m.group(4) == "-start":
            start_lines[m.group(1)] = line
    ops: List[Dict[str, Any]] = []
    for line in lines:
        m = _OP_RE.match(line)
        if m is None or m.group(4) == "-start":
            continue
        name, out_type, kind = m.group(1), m.group(2), m.group(3)
        attr_line = line
        if m.group(4) == "-done":
            operand = re.search(r"%([\w.\-]+)\s*\)", line)
            if operand and operand.group(1) in start_lines:
                attr_line = start_lines[operand.group(1)]
        dtypes = sorted({d for d, _ in _SHAPE_RE.findall(out_type)
                         if d in _DTYPE_BYTES})
        payload = shape_bytes(out_type)
        n = _group_size(attr_line, num_partitions)
        meta = (re.search(r'op_name="([^"]+)"', line)
                or re.search(r'op_name="([^"]+)"', attr_line))
        ops.append({
            "name": name, "kind": kind,
            "dtype": "+".join(dtypes) or "unknown",
            "payload_bytes": payload,
            "wire_bytes": wire_bytes(kind, payload, n),
            "group_size": n,
            "op_name": meta.group(1) if meta else "",
        })
    return ops


def aggregate_census(ops: List[Dict[str, Any]]) -> List[CollectiveStat]:
    """Collapse per-op records into per-(kind, dtype) census rows."""
    rows: Dict[tuple, CollectiveStat] = {}
    for op in ops:
        key = (op["kind"], op["dtype"])
        row = rows.setdefault(key, CollectiveStat(
            kind=op["kind"], dtype=op["dtype"],
            group_size=op["group_size"]))
        row.count += 1
        row.payload_bytes += op["payload_bytes"]
        row.wire_bytes += op["wire_bytes"]
        row.group_size = max(row.group_size, op["group_size"])
    return sorted(rows.values(), key=lambda c: (c.kind, c.dtype))


def parse_input_output_alias(hlo_text: str) -> Dict[int, str]:
    """The module header's donation outcome: ``{param_index:
    output_index_path}`` for every input buffer XLA actually aliased."""
    m = _ALIAS_RE.search(hlo_text)
    if m is None:
        return {}
    out: Dict[int, str] = {}
    for out_idx, param in _ALIAS_PAIR_RE.findall(m.group(1)):
        out[int(param)] = out_idx.replace(" ", "")
    return out


def entry_lines(hlo_text: str) -> List[str]:
    """The ENTRY computation's lines (brace-balanced extraction) — the
    computation whose op results are the module's actually-allocated
    buffers (fusion bodies are virtual; their internals never allocate
    separately)."""
    entry: Optional[str] = None
    depth = 0
    lines: List[str] = []
    for line in hlo_text.splitlines():
        if entry is None:
            if line.lstrip().startswith("ENTRY"):
                entry = line
                depth = line.count("{") - line.count("}")
                lines.append(line)
            continue
        lines.append(line)
        depth += line.count("{") - line.count("}")
        if depth <= 0:
            break
    return lines


def entry_parameters(hlo_text: str) -> List[Dict[str, Any]]:
    """``[{index, type}]`` for the ENTRY computation's parameters (the
    flat argument buffers, in jax's flattened-args order)."""
    params = []
    for line in entry_lines(hlo_text):
        m = re.match(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.+?)\s+"
                     r"parameter\((\d+)\)", line)
        if m:
            params.append({"index": int(m.group(2)), "type": m.group(1)})
    return sorted(params, key=lambda p: p["index"])


# ops whose "result" re-labels an existing allocation rather than
# creating one — excluded from the buffer census
_NO_ALLOC_OPCODES = ("bitcast", "get-tuple-element", "parameter", "tuple")

_BUF_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"(\([^=]*?\)|[a-z][\w\[\],]*(?:\{[^}]*\})?)\s+([\w\-]+)\(")


def parse_buffers(hlo_text: str) -> List[Dict[str, Any]]:
    """Large-allocation census of the ENTRY computation: one record per
    op result — ``{name, opcode, bytes, dtype, shape, op_name,
    param_index}`` — the static stand-in for XLA's buffer-assignment
    dump (the text module does not carry the assignment itself, but
    every separately-allocated live buffer is some entry op's result).
    ``shape`` is the dims of the op's largest typed buffer; tuple results
    sum all member buffers into ``bytes``.  No-alloc ops (parameter /
    tuple / get-tuple-element / bitcast) are skipped — parameters are
    reported separately with their ``param_index`` so the caller can
    classify them via the argument manifests."""
    out: List[Dict[str, Any]] = []
    for line in entry_lines(hlo_text):
        m = _BUF_OP_RE.match(line)
        if m is None:
            continue
        name, out_type, opcode = m.group(1), m.group(2), m.group(3)
        shapes = [(d, tuple(int(x) for x in dims.split(",") if x))
                  for d, dims in _SHAPE_RE.findall(out_type)
                  if d in _DTYPE_BYTES]
        if not shapes:
            continue
        param_index = None
        if opcode == "parameter":
            pm = re.search(r"parameter\((\d+)\)", line)
            param_index = int(pm.group(1)) if pm else None
        elif opcode in _NO_ALLOC_OPCODES:
            continue
        total = shape_bytes(out_type)
        big_dtype, big_shape = max(
            shapes, key=lambda s: _DTYPE_BYTES[s[0]] * prod(s[1]))
        meta = re.search(r'op_name="([^"]+)"', line)
        out.append({"name": name, "opcode": opcode, "bytes": total,
                    "dtype": big_dtype, "shape": list(big_shape),
                    "op_name": meta.group(1) if meta else "",
                    "param_index": param_index})
    return out


def custom_call_targets(hlo_text: str) -> List[str]:
    return sorted(set(_CUSTOM_CALL_RE.findall(hlo_text)))


def has_infeed(hlo_text: str) -> bool:
    return bool(re.search(r"=\s*\([^)]*\)\s*infeed\(|\s+infeed\(",
                          hlo_text))
