"""Static memory-plan auditor: budget the step's HBM before it runs.

The compiled program's memory plan is fully inspectable before a single
step executes — the same placement-semantics reasoning the collective
census applies to wires applies to buffers.  Off one AOT
``lower().compile()`` (shared with the graph audit via
:class:`~deepspeed_tpu.analysis.auditor.LoweredStep`) this module emits a
typed frozen-schema :class:`~deepspeed_tpu.analysis.report.MemoryAuditReport`:

* **totals** — ``compiled.memory_analysis()`` per device (the SPMD
  module IS the per-device program): temp / argument / output / alias /
  generated-code bytes, plus the derived static ``peak_bytes``.
* **buffer census** — top-K ENTRY-computation buffers off the optimized
  HLO (``analysis/hlo.parse_buffers``) with shape, dtype, bytes and
  defining op, classified into params / grads / opt-state / activations
  / transients via the engines' argument manifests
  (``audit_arg_categories``, the same tree-path naming the
  PartitionOracle's flat manifests use).
* **findings** — PR-11-style typed findings with fingerprint baselines:
  ``unsharded_transient`` (a buffer carrying the GLOBAL shape of an
  argument the partitioner sharded — replication across a >1 mesh axis
  where a sharded layout exists; the pre-PR-11 zero-grads pattern),
  ``remat_miss`` (a score-shaped S²-per-head fp32 transient alive under
  a config that declared flash/ring attention), ``peak_regression``
  (static peak grew >10% past the frozen per-target budget committed in
  ``tools/memory_baseline.json``), and ``model_drift`` (the autotuner's
  analytic ``estimate_memory_per_device`` vs the XLA-measured totals
  diverging >25% — emitted as the calibration record the autotuner
  attaches to its tuning-space pruning).

Zero step executions: the audit runs on the virtual 8-device CPU mesh in
CI against every bench-row target (``analysis/targets.py``), gates
``tools/graft_lint.py --memory``, and its rollup rides the overlap
scheduler's pinned ``static_memory`` evidence.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from math import prod

from deepspeed_tpu.analysis.hlo import (entry_parameters, parse_buffers,
                                        shape_bytes)
from deepspeed_tpu.analysis.report import (MEMORY_CLASSES, Finding,
                                           MemoryAuditReport, bucket_bytes,
                                           memory_totals_from_analysis)

# peak grew past budget × (1 + PEAK_REGRESSION_TOLERANCE) ⇒ high finding
PEAK_REGRESSION_TOLERANCE = 0.10
# analytic-vs-measured divergence past this ratio ⇒ model_drift record
MODEL_DRIFT_TOLERANCE = 0.25


@dataclass
class MemoryIntent:
    """What the config declares about the step's memory layout.

    ``arg_categories`` classifies the example-args tuple ELEMENT-wise
    (one :data:`MEMORY_CLASSES` entry per top-level argument — the
    engines' ``audit_arg_categories()``); flat parameter buffers inherit
    their subtree's class.  ``seq_len`` is the PER-SHARD sequence length
    and ``flash`` whether the config declared a flash/ring attention
    kernel (score matrices then must never reach HBM).
    ``analytic_bytes`` is the autotuner's per-device estimate for the
    same geometry — the ``model_drift`` cross-check input.
    """
    arg_categories: Tuple[str, ...] = ()
    analytic_bytes: Optional[int] = None
    seq_len: int = 0
    flash: bool = False
    min_buffer_bytes: int = 1 << 16
    # classes whose GLOBAL shapes may legitimately appear replicated:
    # ZeRO materializes full params transiently by design (stage-3
    # per-use gathers, the stage-1/2 updated-param re-gather), so engine
    # intents exempt params/opt-state/grads shapes — replication of a
    # sharded BATCH or activation layout stays a finding, and planted
    # tests use the strict empty default
    replicated_ok: Tuple[str, ...] = ()

    def __post_init__(self):
        bad = [c for c in (tuple(self.arg_categories)
                           + tuple(self.replicated_ok))
               if c not in MEMORY_CLASSES]
        if bad:
            raise ValueError(f"unknown memory classes {bad!r} "
                             f"(known: {list(MEMORY_CLASSES)})")


# ----------------------------------------------------------------------
# classification
# ----------------------------------------------------------------------
def flat_arg_classes(args: Tuple[Any, ...],
                     categories: Tuple[str, ...]) -> Dict[int, str]:
    """Flat-parameter-index → class, from the per-top-level-argument
    category tuple (jax flattens the args tuple left to right, so the
    flat index ranges are the cumulative subtree leaf counts)."""
    import jax

    if len(categories) != len(args):
        raise ValueError(
            f"arg_categories has {len(categories)} entries for "
            f"{len(args)} top-level arguments")
    classes: Dict[int, str] = {}
    i = 0
    for cat, a in zip(categories, args):
        for _ in jax.tree_util.tree_leaves(a):
            classes[i] = cat
            i += 1
    return classes


def _classify_buffer(buf: Dict[str, Any],
                     arg_classes: Dict[int, str]) -> str:
    """Census-row class: parameters through the argument manifest;
    program-defined buffers split into loop-carried state (the layer
    scan's stacked activations) vs everything else (transients — fusion
    outputs, cotangents, resharding scratch)."""
    if buf["param_index"] is not None:
        return arg_classes.get(buf["param_index"], "other")
    if buf["opcode"] == "while" or "scan" in buf["op_name"]:
        return "activations"
    return "transients"


# ----------------------------------------------------------------------
# findings
# ----------------------------------------------------------------------
def _sharded_global_shapes(art, intent) -> Dict[Tuple[int, ...], int]:
    """Global dims → shard ratio, for every argument the partitioner
    SHARDED (per-device entry-parameter footprint strictly below the
    global aval's), minus shapes belonging to ``intent.replicated_ok``
    classes (layouts the config legitimately re-materializes in full).
    Only computable when the executable kept every argument (same
    reliability caveat as the donation audit)."""
    import jax
    import numpy as np

    flat_info, _ = jax.tree_util.tree_flatten(art.lowered.args_info)
    entry = entry_parameters(art.hlo)
    if len(entry) != len(flat_info):
        return {}
    classes = (flat_arg_classes(art.args, intent.arg_categories)
               if intent.arg_categories else {})
    exempt_shapes = set()
    out: Dict[Tuple[int, ...], int] = {}
    for i, (info, param) in enumerate(zip(flat_info, entry)):
        shape = tuple(int(d) for d in getattr(info, "shape", ()))
        try:
            global_bytes = int(prod(shape)) * np.dtype(
                getattr(info, "dtype", "f4")).itemsize
        except Exception:
            continue
        local_bytes = shape_bytes(param["type"])
        if local_bytes and global_bytes > local_bytes:
            if classes.get(i) in intent.replicated_ok:
                exempt_shapes.add(shape)
                continue
            ratio = max(2, round(global_bytes / local_bytes))
            out[shape] = max(out.get(shape, 0), ratio)
    # a shape both exempted and flagged (an activation arg sharing dims
    # with a param arg) resolves to exempt — never a phantom finding
    for shape in exempt_shapes:
        out.pop(shape, None)
    return out


def _unsharded_transient_findings(buffers, art, intent,
                                  label) -> List[Finding]:
    sharded = _sharded_global_shapes(art, intent)
    if not sharded:
        return []
    findings = []
    seen = set()
    for buf in buffers:
        if buf["param_index"] is not None:
            continue
        shape = tuple(buf["shape"])
        # one finding per (shape, dtype): several ops carrying the same
        # replicated buffer (the gather + its consumer fusion) share a
        # fingerprint anyway — report the first, largest-first callers
        # sort by bytes upstream
        if (shape, buf["dtype"]) in seen:
            continue
        if shape in sharded and buf["bytes"] >= intent.min_buffer_bytes:
            seen.add((shape, buf["dtype"]))
            ratio = sharded[shape]
            findings.append(Finding(
                kind="unsharded_transient", severity="high",
                message=f"{buf['opcode']} buffer {buf['dtype']}"
                        f"{list(shape)} ({buf['bytes']} bytes/device) "
                        f"carries the GLOBAL shape of an argument the "
                        f"partitioner sharded {ratio}× — a replicated "
                        "transient where a sharded layout exists (the "
                        "pre-PR-11 zero-grads pattern)",
                where=label,
                detail={"key": f"{list(shape)}:{buf['dtype']}",
                        "bytes": buf["bytes"], "shard_ratio": ratio,
                        "op": buf["opcode"]}))
    return findings


def _remat_miss_findings(buffers, intent, label) -> List[Finding]:
    if not intent.flash or intent.seq_len < 8:
        return []
    s = intent.seq_len
    findings = []
    for buf in buffers:
        if buf["param_index"] is not None:
            continue
        dims = list(buf["shape"])
        if (dims.count(s) >= 2 and buf["dtype"] in ("f32", "f64")
                and buf["bytes"] >= intent.min_buffer_bytes):
            findings.append(Finding(
                kind="remat_miss", severity="high",
                message=f"score-shaped {buf['dtype']}{dims} transient "
                        f"({buf['bytes']} bytes/device) is live in a step "
                        "whose config declares flash/ring attention — the "
                        "S²·heads matrix was supposed to stay in VMEM "
                        "tiles, not reach HBM",
                where=label,
                detail={"key": f"{dims}:{buf['dtype']}",
                        "bytes": buf["bytes"], "seq_len": s}))
    return findings


def _budget_findings(peak: int, budget: Optional[int],
                     label: str) -> List[Finding]:
    if budget is None:
        return [Finding(
            kind="peak_regression", severity="warning",
            message=f"no frozen peak budget for this target/backend — "
                    f"current static peak is {peak} bytes/device; run "
                    "graft_lint --memory --write-baseline to freeze it",
            where=label, detail={"key": f"nobudget:{label}",
                                 "peak_bytes": peak})]
    limit = int(budget * (1.0 + PEAK_REGRESSION_TOLERANCE))
    if peak > limit:
        return [Finding(
            kind="peak_regression", severity="high",
            message=f"statically-predicted peak {peak} bytes/device grew "
                    f">{PEAK_REGRESSION_TOLERANCE:.0%} past the frozen "
                    f"budget {budget} — an OOM waiting to happen; fix the "
                    "regression or deliberately re-freeze the budget",
            where=label, detail={"key": f"budget:{label}",
                                 "peak_bytes": peak,
                                 "budget_bytes": budget})]
    return []


def _drift_finding(measured: int, analytic: Optional[int],
                   label: str) -> Tuple[Dict[str, Any], List[Finding]]:
    record: Dict[str, Any] = {"analytic_bytes": analytic,
                              "measured_bytes": int(measured),
                              "ratio": None}
    if not analytic or analytic <= 0 or measured <= 0:
        return record, []
    ratio = measured / analytic
    record["ratio"] = round(ratio, 4)
    if abs(ratio - 1.0) <= MODEL_DRIFT_TOLERANCE:
        return record, []
    return record, [Finding(
        kind="model_drift", severity="info",
        message=f"analytic estimate_memory_per_device ({analytic} "
                f"bytes/device) vs XLA-measured static peak ({measured}) "
                f"diverge {abs(ratio - 1.0):.0%} — calibration record for "
                "the autotuner's tuning-space pruning "
                "(autotuning.load_memory_calibration)",
        where=label, detail={"key": f"drift:{label}",
                             "ratio": record["ratio"]})]


# ----------------------------------------------------------------------
# the auditor
# ----------------------------------------------------------------------
def audit_memory(art_or_fn, *args, intent: Optional[MemoryIntent] = None,
                 label: Optional[str] = None,
                 budget: Optional[int] = None,
                 top_k: int = 12) -> MemoryAuditReport:
    """Audit one lowered step's static memory plan — pass either a
    :class:`~deepspeed_tpu.analysis.auditor.LoweredStep` (shared with
    the graph audit) or a jitted fn + example args."""
    from deepspeed_tpu.analysis.auditor import LoweredStep, lower_step

    if isinstance(art_or_fn, LoweredStep):
        art = art_or_fn
    else:
        art = lower_step(art_or_fn, *args, label=label or "step")
    label = label or art.label
    intent = intent or MemoryIntent()

    try:
        ma = art.compiled.memory_analysis()
    except Exception:
        ma = None
    totals = memory_totals_from_analysis(ma)

    raw = parse_buffers(art.hlo)
    arg_classes = (flat_arg_classes(art.args, intent.arg_categories)
                   if intent.arg_categories else {})
    if arg_classes and len(entry_parameters(art.hlo)) != len(arg_classes):
        # the executable dropped unused arguments, renumbering the HLO
        # parameter(i) indices past the flat-arg manifest (same caveat
        # as the donation audit) — a silently WRONG class is worse than
        # none, so degrade every parameter buffer to uncategorized
        arg_classes = {}
    class_bytes = {c: 0 for c in MEMORY_CLASSES}
    rows: List[Dict[str, Any]] = []
    for buf in raw:
        cat = _classify_buffer(buf, arg_classes)
        class_bytes[cat] += buf["bytes"]
        rows.append({"bytes": buf["bytes"], "category": cat,
                     "dtype": buf["dtype"], "op": buf["opcode"],
                     "shape": list(buf["shape"])})
    rows.sort(key=lambda r: (-r["bytes"], r["op"], str(r["shape"])))

    findings: List[Finding] = []
    findings.extend(_unsharded_transient_findings(raw, art, intent, label))
    findings.extend(_remat_miss_findings(raw, intent, label))
    findings.extend(_budget_findings(totals["peak_bytes"], budget, label))
    calibration, drift = _drift_finding(totals["peak_bytes"],
                                        intent.analytic_bytes, label)
    findings.extend(drift)
    order = {"high": 0, "warning": 1, "info": 2}
    findings.sort(key=lambda f: (order[f.severity], f.kind,
                                 str(f.detail.get("key", ""))))
    return MemoryAuditReport(
        label=label, backend=art.backend,
        num_partitions=max(1, art.num_partitions), totals=totals,
        buffers=rows[:top_k], class_bytes=class_bytes,
        budget={"bucketed_peak_bytes": bucket_bytes(totals["peak_bytes"]),
                "budget_bytes": budget,
                "peak_bytes": totals["peak_bytes"]},
        calibration=calibration, findings=findings)


# ----------------------------------------------------------------------
# engine adapters
# ----------------------------------------------------------------------
def memory_intent_for_engine(engine) -> MemoryIntent:
    """Derive the memory intent from a built train engine: argument
    classes from the engine's own step-signature manifest, the per-shard
    sequence length + flash declaration from the model config, and the
    autotuner's analytic per-device estimate for the same geometry."""
    mc = engine.model_config
    topo = engine.topology
    sp = getattr(topo, "sp_size", 1)
    seq = int(getattr(mc, "max_seq_len", 0) or 0) // max(1, sp)
    flash = False
    if mc is not None:
        flash = (getattr(mc, "attn_impl", "") == "pallas_flash"
                 or (getattr(mc, "seq_impl", "") == "ring" and sp > 1
                     and getattr(mc, "attn_impl", "") != "xla"))
    return MemoryIntent(
        arg_categories=tuple(engine.audit_arg_categories()),
        analytic_bytes=_analytic_bytes_for_engine(engine),
        seq_len=seq, flash=bool(flash),
        # ZeRO re-materializes full params/grads transiently by design
        # (per-use stage-3 gathers, the updated-param re-gather at
        # stage 1/2) — those layouts are the config's own intent; a
        # replicated BATCH/activation layout is still a finding
        replicated_ok=("params", "opt_state", "grads"))


def _analytic_bytes_for_engine(engine) -> Optional[int]:
    try:
        import jax

        from deepspeed_tpu.autotuning.autotuner import (
            ModelInfo, estimate_memory_per_device)

        mc = engine.model_config
        if mc is None:
            return None
        n_params = sum(int(prod(x.shape)) for x in
                       jax.tree_util.tree_leaves(engine.params))
        topo = engine.topology
        cfg = engine.config
        dtype = ("bf16" if getattr(cfg, "bf16_enabled", False) else
                 "fp16" if getattr(cfg, "fp16_enabled", False) else "fp32")
        return estimate_memory_per_device(
            ModelInfo(num_params=n_params,
                      hidden_size=getattr(mc, "hidden_size", 0),
                      num_layers=getattr(mc, "num_layers", 0),
                      vocab_size=getattr(mc, "vocab_size", 0)),
            engine.zero_stage, max(1, getattr(topo, "dp_size", 1)),
            engine.micro_batch_size, getattr(mc, "max_seq_len", 0),
            dtype=dtype, tp_size=getattr(topo, "tp_size", 1),
            pp_size=getattr(topo, "pp_size", 1),
            sp_size=getattr(topo, "sp_size", 1))
    except Exception:
        return None


def memory_intent_for_v2(v2) -> MemoryIntent:
    """Memory intent for the serving engine's ragged step: no analytic
    train-memory model applies (no grads/opt state) — classification and
    transient findings only."""
    mc = getattr(v2, "model_config", None)
    return MemoryIntent(
        arg_categories=tuple(v2.audit_arg_categories()),
        seq_len=int(getattr(mc, "max_seq_len", 0) or 0) if mc else 0,
        flash=bool(mc and getattr(mc, "attn_impl", "") == "pallas_flash"))
