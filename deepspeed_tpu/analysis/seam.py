"""AST-level jax-version-seam lint.

ROADMAP standing constraint: ``utils/jax_compat.py`` is the ONLY place
allowed to spell a version-gated jax API — every other module imports
the portable helper.  This lint enforces that at the AST level (so a
symbol in a comment or docstring never trips it) over the production
tree: ``deepspeed_tpu/``, ``tools/``, ``bench.py``,
``__graft_entry__.py``.  Tests are exempt — they may pin version
behavior on purpose.

A violation is a :class:`~deepspeed_tpu.analysis.report.Finding` of kind
``seam_violation`` (severity high), so ``tools/graft_lint.py --seam``
and the tier-1 hook share the baseline/severity machinery with the graph
auditor.  Intentional exceptions live in ``tools/seam_allowlist.json``
as ``"<repo-relative path>::<symbol>"`` entries.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Iterable, List, Optional, Set, Tuple

from deepspeed_tpu.analysis.report import Finding

# The one file allowed to spell the gated APIs — plus this linter,
# which must name them to ban them.
SEAM_FILE = os.path.join("deepspeed_tpu", "utils", "jax_compat.py")
_EXEMPT_FILES = frozenset({
    SEAM_FILE.replace(os.sep, "/"),
    "deepspeed_tpu/analysis/seam.py",
})

# Module prefixes that only exist (or only behave) on one side of the
# 0.4.x / current-jax split, plus everything under jax._src (private —
# any release may move it).
GATED_MODULE_PREFIXES = ("jax.experimental.shard_map", "jax._src")

# Attribute chains gated by version: `jax.shard_map` (current-only),
# `jax.memory` (current-only), `jax.sharding.get_abstract_mesh`
# (current-only).
GATED_ATTR_CHAINS = frozenset({
    "jax.shard_map", "jax.memory", "jax.sharding.get_abstract_mesh",
})

# Bare names gated by version wherever they appear (pallas pre-/post-
# stabilization compiler-params class).
GATED_NAMES = frozenset({"TPUCompilerParams"})

# `from jax import <name>` / `from jax.sharding import <name>` forms of
# the gated attribute chains.
_GATED_FROM_IMPORTS = {
    "jax": {"shard_map", "memory"},
    "jax.sharding": {"get_abstract_mesh"},
    "jax.experimental": {"shard_map"},
}

_SCAN_DIRS = ("deepspeed_tpu", "tools")
_SCAN_FILES = ("bench.py", "__graft_entry__.py")


def _dotted(node: ast.AST) -> Optional[str]:
    """`jax.sharding.get_abstract_mesh` Attribute chain → dotted string
    (None when the chain does not bottom out in a Name)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _violations_in_tree(tree: ast.AST) -> List[Tuple[int, str, str]]:
    """→ [(lineno, symbol, how)] for every gated-symbol use."""
    out: List[Tuple[int, str, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if any(alias.name == p or alias.name.startswith(p + ".")
                       for p in GATED_MODULE_PREFIXES):
                    out.append((node.lineno, alias.name, "import"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level:   # relative import — never a jax module
                continue
            if any(mod == p or mod.startswith(p + ".")
                   for p in GATED_MODULE_PREFIXES):
                for alias in node.names:
                    out.append((node.lineno, f"{mod}.{alias.name}",
                                "import-from"))
                continue
            gated = _GATED_FROM_IMPORTS.get(mod, ())
            for alias in node.names:
                if alias.name in gated:
                    out.append((node.lineno, f"{mod}.{alias.name}",
                                "import-from"))
                if alias.name in GATED_NAMES:
                    out.append((node.lineno, alias.name, "import-from"))
        elif isinstance(node, ast.Attribute):
            chain = _dotted(node)
            if chain is None:
                continue
            if chain in GATED_ATTR_CHAINS or any(
                    chain == p or chain.startswith(p + ".")
                    for p in GATED_MODULE_PREFIXES):
                out.append((node.lineno, chain, "attribute"))
            elif node.attr in GATED_NAMES:
                out.append((node.lineno, node.attr, "attribute"))
        elif isinstance(node, ast.Constant):
            # getattr(pltpu, "TPUCompilerParams") and friends
            if isinstance(node.value, str) and node.value in GATED_NAMES:
                out.append((node.lineno, node.value, "string"))
    # one entry per (line, symbol)
    return sorted(set(out))


def lint_source(source: str, rel_path: str,
                allow: Iterable[str] = ()) -> List[Finding]:
    """Lint one file's source text; ``rel_path`` keys the allowlist."""
    rel = rel_path.replace(os.sep, "/")
    if rel in _EXEMPT_FILES:
        return []
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(kind="seam_violation", severity="warning",
                        message=f"unparseable python: {e}",
                        where=rel, detail={"key": "syntax"})]
    allow_set = set(allow)
    findings = []
    for lineno, symbol, how in _violations_in_tree(tree):
        if f"{rel}::{symbol}" in allow_set:
            continue
        findings.append(Finding(
            kind="seam_violation", severity="high",
            message=f"version-gated jax symbol `{symbol}` used directly "
                    f"({how}) — route it through utils/jax_compat.py, "
                    "the repo's only jax-version seam",
            where=f"{rel}:{lineno}",
            detail={"key": symbol, "how": how}))
    return findings


def default_allowlist_path(repo_root: str) -> str:
    return os.path.join(repo_root, "tools", "seam_allowlist.json")


def load_allowlist(path: str) -> Set[str]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return {str(e) for e in json.load(f).get("allow", [])}
    except FileNotFoundError:
        return set()


def lint_repo(repo_root: str,
              allow: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint the production tree.  ``allow`` defaults to the checked-in
    ``tools/seam_allowlist.json``."""
    if allow is None:
        allow = load_allowlist(default_allowlist_path(repo_root))
    targets: List[str] = []
    for d in _SCAN_DIRS:
        base = os.path.join(repo_root, d)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in filenames:
                if fn.endswith(".py"):
                    targets.append(os.path.join(dirpath, fn))
    for fn in _SCAN_FILES:
        p = os.path.join(repo_root, fn)
        if os.path.exists(p):
            targets.append(p)
    findings: List[Finding] = []
    for path in sorted(targets):
        rel = os.path.relpath(path, repo_root)
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        findings.extend(lint_source(src, rel, allow=allow))
    return findings
