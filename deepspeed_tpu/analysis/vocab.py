"""Shared frozen-vocabulary checker.

Every telemetry/bench/audit vocabulary in this repo follows the same
contract: a module-level tuple is FROZEN, a lint compares it against an
expected list checked into the lint tool, every name must appear
(backticked) in the owning doc, and any bench keys must literally be
emitted by their bench source.  ``tools/telemetry_check.py`` grew four
copy-pasted implementations of that contract; this module is the single
engine both it and ``tools/graft_lint.py`` drive — adding a vocabulary
is ONE :class:`VocabSpec` registration, not another bespoke check
function.

Pure stdlib, no jax: importable from any tool or test.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple


@dataclass
class VocabSpec:
    """One frozen vocabulary and everywhere it must agree.

    ``name``           — label used in error messages.
    ``expected``       — the frozen list the lint tool pins.
    ``actual``         — optional thunk returning the module's live list
                         (import deferred to check time); drift in either
                         direction is an error.
    ``docs_path``      — file every documented name must appear in.
    ``doc_names``      — names to look for in the docs (defaults to
                         ``expected``); matched as `` `name` `` unless a
                         ``doc_normalize`` maps a concrete name onto its
                         documented wildcard row first.
    ``doc_normalize``  — e.g. ``router_routed_r3_total →
                         router_routed_r*_total``.
    ``source_keys``    — ``[(path, keys)]``: each key must appear as a
                         ``"key"`` string literal in that source file
                         (the bench-row emission contract).
    """
    name: str
    expected: Sequence[str] = ()
    actual: Optional[Callable[[], Sequence[str]]] = None
    docs_path: Optional[str] = None
    doc_names: Optional[Sequence[str]] = None
    doc_normalize: Optional[Callable[[str], str]] = None
    source_keys: Sequence[Tuple[str, Sequence[str]]] = field(
        default_factory=list)

    def check(self) -> List[str]:
        errors: List[str] = []
        live = list(self.expected)
        if self.actual is not None:
            try:
                live = list(self.actual())
            except Exception as e:   # import failure is a lint failure
                return [f"{self.name}: cannot load live vocabulary: {e}"]
            if sorted(live) != sorted(self.expected):
                errors.append(
                    f"{self.name} drifted from the frozen list: "
                    f"extra={sorted(set(live) - set(self.expected))}, "
                    f"missing={sorted(set(self.expected) - set(live))} — "
                    "update the frozen list and the docs together")
        if self.docs_path is not None:
            try:
                with open(self.docs_path, "r", encoding="utf-8") as f:
                    docs = f.read()
            except OSError as e:
                errors.append(f"{self.name}: cannot read "
                              f"{self.docs_path}: {e}")
                docs = None
            if docs is not None:
                import os
                base = os.path.basename(self.docs_path)
                for nm in (self.doc_names if self.doc_names is not None
                           else live):
                    doc_nm = (self.doc_normalize(nm) if self.doc_normalize
                              else nm)
                    if f"`{nm}`" not in docs and f"`{doc_nm}`" not in docs:
                        errors.append(f"{self.name}: {nm!r} not "
                                      f"documented in {base}")
        for path, keys in self.source_keys:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
            except OSError as e:
                errors.append(f"{self.name}: cannot read {path}: {e}")
                continue
            import os
            base = os.path.basename(path)
            for key in keys:
                if f'"{key}"' not in src and f"'{key}'" not in src:
                    errors.append(
                        f"{self.name}: key {key!r} not emitted by {base} "
                        "(frozen key list drifted)")
        return errors


def check_all(specs: Sequence[VocabSpec]) -> List[str]:
    errors: List[str] = []
    for spec in specs:
        errors.extend(spec.check())
    return errors
